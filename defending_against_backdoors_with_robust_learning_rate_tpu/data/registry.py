"""Dataset registry: fmnist / cifar10 / fedemnist / synthetic.

Reference: `get_datasets` (src/utils.py:95-124) loads FashionMNIST/CIFAR-10 via
torchvision (with fixed normalization constants) and Fed-EMNIST from
pre-serialized `.pt` files. This environment has no torchvision and zero
egress, so we read the standard on-disk formats directly when present
(torchvision's own raw layout for FMNIST, the python pickle batches for
CIFAR-10, `torch.load` for Fed-EMNIST) and otherwise fall back to a
deterministic, class-structured **synthetic** dataset with identical shapes —
separable enough that FL training, backdoor attack and RLR-defense dynamics
are all exercised end-to-end.

Images are kept as *raw* pixels (uint8 for fmnist/cifar10, pre-normalized
float32 for fedemnist) because poisoning stamps raw pixels before
normalization (src/utils.py:169-177; SURVEY.md 2.3.4). Normalization happens
on-device in the train/eval step using the reference's constants
(src/utils.py:101, src/utils.py:113-116).
"""

from __future__ import annotations

import dataclasses
import gzip
import os
import pickle
import struct
from typing import Optional, Tuple

import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.data.arrays import (
    AgentShards)

# reference normalization constants (src/utils.py:101, 113-116)
NORM_STATS = {
    "fmnist": ((0.2860,), (0.3530,)),
    "cifar10": ((0.4914, 0.4822, 0.4465), (0.2023, 0.1994, 0.2010)),
    "fedemnist": ((0.0,), (1.0,)),   # inputs already normalized in the .pt files
    "synthetic": ((0.5,), (0.5,)),
}


@dataclasses.dataclass
class RawDataset:
    images: np.ndarray     # [N, H, W, C] raw pixels
    labels: np.ndarray     # [N] int32
    name: str

    def __len__(self) -> int:
        return len(self.labels)


@dataclasses.dataclass
class FederatedData:
    """Everything the FL loop needs, fully materialized as numpy arrays."""
    train: "AgentShards"                 # poisoned agent-stacked train shards
    val_images: np.ndarray               # [Nv, H, W, C] clean validation
    val_labels: np.ndarray               # [Nv]
    pval_images: np.ndarray              # poisoned validation (backdoor metric)
    pval_labels: np.ndarray
    mean: np.ndarray                     # [C] normalization mean (of x/255)
    std: np.ndarray                      # [C]
    raw_is_normalized: bool              # fedemnist: skip /255 + mean/std
    synthetic: bool = False


def _norm_arrays(data: str) -> Tuple[np.ndarray, np.ndarray]:
    mean, std = NORM_STATS[data]
    return (np.asarray(mean, np.float32), np.asarray(std, np.float32))


@dataclasses.dataclass
class CohortData(FederatedData):
    """FederatedData for the cohort-sampled population path (ISSUE 7).

    ``train`` holds a ZERO-client AgentShards whose arrays carry only the
    *shapes and dtypes* one cohort row has ([0, max_n, H, W, C] — zero
    bytes): everything downstream that reads shard geometry (model init,
    AOT avals, the host-mode byte check) works unchanged, while the
    actual population lives in the memory-mapped client bank. Cohort rows
    are materialized per round by ``gather_cohort`` — base-dataset fancy
    indexing through the bank's offset store, with corrupt clients'
    rows poisoned by the same per-client routine the dense build uses
    (attack/poison.poison_client_row: bitwise-identical shards)."""
    bank: object = None                  # data/bank.ClientBank
    base_images: np.ndarray = None       # [N, H, W, C] raw pixels
    base_labels: np.ndarray = None       # [N] int32
    max_n: int = 0                       # padded cohort-row length
    cfg: object = None                   # poison + population params
    _stamps: dict = dataclasses.field(default_factory=dict)

    def gather_cohort(self, ids) -> Tuple[np.ndarray, np.ndarray,
                                          np.ndarray]:
        """([m, max_n, ...], [m, max_n], [m]) padded stacks for the
        sampled cohort — O(cohort) work and memory, population-blind."""
        from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
            poison)
        imgs, lbls, sizes = self.bank.gather(ids, self.base_images,
                                             self.base_labels, self.max_n)
        cfg = self.cfg
        if cfg.num_corrupt > 0 and cfg.poison_frac > 0:
            for j, cid in enumerate(np.asarray(ids)):
                cid = int(cid)
                if cid >= cfg.num_corrupt:
                    continue
                stamp = self._stamps.get(cid)
                if stamp is None:
                    # attack-registry stamp source (attack/registry.py):
                    # static = the legacy per-agent stamp, dba = the
                    # agent's shard of the full pattern — same source as
                    # the dense build, so rows stay bitwise-identical
                    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
                        registry as attack_registry)
                    stamp = attack_registry.stamp_for_agent(cfg, cid)
                    self._stamps[cid] = stamp
                poison.poison_client_row(imgs[j], lbls[j], int(sizes[j]),
                                         cid, cfg, stamp=stamp)
        return imgs, lbls, sizes


def resolve_bank_root(cfg) -> str:
    """The client-bank ROOT this config would use: --bank_dir wins;
    otherwise <data_dir>/client_banks when data_dir exists (persistent
    across runs, gitignored), else under log_dir (always writable).
    Shared with the chaos bank_corrupt drill (service/driver.py), which
    must search the same root the engine will open."""
    if cfg.bank_dir:
        return cfg.bank_dir
    base = (cfg.data_dir if os.path.isdir(cfg.data_dir) else cfg.log_dir)
    return os.path.join(base, "client_banks")


def resolve_bank_dir(cfg, key: str) -> str:
    if cfg.bank_dir:
        return cfg.bank_dir
    return os.path.join(resolve_bank_root(cfg), f"{cfg.data}-{key[:12]}")


def get_cohort_data(cfg) -> CohortData:
    """Build the cohort-sampled data environment: base dataset + client
    bank (opened when a matching build exists, partitioned once
    otherwise) + the usual eval sets. Host memory is O(base dataset), not
    O(population) — the bank is offset-indexed and memory-mapped."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack.poison import (
        build_poisoned_val)
    from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
        bank as bank_mod)

    train, val, synthetic = get_datasets(cfg)
    if isinstance(train, list):
        raise ValueError(
            f"cohort-sampled mode needs a single base dataset to index; "
            f"{cfg.data!r} loads pre-split per-user shards — run it "
            f"through the host-sampled path (--cohort_sampled off)")
    key = bank_mod.bank_key(
        train.labels, population=cfg.num_agents,
        partitioner=cfg.partitioner,
        samples_per_client=bank_mod.resolve_samples_per_client(
            cfg.samples_per_client, len(train.labels), cfg.num_agents),
        dirichlet_alpha=cfg.dirichlet_alpha,
        classes_per_client=cfg.classes_per_client, seed=cfg.seed,
        n_classes=cfg.n_classes)
    bank, built = bank_mod.get_or_build(
        resolve_bank_dir(cfg, key), train.labels,
        population=cfg.num_agents, partitioner=cfg.partitioner,
        samples_per_client=cfg.samples_per_client,
        dirichlet_alpha=cfg.dirichlet_alpha,
        classes_per_client=cfg.classes_per_client, seed=cfg.seed,
        n_classes=cfg.n_classes, shard_clients=cfg.bank_shard_clients,
        key=key, verify=cfg.bank_verify,
        workers=cfg.bank_build_workers)
    if not built:
        print(f"[bank] opened existing {cfg.partitioner} bank "
              f"({bank.population:,} clients) at {bank.dir}")
    max_n = bank.padded_max_n(cfg.bs)
    shard_shim = AgentShards(
        images=np.zeros((0, max_n) + train.images.shape[1:],
                        dtype=train.images.dtype),
        labels=np.zeros((0, max_n), dtype=np.int32),
        sizes=np.zeros((0,), dtype=np.int32))
    pv_imgs, pv_lbls = build_poisoned_val(val.images, val.labels, cfg)
    mean, std = _norm_arrays(cfg.data)
    return CohortData(
        train=shard_shim,
        val_images=val.images, val_labels=val.labels,
        pval_images=pv_imgs, pval_labels=pv_lbls,
        mean=mean, std=std,
        raw_is_normalized=(cfg.data == "fedemnist"),
        synthetic=synthetic,
        bank=bank, base_images=train.images, base_labels=train.labels,
        max_n=max_n, cfg=cfg)


# ---------------------------------------------------------------- loaders ---

def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX file (optionally gzipped) — the raw MNIST-family format.
    numpy frombuffer is zero-copy over the payload."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        buf = f.read()
    zero, dtype_code, ndim = struct.unpack(">HBB", buf[:4])
    dims = struct.unpack(">" + "I" * ndim, buf[4:4 + 4 * ndim])
    return np.frombuffer(buf, dtype=np.uint8,
                         offset=4 + 4 * ndim).reshape(dims)


def _find(path_candidates) -> Optional[str]:
    for p in path_candidates:
        if os.path.exists(p):
            return p
    return None


def _load_fmnist(data_dir: str) -> Optional[Tuple[RawDataset, RawDataset]]:
    base_candidates = [
        os.path.join(data_dir, "FashionMNIST", "raw"),
        os.path.join(data_dir, "fmnist"),
        data_dir,
    ]
    out = []
    for split in ("train", "t10k"):
        img = lbl = None
        for base in base_candidates:
            img = _find([os.path.join(base, f"{split}-images-idx3-ubyte{s}")
                         for s in ("", ".gz")])
            lbl = _find([os.path.join(base, f"{split}-labels-idx1-ubyte{s}")
                         for s in ("", ".gz")])
            if img and lbl:
                break
        if not (img and lbl):
            return None
        images = _read_idx(img)[..., None]           # [N, 28, 28, 1] uint8
        labels = _read_idx(lbl).astype(np.int32)
        out.append(RawDataset(images, labels, "fmnist"))
    return out[0], out[1]


def _load_cifar10(data_dir: str) -> Optional[Tuple[RawDataset, RawDataset]]:
    base = _find([os.path.join(data_dir, "cifar-10-batches-py"),
                  os.path.join(data_dir, "cifar10", "cifar-10-batches-py")])
    if base is None:
        return None

    def load_batch(name):
        with open(os.path.join(base, name), "rb") as f:
            d = pickle.load(f, encoding="bytes")
        imgs = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        return imgs.astype(np.uint8), np.asarray(d[b"labels"], np.int32)

    tr_i, tr_l = zip(*[load_batch(f"data_batch_{i}") for i in range(1, 6)], strict=True)
    te_i, te_l = load_batch("test_batch")
    return (RawDataset(np.concatenate(tr_i), np.concatenate(tr_l), "cifar10"),
            RawDataset(te_i, te_l, "cifar10"))


def _to_numpy_pt(obj):
    """Best-effort extraction of (inputs, targets) from Fed-EMNIST .pt objects
    (the reference pickles H5Dataset-like objects, src/utils.py:11-36)."""
    import torch
    if isinstance(obj, dict) and "pixels" in obj:
        x, y = obj["pixels"], obj["label"]
    elif hasattr(obj, "inputs") and hasattr(obj, "targets"):
        x, y = obj.inputs, obj.targets
    elif isinstance(obj, (tuple, list)) and len(obj) == 2:
        x, y = obj
    else:
        raise ValueError(f"unrecognized .pt payload: {type(obj)}")
    x = x.numpy() if isinstance(x, torch.Tensor) else np.asarray(x)
    y = y.numpy() if isinstance(y, torch.Tensor) else np.asarray(y)
    x = np.asarray(x, np.float32)
    if x.ndim == 4 and x.shape[1] == 1:          # NCHW -> NHWC
        x = x.transpose(0, 2, 3, 1)
    elif x.ndim == 3:
        x = x[..., None]
    return x, y.astype(np.int32)


def _load_fedemnist(data_dir: str):
    """Returns (per_user_shards | None, val RawDataset) or None.

    Layout mirrors the reference (src/utils.py:106-109, src/agent.py:17):
      Fed_EMNIST/fed_emnist_all_valset.pt
      Fed_EMNIST/user_trainsets/user_{id}_trainset.pt
    """
    base = _find([os.path.join(data_dir, "Fed_EMNIST"),
                  os.path.join(data_dir, "fedemnist")])
    if base is None:
        return None
    import torch
    val_path = _find([os.path.join(base, "fed_emnist_all_valset.pt")])
    if val_path is None:
        return None
    vx, vy = _to_numpy_pt(torch.load(val_path, weights_only=False))
    users_dir = os.path.join(base, "user_trainsets")
    shards = []
    uid = 0
    while os.path.exists(os.path.join(users_dir, f"user_{uid}_trainset.pt")):
        ux, uy = _to_numpy_pt(torch.load(
            os.path.join(users_dir, f"user_{uid}_trainset.pt"),
            weights_only=False))
        shards.append((ux, uy))
        uid += 1
    return shards, RawDataset(vx, vy, "fedemnist")


# ------------------------------------------------------------- synthetic ---

def make_synthetic(name: str, shape: Tuple[int, int, int], n_train: int,
                   n_val: int, seed: int, n_classes: int = 10,
                   float_normalized: bool = False, hardness: float = 0.0
                   ) -> Tuple[RawDataset, RawDataset]:
    """Deterministic class-structured data: each class is a fixed random
    prototype image plus pixel noise — linearly separable, so a small CNN
    learns it in a few steps and backdoor dynamics are observable.

    `hardness` in [0, 1] controls task difficulty (VERDICT r1 #4: at 0 the
    task saturates val_acc=1.0 within ~20 rounds, which makes accuracy
    curves vacuous). At hardness h:
      - each sample's prototype is circularly shifted by a per-sample
        random offset up to round(6h) pixels per axis — template matching
        stops working and the CNN has to learn shift-tolerant features,
        which is what makes accuracy climb over tens of rounds instead of
        a few steps (a fixed template is linearly separable at any noise
        level, so noise alone cannot slow learning down),
      - each prototype is pulled toward a single shared background image
        (class signal shrinks by 1-0.85h — classes overlap),
      - pixel noise grows from sigma=0.10 to 0.10+0.35h (SNR drops),
      - a fraction 0.1h of TRAIN labels is resampled uniformly (irreducible
        label noise; validation stays clean so val_acc is interpretable).
    The trojan patterns are stamped AFTER generation on raw pixels
    (attack/poison.py), so the trigger stays at its fixed location — shifts
    make the task harder without touching the backdoor geometry.
    hardness=0 reproduces the round-1 data bit-for-bit."""
    rng = np.random.default_rng(seed)
    h, w, c = shape
    protos = rng.uniform(0.15, 0.85, size=(n_classes, h, w, c))
    if hardness > 0.0:
        shared = rng.uniform(0.15, 0.85, size=(h, w, c))
        mix = 0.85 * float(hardness)
        protos = (1.0 - mix) * protos + mix * shared
    sigma = 0.10 + 0.35 * float(hardness)
    label_noise = 0.1 * float(hardness)
    max_shift = int(round(6.0 * float(hardness)))

    def gen(n, split_seed, noisy_labels):
        r = np.random.default_rng(seed * 1000003 + split_seed)
        labels = r.integers(0, n_classes, size=n).astype(np.int32)
        x = protos[labels]
        if max_shift > 0:
            dy = r.integers(-max_shift, max_shift + 1, size=n)
            dx = r.integers(-max_shift, max_shift + 1, size=n)
            ry = (np.arange(h)[None, :] - dy[:, None]) % h        # [n, h]
            rx = (np.arange(w)[None, :] - dx[:, None]) % w        # [n, w]
            x = x[np.arange(n)[:, None, None],
                  ry[:, :, None], rx[:, None, :]]                 # [n,h,w,c]
        noise = r.normal(0.0, sigma, size=(n, h, w, c))
        x = np.clip(x + noise, 0.0, 1.0)
        if noisy_labels and label_noise > 0.0:
            flip = r.random(n) < label_noise
            labels = np.where(
                flip, r.integers(0, n_classes, size=n).astype(np.int32),
                labels)
        if float_normalized:
            return x.astype(np.float32), labels
        return (x * 255.0).astype(np.uint8), labels

    tx, ty = gen(n_train, 1, True)
    vx, vy = gen(n_val, 2, False)
    return RawDataset(tx, ty, name), RawDataset(vx, vy, name)


# -------------------------------------------------------------- registry ---

def get_datasets(cfg) -> Tuple[object, RawDataset, bool]:
    """Return (train, val, synthetic?) where train is a RawDataset, or for
    fedemnist a list of per-user (images, labels) shards.

    Mirrors src/utils.py:95-124 with on-disk formats replacing torchvision.
    """
    if cfg.data == "fmnist":
        got = _load_fmnist(cfg.data_dir)
        if got is not None:
            return got[0], got[1], False
        tr, va = make_synthetic("fmnist", (28, 28, 1), cfg.synth_train_size,
                                cfg.synth_val_size, cfg.seed,
                                hardness=cfg.synth_hardness)
        return tr, va, True
    if cfg.data == "cifar10":
        got = _load_cifar10(cfg.data_dir)
        if got is not None:
            return got[0], got[1], False
        tr, va = make_synthetic("cifar10", (32, 32, 3), cfg.synth_train_size,
                                cfg.synth_val_size, cfg.seed,
                                hardness=cfg.synth_hardness)
        return tr, va, True
    if cfg.data == "fedemnist":
        got = _load_fedemnist(cfg.data_dir)
        if got is not None:
            shards, val = got
            if len(shards) < cfg.num_agents:
                raise ValueError(
                    f"fedemnist: found only {len(shards)} contiguous "
                    f"user_<id>_trainset.pt shards under {cfg.data_dir!r} but "
                    f"--num_agents={cfg.num_agents}; refusing to train with "
                    f"out-of-range agent ids")
            return shards[:cfg.num_agents], val, False
        # synthetic non-IID per-user shards, uneven sizes, float-normalized
        rng = np.random.default_rng(cfg.seed + 7)
        tr, va = make_synthetic("fedemnist", (28, 28, 1),
                                cfg.synth_train_size, cfg.synth_val_size,
                                cfg.seed, float_normalized=True,
                                hardness=cfg.synth_hardness)
        sizes = rng.integers(max(8, cfg.bs // 4),
                             max(16, cfg.bs), size=cfg.num_agents)
        order = rng.permutation(len(tr.images))
        shards, pos = [], 0
        for a in range(cfg.num_agents):
            n = int(min(sizes[a], len(order) - pos)) or 8
            idx = order[pos:pos + n] if pos + n <= len(order) else \
                rng.choice(len(tr.images), size=n)
            pos += n
            shards.append((tr.images[idx], tr.labels[idx]))
        return shards, va, True
    if cfg.data == "synthetic":
        tr, va = make_synthetic("synthetic", cfg.image_shape,
                                cfg.synth_train_size, cfg.synth_val_size,
                                cfg.seed, hardness=cfg.synth_hardness)
        return tr, va, True
    raise ValueError(f"unknown dataset {cfg.data!r}")


def get_federated_data(cfg) -> FederatedData:
    """Build the complete device-ready federated dataset:
    partition -> stack -> poison corrupt agents -> poisoned val set.

    Mirrors the setup phase of src/federated.py:33-56.
    """
    # partition + pack go through the native host runtime when available
    # (native/fl_host.cc via data/native.py), numpy otherwise — identical
    # outputs either way (tests/test_native.py)
    from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
        native)
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack.poison import (
        poison_agent_shards, build_poisoned_val)

    train, val, synthetic = get_datasets(cfg)

    # pad shards to a multiple of the batch size so the client's
    # [n_batches, bs] reshape is exact (fl/client.py)
    if isinstance(train, list):     # fedemnist-style per-user shards
        shards = native.pack_uneven([s[0] for s in train],
                                    [s[1] for s in train],
                                    pad_multiple=cfg.bs)
    else:
        groups = native.distribute_data(train.labels, cfg.num_agents,
                                        n_classes=cfg.n_classes)
        shards = native.pack_shards(train.images, train.labels, groups,
                                    cfg.num_agents, pad_multiple=cfg.bs)

    imgs, lbls, pmask = poison_agent_shards(shards.images, shards.labels,
                                            shards.sizes, cfg)
    shards.images, shards.labels, shards.poison_mask = imgs, lbls, pmask

    pv_imgs, pv_lbls = build_poisoned_val(val.images, val.labels, cfg)
    mean, std = _norm_arrays(cfg.data)
    return FederatedData(
        train=shards,
        val_images=val.images, val_labels=val.labels,
        pval_images=pv_imgs, pval_labels=pv_lbls,
        mean=mean, std=std,
        raw_is_normalized=(cfg.data == "fedemnist"),
        synthetic=synthetic,
    )
