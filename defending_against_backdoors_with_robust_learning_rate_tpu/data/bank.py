"""Sharded, memory-mapped client bank — the million-client population store.

The dense layout (data/arrays.AgentShards) materializes every client's
shard as one [K, max_n, ...] host/HBM array, welding population size to
per-round cohort size: a 1M-client population would need terabytes before
the first round runs. FedJAX (arXiv:2108.02117) identifies the right
simulator primitives instead — client *sampling* plus for-each-client
batching — which only ever touch the sampled cohort. This module is the
storage half of that split:

- **partition once, store offsets**: the population is partitioned into
  per-client *index lists* over the base dataset (the samples themselves
  are never duplicated). The flat int64 index stream is written to sharded
  ``indices-<i>.bin`` files (``shard_clients`` clients per file) plus a
  memory-mapped ``offsets.npy`` [K+1] — an offset-indexed store whose
  resident set is O(touched cohort), not O(population).
- **partitioners that scale**: ``dirichlet`` and ``pathological`` draw
  each client's shard as a pure per-client function of ``(seed, client)``
  (generated in fixed 4096-client blocks, vectorized numpy), so a 1M-client
  partition streams through constant memory and its content is independent
  of the shard layout, the build order, and the building process —
  fingerprint-stable by construction (``content_sha``). ``label_shards``
  wraps the paper's reference partitioner (data/partition.py) for
  populations small enough to partition exactly; its bank rows are
  bitwise-identical to the dense ``stack_agent_shards`` layout.
- **cohort gather**: ``ClientBank.gather`` materializes only the m sampled
  clients' rows as a padded [m, max_n, ...] stack (the static shape one
  compiled round program consumes forever), fancy-indexing the base
  dataset through the memmapped index lists.

Planet-scale additions (ISSUE 17):

- **parallel sharded build**: ``build_bank(..., workers=N)`` splits the
  shard range across N spawn subprocesses. Content is already a pure
  per-client function of ``(seed, client)`` generated on a fixed global
  block grid, so each worker writes its contiguous run of whole shard
  files (plus sha256 sidecars) into the shared tmp dir and the parent
  merges offsets, streams the shard files in shard order through one
  sha256 (bitwise the serial byte stream) and publishes with the same
  atomic rename. ``workers`` is an IO/throughput knob like
  ``shard_clients``: same bank_key, same content_sha, same bank.
- **streamed row gathers**: ``gather`` preads exactly the touched rows'
  byte ranges from the shard files instead of accumulating memmap pages,
  keeping the resident set O(cohort) at 10M+ clients (the memmap path
  stays available as ``streamed=False`` for the bitwise-equality tests).

This module is numpy-only on purpose: bank builds run in subprocesses and
CI jobs that never initialize a jax backend, and the determinism tests
compare content hashes across processes. (The obs import below is
stdlib-only and no-ops unless a service ledger/exporter is installed.)
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import multiprocessing
import os
import shutil
from typing import Dict, List, Optional, Tuple

import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.data.arrays import (
    padded_max_n)
from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    events as obs_events)

BANK_VERSION = 1
META_NAME = "meta.json"
OFFSETS_NAME = "offsets.npy"
DIGEST_SUFFIX = ".sha256"

# fixed generation block for the per-client-seeded partitioners: content is
# a function of (seed, block index) with BUILD_BLOCK a named constant, so
# the partition never depends on `shard_clients` (an IO layout knob) or on
# how many clients one build call handles
BUILD_BLOCK = 4096

PARTITIONERS = ("label_shards", "dirichlet", "pathological")

# samples_per_client auto-resolution bounds (resolve_samples_per_client)
MIN_SAMPLES_PER_CLIENT = 16
MAX_SAMPLES_PER_CLIENT = 4096


def resolve_samples_per_client(requested: int, n_samples: int,
                               population: int) -> int:
    """``--samples_per_client 0`` = auto: an even split of the base dataset
    clamped to [16, 4096] — at 1M clients over a 60k-sample dataset every
    client still holds a trainable (16-sample) shard drawn with
    replacement."""
    if requested > 0:
        return requested
    return int(np.clip(n_samples // max(population, 1),
                       MIN_SAMPLES_PER_CLIENT, MAX_SAMPLES_PER_CLIENT))


def bank_key(labels: np.ndarray, *, population: int, partitioner: str,
             samples_per_client: int, dirichlet_alpha: float,
             classes_per_client: int, seed: int, n_classes: int) -> str:
    """Input fingerprint deciding bank reuse: dataset content (labels) +
    every partition-shaping parameter. The shard layout
    (``shard_clients``) and the gather-time padding (``pad_multiple`` —
    applied by ``padded_max_n`` when rows are materialized, never at
    build) are deliberately NOT part of the key: neither can change the
    stored content, so e.g. a batch-size change reuses the bank."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(labels, dtype=np.int64).tobytes())
    h.update(json.dumps({
        "version": BANK_VERSION, "population": population,
        "partitioner": partitioner,
        "samples_per_client": samples_per_client,
        "dirichlet_alpha": dirichlet_alpha,
        "classes_per_client": classes_per_client,
        "seed": seed, "n_classes": n_classes,
    }, sort_keys=True).encode())
    return h.hexdigest()[:20]


def _class_pools(labels: np.ndarray, n_classes: int) -> List[np.ndarray]:
    return [np.nonzero(labels == c)[0].astype(np.int64)
            for c in range(n_classes)]


def _block_rng(seed: int, block: int) -> np.random.Generator:
    # SeedSequence([...]) keys the stream by (constant, seed, block): two
    # builds of the same config produce identical blocks in any order
    return np.random.default_rng([0xBA4C, seed, block])


def _draw_block(rng: np.random.Generator, counts: np.ndarray,
                pools: List[np.ndarray]) -> np.ndarray:
    """[B, spc] sample indices from per-(client, class) `counts` [B, C]
    (rows sum to spc): class-major draws scattered back to clients.

    Within a client the row is ordered class-major then draw-order — a
    deterministic function of the rng stream alone (np.argsort stable)."""
    B = counts.shape[0]
    owners, vals = [], []
    for c, pool in enumerate(pools):
        tot = int(counts[:, c].sum())
        if tot == 0:
            continue
        vals.append(pool[rng.integers(0, len(pool), size=tot)])
        owners.append(np.repeat(np.arange(B), counts[:, c]))
    owner = np.concatenate(owners)
    order = np.argsort(owner, kind="stable")
    return np.concatenate(vals)[order].reshape(B, -1)


def _dirichlet_block(rng: np.random.Generator, block_size: int,
                     pools: List[np.ndarray], spc: int,
                     alpha: float) -> np.ndarray:
    """Per-client Dir(alpha) class mixtures -> multinomial counts -> index
    draws. Classes with empty pools get zero mass (a dataset missing a
    class cannot be sampled from)."""
    C = len(pools)
    nonempty = np.array([len(p) > 0 for p in pools])
    g = rng.standard_gamma(alpha, size=(block_size, C))
    g = np.where(nonempty[None, :], np.maximum(g, 1e-30), 0.0)
    p = g / g.sum(axis=1, keepdims=True)
    counts = rng.multinomial(spc, p)
    return _draw_block(rng, counts, pools)


def _pathological_block(rng: np.random.Generator, block_size: int,
                        pools: List[np.ndarray], spc: int,
                        classes_per_client: int) -> np.ndarray:
    """The classic pathological non-IID split: each client sees only
    `classes_per_client` distinct (nonempty) classes, samples split evenly
    (remainder to the client's first picks)."""
    C = len(pools)
    nonempty = np.nonzero([len(p) > 0 for p in pools])[0]
    cpc = min(classes_per_client, len(nonempty))
    scores = rng.random((block_size, len(nonempty)))
    picks = nonempty[np.argsort(scores, axis=1, kind="stable")[:, :cpc]]
    base, rem = divmod(spc, cpc)
    counts = np.zeros((block_size, C), dtype=np.int64)
    rows = np.arange(block_size)[:, None]
    np.add.at(counts, (np.broadcast_to(rows, picks.shape), picks), base)
    if rem:
        np.add.at(counts, (np.broadcast_to(rows, picks[:, :rem].shape),
                           picks[:, :rem]), 1)
    return _draw_block(rng, counts, pools)


def _iter_client_lists(labels: np.ndarray, *, population: int,
                       partitioner: str, spc: int, alpha: float,
                       classes_per_client: int, seed: int, n_classes: int,
                       lo: int = 0, hi: Optional[int] = None):
    """Yield (first_client_id, [per-client int64 index arrays]) in client
    order, in bounded chunks — the streaming source every build consumes.

    ``[lo, hi)`` restricts the yield to a client range WITHOUT changing
    any client's content: blocks are always generated on the global
    BUILD_BLOCK grid (rng keyed by the global block index, block size
    taken from the population), then sliced to the range — the invariant
    the parallel build rests on."""
    hi = population if hi is None else hi
    grid_lo = (lo // BUILD_BLOCK) * BUILD_BLOCK
    if partitioner == "label_shards":
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            native)
        groups = native.distribute_data(labels, population,
                                        n_classes=n_classes)
        for start in range(grid_lo, hi, BUILD_BLOCK):
            stop = min(start + BUILD_BLOCK, population)
            a0, a1 = max(start, lo), min(stop, hi)
            yield a0, [np.asarray(list(groups.get(a, ())), dtype=np.int64)
                       for a in range(a0, a1)]
        return
    if partitioner not in PARTITIONERS:
        raise ValueError(f"partitioner must be one of {PARTITIONERS}, "
                         f"got {partitioner!r}")
    pools = _class_pools(labels, n_classes)
    if not any(len(p) for p in pools):
        raise ValueError("cannot partition an empty dataset")
    for start in range(grid_lo, hi, BUILD_BLOCK):
        stop = min(start + BUILD_BLOCK, population)
        rng = _block_rng(seed, start // BUILD_BLOCK)
        if partitioner == "dirichlet":
            block = _dirichlet_block(rng, stop - start, pools, spc, alpha)
        else:
            block = _pathological_block(rng, stop - start, pools, spc,
                                        classes_per_client)
        a0, a1 = max(start, lo), min(stop, hi)
        yield a0, list(block[a0 - start:a1 - start])


@dataclasses.dataclass
class ClientBank:
    """An opened bank: memmapped offsets + lazily-memmapped index shards.

    ``offsets`` is np.load(mmap_mode="r") — O(population) bytes stay on
    disk; a cohort gather touches m+1 entries. Shard memmaps open on first
    use and are views, never copies."""

    dir: str
    meta: Dict
    offsets: np.ndarray                       # int64 [K+1] (memmap)
    _shards: Dict[int, np.ndarray] = dataclasses.field(default_factory=dict)
    _files: Dict[int, object] = dataclasses.field(default_factory=dict)

    @property
    def population(self) -> int:
        return int(self.meta["population"])

    @property
    def max_client_n(self) -> int:
        return int(self.meta["max_client_n"])

    @property
    def shard_clients(self) -> int:
        return int(self.meta["shard_clients"])

    def padded_max_n(self, pad_multiple: int = 1) -> int:
        """The static cohort-row length: max client shard size rounded up
        exactly like the dense layout (data/arrays.padded_max_n), so a
        label_shards bank row is bitwise the dense stacked row."""
        return padded_max_n(np.asarray([self.max_client_n]), pad_multiple)

    def _shard(self, i: int) -> np.ndarray:
        mm = self._shards.get(i)
        if mm is None:
            path = os.path.join(self.dir, f"indices-{i:05d}.bin")
            mm = np.memmap(path, dtype=np.int64, mode="r")
            self._shards[i] = mm
        return mm

    def client_indices(self, cid: int) -> np.ndarray:
        """This client's sample-index list (a memmap view)."""
        cid = int(cid)
        lo, hi = int(self.offsets[cid]), int(self.offsets[cid + 1])
        if lo == hi:
            # an empty shard must not touch the shard file (a shard whose
            # clients are all empty is a 0-byte file np.memmap rejects)
            return np.empty((0,), dtype=np.int64)
        s = cid // self.shard_clients
        base = int(self.offsets[s * self.shard_clients])
        return self._shard(s)[lo - base:hi - base]

    def _shard_fd(self, i: int) -> int:
        f = self._files.get(i)
        if f is None:
            path = os.path.join(self.dir, f"indices-{i:05d}.bin")
            f = open(path, "rb")
            self._files[i] = f
        return f.fileno()

    def read_client_indices(self, cid: int) -> np.ndarray:
        """This client's sample-index list, STREAMED: one pread of
        exactly the row's byte range into a fresh buffer. Unlike the
        memmap view (``client_indices``) no shard pages join the resident
        set — at 10M+ clients a long run's gathers would otherwise
        accumulate the whole touched shard in RSS. Bitwise-equal to
        ``client_indices`` by construction (same bytes, same dtype)."""
        cid = int(cid)
        lo, hi = int(self.offsets[cid]), int(self.offsets[cid + 1])
        if lo == hi:
            return np.empty((0,), dtype=np.int64)
        s = cid // self.shard_clients
        base = int(self.offsets[s * self.shard_clients])
        buf = os.pread(self._shard_fd(s), (hi - lo) * 8, (lo - base) * 8)
        return np.frombuffer(buf, dtype=np.int64)

    def close(self) -> None:
        """Release streamed-read file handles (memmaps close with GC;
        the pread fds are real OS handles and deserve an explicit
        release — long-lived drivers reopen lazily on next use)."""
        for f in self._files.values():
            try:
                f.close()
            except OSError:
                pass
        self._files.clear()

    def sizes_of(self, ids) -> np.ndarray:
        ids = np.asarray(ids, dtype=np.int64)
        off = self.offsets
        return (off[ids + 1] - off[ids]).astype(np.int32)

    def gather(self, ids, images: np.ndarray, labels: np.ndarray,
               max_n: int, streamed: bool = True
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The cohort's padded stacks: ([m, max_n, ...] images, [m, max_n]
        labels, [m] sizes) — the exact AgentShards row layout, built for
        the m sampled clients only. ``streamed`` (default) preads each
        row's byte range; ``streamed=False`` keeps the historical memmap
        path (bitwise-identical output, larger resident set)."""
        ids = np.asarray(ids, dtype=np.int64)
        fetch = self.read_client_indices if streamed else self.client_indices
        m = len(ids)
        out_img = np.zeros((m, max_n) + images.shape[1:], dtype=images.dtype)
        out_lbl = np.zeros((m, max_n), dtype=np.int32)
        sizes = np.zeros((m,), dtype=np.int32)
        for j, cid in enumerate(ids):
            idx = np.asarray(fetch(cid))
            n = len(idx)
            sizes[j] = n
            if n:
                out_img[j, :n] = images[idx]
                out_lbl[j, :n] = labels[idx]
        return out_img, out_lbl, sizes

    @classmethod
    def open(cls, bank_dir: str) -> "ClientBank":
        with open(os.path.join(bank_dir, META_NAME)) as f:
            meta = json.load(f)
        if meta.get("version") != BANK_VERSION:
            raise ValueError(f"bank {bank_dir!r}: version "
                             f"{meta.get('version')} != {BANK_VERSION}")
        offsets = np.load(os.path.join(bank_dir, OFFSETS_NAME),
                          mmap_mode="r")
        return cls(bank_dir, meta, offsets)


class BankCorrupted(ValueError):
    """A shard's bytes disagree with its sha256 sidecar — real on-disk
    damage, never a stale-config condition ``get_or_build`` may silently
    rebuild over."""


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def verify_digests(bank_dir: str, log=print) -> int:
    """Data-plane integrity (ISSUE 14): check every ``indices-*.bin``
    shard against its ``.sha256`` sidecar (written at build — presence
    is atomic with the bank's publish rename). A mismatch raises a loud,
    actionable error NAMING the shard: a silently corrupted index shard
    would otherwise feed garbage batches to every cohort that touches
    its clients. Shards without a sidecar (a pre-digest legacy bank) are
    skipped with a note. Returns the number of shards verified."""
    names = sorted(n for n in os.listdir(bank_dir)
                   if n.startswith("indices-") and n.endswith(".bin"))
    checked = 0
    for name in names:
        path = os.path.join(bank_dir, name)
        sidecar = path + DIGEST_SUFFIX
        if not os.path.exists(sidecar):
            log(f"[bank] {name}: no digest sidecar (pre-digest bank) — "
                f"skipping verification for this shard")
            continue
        with open(sidecar, encoding="utf-8") as f:
            want = f.read().strip()
        have = _file_sha256(path)
        if have != want:
            raise BankCorrupted(
                f"client bank shard CORRUPTED: {path} hashes to "
                f"{have[:16]}… but its sidecar records {want[:16]}… — "
                f"the bank on disk is damaged (bad disk, torn copy, or "
                f"tampering). Delete the bank directory ({bank_dir}) to "
                f"rebuild it deterministically, or restore it from a "
                f"good copy.")
        checked += 1
    return checked


def _write_range(tmp: str, labels: np.ndarray, lo: int, hi: int, *,
                 population: int, partitioner: str, spc: int, alpha: float,
                 classes_per_client: int, seed: int, n_classes: int,
                 shard_clients: int, sha=None
                 ) -> Tuple[np.ndarray, int, int]:
    """Write the shard files covering clients ``[lo, hi)`` into ``tmp``
    (plus sha256 sidecars). ``lo`` must be shard-aligned so every shard
    file this range touches is written whole — the unit one build worker
    owns. ``sha``, when given, is updated with each row's bytes in client
    order (the serial in-process build's running content hash). Returns
    (per-client row sizes [hi-lo], max_client_n, total_indices)."""
    if lo % shard_clients:
        raise ValueError(f"range start {lo} not aligned to "
                         f"shard_clients={shard_clients}")
    sizes = np.zeros(hi - lo, dtype=np.int64)
    max_client_n = 0
    total = 0
    shard_f = None
    shard_id = -1
    shard_sha = None

    def close_shard():
        # finalize the open shard: close it and land its sha256 sidecar
        # (data-plane integrity, ISSUE 14 — verify_digests checks it on
        # every --bank_verify open). Sidecars are written inside the tmp
        # dir, so they publish atomically with the bank's rename.
        nonlocal shard_f, shard_sha
        if shard_f is not None:
            path = shard_f.name
            shard_f.close()
            shard_f = None
            with open(path + DIGEST_SUFFIX, "w", encoding="utf-8") as sf:
                sf.write(shard_sha.hexdigest() + "\n")

    try:
        for start, lists in _iter_client_lists(
                labels, population=population, partitioner=partitioner,
                spc=spc, alpha=alpha,
                classes_per_client=classes_per_client, seed=seed,
                n_classes=n_classes, lo=lo, hi=hi):
            for j, idx in enumerate(lists):
                cid = start + j
                s = cid // shard_clients
                if s != shard_id:
                    close_shard()
                    shard_id = s
                    shard_sha = hashlib.sha256()
                    shard_f = open(os.path.join(
                        tmp, f"indices-{s:05d}.bin"), "wb")
                buf = np.ascontiguousarray(idx, dtype=np.int64).tobytes()
                shard_f.write(buf)
                if sha is not None:
                    sha.update(buf)
                shard_sha.update(buf)
                n = len(idx)
                max_client_n = max(max_client_n, n)
                total += n
                sizes[cid - lo] = n
    finally:
        close_shard()
    return sizes, max_client_n, total


_WORKER_LABELS = "labels.npy"


def _build_worker(args) -> Dict:
    """One parallel-build subprocess: write this worker's whole-shard
    client range. Module-level and primitive-args so the spawn context
    can pickle it; labels come from the tmp dir (saved once by the
    parent) rather than the pickle stream."""
    (tmp, w, lo, hi, population, partitioner, spc, alpha,
     classes_per_client, seed, n_classes, shard_clients) = args
    labels = np.load(os.path.join(tmp, _WORKER_LABELS))
    sizes, max_client_n, total = _write_range(
        tmp, labels, lo, hi, population=population,
        partitioner=partitioner, spc=spc, alpha=alpha,
        classes_per_client=classes_per_client, seed=seed,
        n_classes=n_classes, shard_clients=shard_clients)
    # sizes ride a file, not the result pickle: at 100M clients a
    # worker's sizes array is hundreds of MB
    np.save(os.path.join(tmp, f"sizes-{w:05d}.npy"), sizes)
    return {"w": w, "lo": lo, "hi": hi,
            "max_client_n": int(max_client_n), "total": int(total),
            "shards": (hi - lo + shard_clients - 1) // shard_clients}


# optional Prometheus exporter for build progress (obs/export.py
# MetricsExporter); the service driver installs its instance so a
# multi-hour 100M build is watchable from the fleet console
_BUILD_EXPORTER = None


def install_build_exporter(exporter) -> None:
    global _BUILD_EXPORTER
    _BUILD_EXPORTER = exporter


def _build_progress(done_clients: int, population: int) -> None:
    if _BUILD_EXPORTER is not None:
        _BUILD_EXPORTER.set(
            "bank_build_clients_total", done_clients, mtype="counter",
            help_text="clients whose bank rows have been written")
        _BUILD_EXPORTER.set(
            "bank_build_clients_target", population,
            help_text="population of the bank being built")


def build_bank(bank_dir: str, labels: np.ndarray, *, population: int,
               partitioner: str = "dirichlet", samples_per_client: int = 0,
               dirichlet_alpha: float = 0.5, classes_per_client: int = 2,
               seed: int = 0, n_classes: int = 10,
               shard_clients: int = 65536, key: Optional[str] = None,
               workers: int = 1, log=print) -> ClientBank:
    """Partition once into an offset-indexed store. Streams: peak memory is
    O(BUILD_BLOCK * samples_per_client) regardless of population. The
    build lands in a temp dir and is renamed into place atomically, so a
    concurrent builder (or a killed one) can never leave a half-bank that
    opens. `key` is the precomputed bank_key of these exact inputs
    (callers that already paid the labels hash pass it through).

    ``workers > 1`` fans the shard range out across spawn subprocesses
    (whole shard files per worker, clamped to the shard count); the
    published bank — content_sha, offsets, every shard byte — is
    bitwise identical to the serial build's by construction, so
    ``workers`` never joins the bank key."""
    labels = np.asarray(labels)
    spc = resolve_samples_per_client(samples_per_client, len(labels),
                                     population)
    shard_clients = max(1, int(shard_clients))
    if key is None:
        key = bank_key(labels, population=population,
                       partitioner=partitioner, samples_per_client=spc,
                       dirichlet_alpha=dirichlet_alpha,
                       classes_per_client=classes_per_client, seed=seed,
                       n_classes=n_classes)
    n_shards = (population + shard_clients - 1) // shard_clients
    workers = max(1, min(int(workers), n_shards))
    tmp = f"{bank_dir}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    obs_events.emit("bank/build_start", population=population,
                    partitioner=partitioner, n_shards=n_shards,
                    workers=workers, key=key)
    _build_progress(0, population)
    kw = dict(population=population, partitioner=partitioner, spc=spc,
              alpha=dirichlet_alpha,
              classes_per_client=classes_per_client, seed=seed,
              n_classes=n_classes, shard_clients=shard_clients)
    if workers == 1:
        sha = hashlib.sha256()
        sizes, max_client_n, total = _write_range(tmp, labels, 0,
                                                  population, sha=sha,
                                                  **kw)
        obs_events.emit("bank/shard_done", worker=0, shards=n_shards,
                        clients=population, indices=int(total))
        _build_progress(population, population)
        content_sha = sha.hexdigest()
    else:
        # whole-shard contiguous ranges per worker: shard s's bytes are
        # written by exactly one process, and the ranges tile the client
        # axis in order — concatenating the shard files in shard order
        # reproduces the serial content byte stream exactly
        np.save(os.path.join(tmp, _WORKER_LABELS),
                np.ascontiguousarray(labels, dtype=np.int64))
        bounds = [round(n_shards * w / workers) * shard_clients
                  for w in range(workers + 1)]
        bounds[-1] = population
        jobs = [(tmp, w, bounds[w], min(bounds[w + 1], population),
                 population, partitioner, spc, dirichlet_alpha,
                 classes_per_client, seed, n_classes, shard_clients)
                for w in range(workers)]
        ctx = multiprocessing.get_context("spawn")
        done_clients = 0
        results = []
        with ctx.Pool(workers) as pool:
            for res in pool.imap_unordered(_build_worker, jobs):
                results.append(res)
                done_clients += res["hi"] - res["lo"]
                obs_events.emit("bank/shard_done", worker=res["w"],
                                shards=res["shards"],
                                clients=res["hi"] - res["lo"],
                                indices=res["total"])
                _build_progress(done_clients, population)
        results.sort(key=lambda r: r["w"])
        sizes = np.concatenate(
            [np.load(os.path.join(tmp, f"sizes-{r['w']:05d}.npy"))
             for r in results])
        max_client_n = max(r["max_client_n"] for r in results)
        total = sum(r["total"] for r in results)
        # one global content sha: stream the finished shard files in
        # shard order (= client order) through a single hash
        sha = hashlib.sha256()
        for s in range(n_shards):
            path = os.path.join(tmp, f"indices-{s:05d}.bin")
            if os.path.exists(path):
                with open(path, "rb") as f:
                    for chunk in iter(lambda: f.read(1 << 20), b""):
                        sha.update(chunk)
        content_sha = sha.hexdigest()
        os.remove(os.path.join(tmp, _WORKER_LABELS))
        for r in results:
            os.remove(os.path.join(tmp, f"sizes-{r['w']:05d}.npy"))
    offsets = np.zeros(population + 1, dtype=np.int64)
    np.cumsum(sizes, out=offsets[1:])
    np.save(os.path.join(tmp, OFFSETS_NAME), offsets)
    meta = {
        "version": BANK_VERSION, "key": key, "content_sha": content_sha,
        "population": population, "partitioner": partitioner,
        "samples_per_client": spc, "dirichlet_alpha": dirichlet_alpha,
        "classes_per_client": classes_per_client, "seed": seed,
        "n_classes": n_classes, "shard_clients": shard_clients,
        "n_base_samples": int(len(labels)),
        "total_indices": int(total), "max_client_n": int(max_client_n),
        "n_shards": (population + shard_clients - 1) // shard_clients,
    }
    with open(os.path.join(tmp, META_NAME), "w") as f:
        json.dump(meta, f, indent=1, sort_keys=True)
    if os.path.isdir(bank_dir):
        # a racing builder finished first: its content is identical by
        # construction (same key); keep it
        shutil.rmtree(tmp)
    else:
        try:
            os.replace(tmp, bank_dir)
        except OSError:
            # check-then-replace race: a concurrent builder published
            # between the isdir check and the rename (os.replace cannot
            # overwrite a non-empty dir). Same key => same content; keep
            # the winner's
            if not os.path.isdir(bank_dir):
                raise
            shutil.rmtree(tmp)
    obs_events.emit("bank/published", population=population,
                    n_shards=meta["n_shards"], workers=workers,
                    content_sha=content_sha, dir=bank_dir)
    log(f"[bank] {partitioner} partition of {population:,} clients "
        f"({total:,} index rows, max shard {max_client_n}, "
        f"{meta['n_shards']} shard file(s)"
        + (f", {workers} build workers" if workers > 1 else "")
        + f") -> {bank_dir}")
    return ClientBank.open(bank_dir)


def get_or_build(bank_dir: str, labels: np.ndarray, *, population: int,
                 partitioner: str, samples_per_client: int,
                 dirichlet_alpha: float, classes_per_client: int,
                 seed: int, n_classes: int, shard_clients: int,
                 key: Optional[str] = None, verify: bool = False,
                 workers: int = 1, log=print) -> Tuple[ClientBank, bool]:
    """Open `bank_dir` when its key matches this config, else (re)build.
    Returns (bank, built). `key` = precomputed bank_key of these inputs
    (the labels sha256 is the expensive part — callers that already
    computed it to resolve the bank dir pass it through). ``verify``
    (--bank_verify) checks every reused shard against its sha256
    sidecar before the first gather — a corrupted bank fails loudly
    naming the shard instead of feeding garbage batches (a fresh build
    is trusted: the sidecars were just computed from the written
    bytes)."""
    labels = np.asarray(labels)
    spc = resolve_samples_per_client(samples_per_client, len(labels),
                                     population)
    if key is None:
        key = bank_key(labels, population=population,
                       partitioner=partitioner, samples_per_client=spc,
                       dirichlet_alpha=dirichlet_alpha,
                       classes_per_client=classes_per_client, seed=seed,
                       n_classes=n_classes)
    meta_path = os.path.join(bank_dir, META_NAME)
    if os.path.exists(meta_path):
        try:
            bank = ClientBank.open(bank_dir)
            if bank.meta.get("key") == key:
                if verify:
                    # a digest MISMATCH stays loud (BankCorrupted is not
                    # caught below): silently rebuilding would hide real
                    # disk damage behind a multi-minute rebuild
                    n = verify_digests(bank_dir, log=log)
                    log(f"[bank] {bank_dir}: {n} shard digest(s) "
                        f"verified (--bank_verify)")
                return bank, False
            log(f"[bank] {bank_dir}: key mismatch "
                f"(have {bank.meta.get('key')}, want {key}); rebuilding")
        except BankCorrupted:
            raise
        except (OSError, ValueError) as e:
            log(f"[bank] {bank_dir}: unreadable ({e}); rebuilding")
        shutil.rmtree(bank_dir, ignore_errors=True)
    bank = build_bank(bank_dir, labels, population=population,
                      partitioner=partitioner, samples_per_client=spc,
                      dirichlet_alpha=dirichlet_alpha,
                      classes_per_client=classes_per_client, seed=seed,
                      n_classes=n_classes, shard_clients=shard_clients,
                      key=key, workers=workers, log=log)
    return bank, True
