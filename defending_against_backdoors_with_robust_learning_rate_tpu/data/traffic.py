"""Trace-shaped diurnal traffic: per-client timezone offsets, a daily
availability curve, and heavy-tailed upload latency — every draw a pure
function of (client id, round).

Flat Bernoulli availability (service/churn.py models multi-round
lifecycles, faults/model.py within-round dropouts) misses the dominant
structure of real FL traffic: device availability follows the sun.
Clients charge-and-idle at night local time, so the reachable population
swings by multiples over a day, and per-client upload latency is
heavy-tailed rather than uniform (FedJAX 2108.02117 and FL_PyTorch
2202.03099 both name availability realism the open simulator problem).
This module adds that shape with the exact discipline churn established:

- **pure function of (client, round)**: each client gets a seeded
  timezone offset in ``[0, traffic_day_rounds)``; its local time of day
  is ``(rnd + offset) mod traffic_day_rounds``. Availability follows a
  raised-cosine diurnal curve between ``traffic_trough_frac`` (local
  night) and ``traffic_peak_frac`` (local peak); presence at round
  ``rnd`` is a per-(client, round) uniform draw against that curve.
  O(1) per query, NO sequential state — crash recovery reconstructs the
  identical traffic history from the config alone.
- **replicated, collective-free**: draws depend only on program
  constants (``traffic_seed``) and traced per-slot values, so every
  device computes the identical mask — ZERO new collectives; the [m]
  presence bools AND into the participation mask exactly like churn.
- **heavy-tailed latency**: buffered/async mode draws each straggler's
  staleness from a log-normal (``traffic_latency_sigma``) clipped to
  ``[1, max_staleness]`` instead of the uniform randint — the same key
  derivation, so the fl/buffered.py host mirror stays bit-identical.

The stream derives from ``cfg.traffic_seed`` (its own `program` config
field), NOT from ``cfg.seed`` — the traffic pattern can be re-drawn
without perturbing any training stream, and distinct fold_in tags keep
it disjoint from churn (0xC4A21), cohort (0xC0407), faults (0x5FA17)
and the async stream (0xA51C).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# fold_in tag separating the traffic stream from every other
# PRNGKey-derived stream
TRAFFIC_KEY_TAG = 0x7AF1C

TRAFFIC_MODES = ("flat", "diurnal")


def traffic_key(cfg):
    """Base key of the traffic streams (a traced program constant)."""
    return jax.random.fold_in(jax.random.PRNGKey(cfg.traffic_seed),
                              TRAFFIC_KEY_TAG)


def mean_available(cfg) -> float:
    """Day-averaged availability: the raised cosine integrates to the
    midpoint of trough and peak — the cohort oversample's scale factor."""
    if not cfg.traffic_enabled:
        return 1.0
    return 0.5 * (float(cfg.traffic_peak_frac)
                  + float(cfg.traffic_trough_frac))


def availability_curve(cfg, local_t):
    """[...] float32 availability at local time-of-day ``local_t`` (in
    rounds): trough + (peak - trough) * (1 + cos(2*pi*t/day)) / 2 —
    peak at local midnight-of-the-curve t=0, trough half a day later.
    Shared by the presence draw and the host-side census."""
    day = max(1, int(cfg.traffic_day_rounds))
    lo = jnp.float32(cfg.traffic_trough_frac)
    hi = jnp.float32(cfg.traffic_peak_frac)
    phase = 2.0 * jnp.pi * local_t.astype(jnp.float32) / day
    return lo + (hi - lo) * 0.5 * (1.0 + jnp.cos(phase))


def present_slots(cfg, client_ids, rnd):
    """[m] bool — is each client traffic-reachable at round ``rnd``?

    ``client_ids`` is any int array of client ids; ``rnd`` may be a
    traced int32 scalar (inside the round program) or a Python int (the
    host mirror — same jax ops, bit-identical answer)."""
    day = max(1, int(cfg.traffic_day_rounds))
    base = traffic_key(cfg)

    def one(cid):
        k_tz, k_draw = jax.random.split(jax.random.fold_in(base, cid))
        # the seeded timezone offset spreads local midnights across the
        # population: at any wall-clock round some of the world is at
        # peak and some in its trough
        off = jax.random.randint(k_tz, (), 0, day)
        local_t = (rnd + off) % day
        p = availability_curve(cfg, local_t)
        return jax.random.uniform(jax.random.fold_in(k_draw, rnd)) < p

    return jax.vmap(one)(jnp.asarray(client_ids, jnp.int32))


def latency_quantile(cfg, u, max_staleness: int):
    """Map uniform draws ``u`` in [0,1) to heavy-tailed integer staleness
    in [1, max_staleness]: the log-normal quantile exp(sigma * PPF(u)),
    ceil'd and clipped. Shared by the traced latency draw and its host
    mirror (same ops => bit-identical)."""
    sigma = jnp.float32(cfg.traffic_latency_sigma)
    # inverse-CDF of the standard normal via erfinv (jax-native, no scipy)
    z = jnp.sqrt(jnp.float32(2.0)) * jax.scipy.special.erfinv(
        2.0 * u.astype(jnp.float32) - 1.0)
    t = jnp.ceil(jnp.exp(sigma * z))
    return jnp.clip(t, 1, max_staleness).astype(jnp.int32)


def census(cfg, rnd: int) -> int:
    """Host-side census: how many of the K clients are traffic-present
    at round ``rnd``. Observability only — never on the hot path."""
    return int(np.asarray(jnp.sum(present_slots(
        cfg, jnp.arange(cfg.num_agents), int(rnd)))))
