"""Seeded, fingerprint-stable per-round cohort sampling — computed inside
the round program AND mirrored on host with the same jax ops.

The population/cohort split (data/bank.py stores the population; this
module picks each round's cohort) needs ONE sampling function with three
properties:

- **in-program**: the round program receives only the round index (a
  traced int32, like the churn lead arg) and recomputes the cohort ids
  itself — corrupt flags (``ids < num_corrupt``) and the churn lifecycle
  mask derive in-jit from real client ids, so the host never ships flag
  arguments and the metrics layer attributes Defense/Faults over *cohort
  membership*, not slot position.
- **host-mirrorable**: the driver must gather the SAME clients' data
  before dispatch. ``host_sampler`` jits the identical function once per
  config; same ops + same PRNG impl => bit-identical ids on both sides.
- **fingerprint-stable**: the draw is a pure function of ``cohort_seed``
  (its own `program` config field, like ``churn_seed``) and the traced
  round index — never of runtime knobs — so one AOT-banked executable
  serves every round and every resume.

Sampling model (O(cohort), never O(population)): draw ``C`` candidate ids
with replacement (C = an oversample of m, scaled by churn availability),
mark each candidate *eligible* iff it is the first occurrence of its id
(dedup) AND its client is churn-present this round
(service/churn.active_slots — cohorts are sampled from the present set,
retiring the host-sampled + churn refusal), then take the first m
eligible candidates. If fewer than m are eligible (tiny populations,
deep churn), the cohort is padded with ineligible candidates whose
``active=False`` flag routes them into the participation mask — they are
excluded from aggregation exactly like a dropped client, so correctness
degrades gracefully instead of ever resampling with a different shape.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# fold_in tag separating the cohort stream from every other PRNGKey stream
# (churn uses 0xC4A21, faults 0x5FA17)
COHORT_KEY_TAG = 0xC0407

# candidate-matrix bound: the dedup is an O(C^2) comparison, so cap C
# (4096^2 bools = 16 MiB of trace-local work — fine; beyond it, raise)
MAX_CANDIDATES = 4096


def cohort_key(cfg):
    """Base key of the cohort stream (a traced program constant)."""
    return jax.random.fold_in(jax.random.PRNGKey(cfg.cohort_seed),
                              COHORT_KEY_TAG)


def oversample_count(cfg) -> int:
    """C: how many candidates one round draws. 2x the cohort, scaled up by
    churn availability (absent candidates are ineligible), capped at the
    population-ish scale only through MAX_CANDIDATES."""
    m = cfg.agents_per_round
    avail = cfg.churn_available if cfg.churn_enabled else 1.0
    c = int(np.ceil(2.0 * m / max(float(avail), 0.05)))
    c = max(c, m + 8)
    if c > MAX_CANDIDATES:
        raise ValueError(
            f"cohort oversample {c} exceeds MAX_CANDIDATES="
            f"{MAX_CANDIDATES} (cohort {m}, churn_available "
            f"{cfg.churn_available}); shrink the cohort or raise "
            f"availability")
    return c


def cohort_feasible(cfg) -> bool:
    """Can this config's implied cohort be sampled at all? False when the
    oversample would blow MAX_CANDIDATES (e.g. cohort_size unset at a big
    population, so m = floor(K * agent_frac) is population-sized).
    `is_cohort_mode`'s auto path consults this so such configs stay on
    their historical dense path instead of crashing; an explicit
    --cohort_sampled on still raises the loud ValueError."""
    try:
        oversample_count(cfg)
    except ValueError:
        return False
    return True


def sample_cohort(cfg, rnd):
    """([m] int32 client ids, [m] bool active) for round ``rnd``.

    ``rnd`` may be a traced int32 scalar (inside the round program) or a
    Python int (the host mirror) — same jax ops, bit-identical answer.
    ``active`` is False only for shortfall padding (duplicate or
    churn-absent candidates used to fill the fixed shape); callers AND it
    into the participation mask."""
    K, m = cfg.num_agents, cfg.agents_per_round
    C = oversample_count(cfg)
    k = jax.random.fold_in(cohort_key(cfg), rnd)
    cand = jax.random.randint(k, (C,), 0, K, dtype=jnp.int32)
    # first-occurrence dedup: argmax over the boolean equality row returns
    # the FIRST matching position
    eq = cand[:, None] == cand[None, :]
    first = jnp.argmax(eq, axis=1) == jnp.arange(C)
    eligible = first
    if cfg.churn_enabled:
        from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
            churn as churn_mod)
        with jax.named_scope("cohort_churn_presence"):
            eligible = eligible & churn_mod.active_slots(cfg, cand, rnd)
    # stable partition: eligible candidates first, original draw order
    # preserved on both sides (unique composite keys make any sort stable)
    key_order = jnp.where(eligible, 0, 1) * C + jnp.arange(C)
    order = jnp.argsort(key_order)[:m]
    return cand[order], eligible[order]


@functools.lru_cache(maxsize=16)
def host_sampler(cfg):
    """The host mirror: a jitted ``rnd -> (ids, active)`` for the gather
    side (Config is a frozen dataclass, so it keys the cache). One
    compile per config; per-round cost is one tiny dispatch on the
    prefetch thread."""
    return jax.jit(lambda rnd: sample_cohort(cfg, rnd))


def sample_cohort_host(cfg, rnd: int):
    """Numpy (ids, active) for round ``rnd`` — the driver-side mirror."""
    ids, active = host_sampler(cfg)(jnp.int32(rnd))
    return np.asarray(ids), np.asarray(active)
