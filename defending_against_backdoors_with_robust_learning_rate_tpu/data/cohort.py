"""Seeded, fingerprint-stable per-round cohort sampling — computed inside
the round program AND mirrored on host with the same jax ops.

The population/cohort split (data/bank.py stores the population; this
module picks each round's cohort) needs ONE sampling function with three
properties:

- **in-program**: the round program receives only the round index (a
  traced int32, like the churn lead arg) and recomputes the cohort ids
  itself — corrupt flags (``ids < num_corrupt``) and the churn lifecycle
  mask derive in-jit from real client ids, so the host never ships flag
  arguments and the metrics layer attributes Defense/Faults over *cohort
  membership*, not slot position.
- **host-mirrorable**: the driver must gather the SAME clients' data
  before dispatch. ``host_sampler`` jits the identical function once per
  config; same ops + same PRNG impl => bit-identical ids on both sides.
- **fingerprint-stable**: the draw is a pure function of ``cohort_seed``
  (its own `program` config field, like ``churn_seed``) and the traced
  round index — never of runtime knobs — so one AOT-banked executable
  serves every round and every resume.

Sampling model (O(cohort), never O(population)): draw ``C`` candidate ids
with replacement (C = an oversample of m, scaled by churn + traffic
availability), mark each candidate *eligible* iff it is the first
occurrence of its id (dedup) AND its client is churn-present AND
traffic-present this round (service/churn.active_slots,
data/traffic.present_slots — cohorts are sampled from the present set,
retiring the host-sampled + churn refusal), then take the first m
eligible candidates. If fewer than m are eligible (tiny populations,
deep churn), the cohort is padded with ineligible candidates whose
``active=False`` flag routes them into the participation mask — they are
excluded from aggregation exactly like a dropped client, so correctness
degrades gracefully instead of ever resampling with a different shape.

Deep churn / diurnal troughs push the needed oversample past one
candidate matrix: the draw then becomes a **chunked rejection resample**
(ISSUE 17) — a ``lax.scan`` over MAX_CANDIDATES-sized chunks, each chunk
deduped within itself AND against the already-selected ids, scattering
its fresh eligible candidates into the next open cohort slots. The
single-chunk path keeps the exact historical op sequence, so every
config that fit under the old cap draws bit-identical cohorts; the loud
refusal now fires only when even MAX_DRAW_CHUNKS chunks could not cover
the oversample.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

# fold_in tag separating the cohort stream from every other PRNGKey stream
# (churn uses 0xC4A21, faults 0x5FA17, traffic 0x7AF1C)
COHORT_KEY_TAG = 0xC0407

# candidate-matrix bound: the dedup is an O(C^2) comparison, so cap C
# (4096^2 bools = 16 MiB of trace-local work — fine; beyond it, chunk)
MAX_CANDIDATES = 4096

# chunked-draw bound: at most this many MAX_CANDIDATES chunks per round
# (64 * 4096 = 262144 candidates — availability floors around 0.5% at
# paper-scale cohorts); past it the refusal stays loud
MAX_DRAW_CHUNKS = 64

# availability floor entering the oversample: below this the chunked
# draw would need more than MAX_DRAW_CHUNKS chunks anyway
MIN_AVAILABILITY = 0.005


def cohort_key(cfg):
    """Base key of the cohort stream (a traced program constant)."""
    return jax.random.fold_in(jax.random.PRNGKey(cfg.cohort_seed),
                              COHORT_KEY_TAG)


def availability(cfg) -> float:
    """Expected fraction of the population reachable on a given round:
    churn availability x the traffic model's mean availability (the
    diurnal curve averages to its midpoint) — the oversample scale."""
    avail = float(cfg.churn_available) if cfg.churn_enabled else 1.0
    if cfg.traffic_enabled:
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            traffic as traffic_mod)
        avail *= traffic_mod.mean_available(cfg)
    return avail


def oversample_count(cfg) -> int:
    """C: how many candidates one round draws in total. 2x the cohort,
    scaled up by churn x traffic availability (absent candidates are
    ineligible). Counts past MAX_CANDIDATES are served by the chunked
    rejection draw; the loud refusal fires only past
    MAX_CANDIDATES * MAX_DRAW_CHUNKS (availability below
    ~MIN_AVAILABILITY at a big cohort — the population genuinely cannot
    fill it round after round)."""
    m = cfg.agents_per_round
    c = int(np.ceil(2.0 * m / max(availability(cfg), MIN_AVAILABILITY)))
    c = max(c, m + 8)
    if c > MAX_CANDIDATES * MAX_DRAW_CHUNKS:
        raise ValueError(
            f"cohort oversample {c} exceeds MAX_CANDIDATES="
            f"{MAX_CANDIDATES} x MAX_DRAW_CHUNKS={MAX_DRAW_CHUNKS} "
            f"(cohort {m}, availability {availability(cfg):.4f}); "
            f"shrink the cohort or raise availability")
    return c


def draw_plan(cfg):
    """(per-chunk candidate count, n_chunks) for this config's draw.
    One chunk keeps the historical single-matrix op sequence (and its
    bit-exact cohorts); more chunks select the chunked rejection
    resample."""
    c = oversample_count(cfg)
    if c <= MAX_CANDIDATES:
        return c, 1
    return MAX_CANDIDATES, -(-c // MAX_CANDIDATES)


def cohort_feasible(cfg) -> bool:
    """Can this config's implied cohort be sampled at all? False when
    even the chunked draw could not cover the oversample (availability
    below the floor at a big cohort). `is_cohort_mode`'s auto path
    consults this so such configs stay on their historical dense path
    instead of crashing; an explicit --cohort_sampled on still raises
    the loud ValueError."""
    try:
        oversample_count(cfg)
    except ValueError:
        return False
    return True


def _present(cfg, cand, rnd):
    """[C] bool: candidate is reachable this round — churn presence AND
    traffic (diurnal) presence, both pure functions of (client, round)."""
    ok = None
    if cfg.churn_enabled:
        from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
            churn as churn_mod)
        with jax.named_scope("cohort_churn_presence"):
            ok = churn_mod.active_slots(cfg, cand, rnd)
    if cfg.traffic_enabled:
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            traffic as traffic_mod)
        with jax.named_scope("cohort_traffic_presence"):
            present = traffic_mod.present_slots(cfg, cand, rnd)
        ok = present if ok is None else ok & present
    return ok


def sample_cohort(cfg, rnd):
    """([m] int32 client ids, [m] bool active) for round ``rnd``.

    ``rnd`` may be a traced int32 scalar (inside the round program) or a
    Python int (the host mirror) — same jax ops, bit-identical answer.
    ``active`` is False only for shortfall padding (duplicate or
    absent candidates used to fill the fixed shape); callers AND it
    into the participation mask."""
    K, m = cfg.num_agents, cfg.agents_per_round
    C, n_chunks = draw_plan(cfg)
    k = jax.random.fold_in(cohort_key(cfg), rnd)
    if n_chunks == 1:
        cand = jax.random.randint(k, (C,), 0, K, dtype=jnp.int32)
        # first-occurrence dedup: argmax over the boolean equality row
        # returns the FIRST matching position
        eq = cand[:, None] == cand[None, :]
        first = jnp.argmax(eq, axis=1) == jnp.arange(C)
        eligible = first
        present = _present(cfg, cand, rnd)
        if present is not None:
            eligible = eligible & present
        # stable partition: eligible candidates first, original draw
        # order preserved on both sides (unique composite keys make any
        # sort stable)
        key_order = jnp.where(eligible, 0, 1) * C + jnp.arange(C)
        order = jnp.argsort(key_order)[:m]
        return cand[order], eligible[order]

    # chunked rejection resample: scan MAX_CANDIDATES-sized chunks, each
    # deduped within itself and against the already-selected ids, its
    # eligible candidates scattered into the next open cohort slots.
    # Static chunk count => one compiled program per config, O(C * m)
    # cross-chunk compare per chunk — never O(population).
    def body(carry, chunk):
        sel, sel_ok, cnt = carry
        kc = jax.random.fold_in(k, chunk)
        cand = jax.random.randint(kc, (C,), 0, K, dtype=jnp.int32)
        eq = cand[:, None] == cand[None, :]
        first = jnp.argmax(eq, axis=1) == jnp.arange(C)
        dup_prev = jnp.any((cand[:, None] == sel[None, :])
                           & sel_ok[None, :], axis=1)
        eligible = first & ~dup_prev
        present = _present(cfg, cand, rnd)
        if present is not None:
            eligible = eligible & present
        # scatter the chunk's eligible candidates, draw order preserved,
        # into slots cnt..; overflow past m (and every ineligible slot)
        # routes to index m, which mode="drop" discards
        rank = jnp.cumsum(eligible) - 1
        dest = jnp.where(eligible, cnt + rank, m)
        sel = sel.at[dest].set(cand, mode="drop")
        sel_ok = sel_ok.at[dest].set(True, mode="drop")
        cnt = jnp.minimum(cnt + eligible.sum(), m)
        return (sel, sel_ok, cnt), None

    init = (jnp.zeros((m,), dtype=jnp.int32),
            jnp.zeros((m,), dtype=bool), jnp.int32(0))
    (sel, sel_ok, _), _ = jax.lax.scan(body, init,
                                       jnp.arange(n_chunks))
    # shortfall slots keep id 0 with active=False — participation-masked
    # out of aggregation exactly like the single-chunk padding
    return sel, sel_ok


@functools.lru_cache(maxsize=16)
def host_sampler(cfg):
    """The host mirror: a jitted ``rnd -> (ids, active)`` for the gather
    side (Config is a frozen dataclass, so it keys the cache). One
    compile per config; per-round cost is one tiny dispatch on the
    prefetch thread."""
    return jax.jit(lambda rnd: sample_cohort(cfg, rnd))


def sample_cohort_host(cfg, rnd: int):
    """Numpy (ids, active) for round ``rnd`` — the driver-side mirror."""
    ids, active = host_sampler(cfg)(jnp.int32(rnd))
    return np.asarray(ids), np.asarray(active)
