"""Label-sorted shard partitioner.

Semantics-parity reimplementation of the reference's `distribute_data`
(src/utils.py:58-92): sort indices by label, split each class's index list
into `slice_size` strided chunks (`seq[i::size]`), then deal `class_per_agent`
chunks to each agent walking classes 0..n_classes-1 round-robin-with-deletion.

Divergence (documented): the reference sorts with `torch.sort`, which is not
stable; we use a stable numpy argsort so partitions are deterministic
(SURVEY.md 2.3.12 — the build adds determinism).
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List

import numpy as np


def distribute_data(labels: np.ndarray, num_agents: int,
                    n_classes: int = 10,
                    class_per_agent: int = 10) -> Dict[int, List[int]]:
    """Map agent id -> list of dataset indices (src/utils.py:58-92)."""
    n = len(labels)
    if num_agents == 1:
        return {0: list(range(n))}

    order = np.argsort(labels, kind="stable")
    labels_dict: Dict[int, List[List[int]]] = defaultdict(list)
    per_class: Dict[int, List[int]] = defaultdict(list)
    for idx in order:
        per_class[int(labels[idx])].append(int(idx))

    # split each class's indices into `slice_size` strided chunks
    shard_size = n // (num_agents * class_per_agent)
    if shard_size == 0:
        raise ValueError(
            f"dataset too small to partition: {n} samples cannot give "
            f"{num_agents} agents x {class_per_agent} class-shards each "
            f"(need >= {num_agents * class_per_agent}). The reference's "
            f"dealing scheme (src/utils.py:58-92) has the same bound.")
    slice_size = (n // n_classes) // shard_size
    for k, v in per_class.items():
        labels_dict[k] = [v[i::slice_size] for i in range(slice_size)]

    # deal chunks to agents (src/utils.py:82-92, incl. the `j % n_classes` quirk
    # which equals `j` since j < n_classes)
    dict_users: Dict[int, List[int]] = defaultdict(list)
    for user_idx in range(num_agents):
        class_ctr = 0
        for j in range(n_classes):
            if class_ctr == class_per_agent:
                break
            elif len(labels_dict[j]) > 0:
                dict_users[user_idx] += labels_dict[j][0]
                del labels_dict[j % n_classes][0]
                class_ctr += 1
    return dict(dict_users)
