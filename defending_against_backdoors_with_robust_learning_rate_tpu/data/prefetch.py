"""Host->device input pipeline for the host-sampled (fedemnist-scale) path.

The reference streams nothing: every agent's dataset sits in one process and
local training reads it directly (src/agent.py:28, src/federated.py:68-72).
This framework's host-sampled mode (train.py: shard stacks above the
device-resident budget, e.g. fedemnist's 3383 users, src/runner.sh:34-38)
instead gathers the round's m sampled shards on host and ships them to the
mesh each round. Done synchronously, that gather + transfer sits on the
critical path between two compiled rounds.

`RoundPrefetcher` moves it off: a worker thread materializes round r+1's
(and r+2's, up to `depth`) shard stack — numpy fancy-index gather plus an
async `jax.device_put` to the agents-mesh sharding — while the TPU executes
round r. `device_put` only *enqueues* a transfer, so the copy itself overlaps
with the running round program; the consumer blocks only when compute is
faster than the pipeline can feed it. Determinism is untouched: the sampling
sequence is owned by the caller's `produce(rnd)` (seeded per round,
train.py), the prefetcher just evaluates it early.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Iterable

_SENTINEL = object()


class RoundPrefetcher:
    """Depth-bounded background producer of per-round payloads.

    produce(rnd) -> payload is called on a worker thread for each round id in
    `rounds`, in order; `get(rnd)` returns the payloads in the same order.
    A producer exception is re-raised by the next `get` call.

    Memory note: effective pipeline depth is `depth + 1` payloads resident
    at once — the queue holds `depth` plus one in the worker's hand mid-put.
    Callers sizing device memory against `--host_prefetch N` should budget
    N+2 payloads (N queued, one being dispatched, one retained for retry —
    see get()); a payload is one dispatch UNIT — a single round's [m, ...]
    stacks, or a whole [chain, m, ...] block in chained host mode
    (documented in the flag help too)."""

    # get() re-checks for a wedged worker at this period, and logs a
    # heartbeat so a hang (e.g. a stuck device_put through a TPU tunnel) is
    # attributable to the pipeline rather than silently blocking the driver
    STALL_WARN_SEC = 30.0

    def __init__(self, produce: Callable, rounds: Iterable[int],
                 depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._err = None
        self._last = None  # (rnd, payload) most recently served — see get()
        self._thread = threading.Thread(
            target=self._worker, args=(produce, rounds), daemon=True)
        self._thread.start()

    def _put_checked(self, item) -> bool:
        """Blocking put that a racing close() can always interrupt: retries
        on a full queue until the item lands or `_stop` is set. Nothing may
        be silently dropped on queue.Full — in particular the sentinel,
        whose loss would turn the consumer's next get() into a permanent
        hang — and nothing may block forever against close() (which sets
        `_stop` and drains)."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self, produce, rounds):
        try:
            for rnd in rounds:
                payload = produce(rnd)
                if not self._put_checked((rnd, payload)):
                    return
        except BaseException as e:  # surfaced to the consumer by get()
            self._err = e
        finally:
            self._put_checked(_SENTINEL)

    def get(self, rnd: int):
        """Blocking fetch of round `rnd`'s payload (calls must follow the
        constructor's round order). Never hangs silently: while waiting it
        logs a stall heartbeat every STALL_WARN_SEC so a wedged produce()
        (hung host gather / device_put) is attributable."""
        if self._last is not None and self._last[0] == rnd:
            # repeat request for the round just served: a supervised retry
            # (service/supervisor.py) re-dispatches the SAME unit after a
            # transient failure — popping the queue again would hand it the
            # NEXT round and trip the order check below. Costs one retained
            # payload (the +1 in the N+2 budget above), replaced on the
            # next distinct get.
            return self._last[1]
        waited = 0.0
        while True:
            try:
                item = self._q.get(timeout=self.STALL_WARN_SEC)
                break
            except queue.Empty:
                waited += self.STALL_WARN_SEC
                alive = self._thread.is_alive()
                print(f"[prefetch] stalled waiting for round {rnd} "
                      f"({waited:.0f}s; worker "
                      f"{'alive' if alive else 'DEAD'})", flush=True)
                if not alive and self._q.empty():
                    raise RuntimeError(
                        f"prefetch worker died without sentinel before "
                        f"round {rnd}") from self._err
        if item is _SENTINEL:
            if self._err is not None:
                raise RuntimeError(
                    f"prefetch worker failed before round {rnd}") \
                    from self._err
            raise RuntimeError(
                f"prefetch exhausted before round {rnd} — the driver asked "
                f"for a round outside the range it constructed")
        got, payload = item
        if got != rnd:
            raise RuntimeError(
                f"prefetch order violation: driver asked for round {rnd}, "
                f"pipeline produced round {got}")
        self._last = (got, payload)
        return payload

    def close(self) -> None:
        """Stop the worker and release anything it buffered."""
        self._stop.set()
        # keep draining until the worker exits: it may be mid-put with one
        # payload in hand, so a single drain pass can leave the queue full
        # again right before its stop-check. Bounded: give up after 10s if
        # produce() itself is stuck (daemon thread, won't block exit).
        deadline = time.monotonic() + 10.0
        while self._thread.is_alive() and time.monotonic() < deadline:
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=0.1)
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
