"""Agent-stacked padded arrays — the device-resident data layout.

The reference keeps per-agent `DatasetSplit` views over a shared torch dataset
and streams minibatches host->GPU every step (src/agent.py:28,43-44). The
TPU-native layout instead stacks every agent's shard into one padded array
`[K, max_n, H, W, C]` that lives in HBM (or is sharded over the `agents` mesh
axis), with true sizes kept for loss masking and weighted FedAvg
(src/aggregation.py:61-63 semantics).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Sequence

import numpy as np


@dataclasses.dataclass
class AgentShards:
    images: np.ndarray      # [K, max_n, H, W, C] raw pixels (uint8 or float32)
    labels: np.ndarray      # [K, max_n] int32 (padding rows hold label 0)
    sizes: np.ndarray       # [K] int32 true shard sizes
    poison_mask: np.ndarray | None = None  # [K, max_n] bool, set after poisoning

    @property
    def num_agents(self) -> int:
        return self.images.shape[0]

    @property
    def max_n(self) -> int:
        return self.images.shape[1]


def padded_max_n(sizes: np.ndarray, pad_multiple: int = 1) -> int:
    """Shared padding rule: the stacked shard length is the max true shard
    size rounded up to `pad_multiple` (e.g. the batch size) so downstream
    reshapes into [n_batches, bs] are exact. The native runtime
    (data/native.py) and the numpy paths below both use THIS function, so
    the layouts can never diverge."""
    max_n = int(sizes.max()) if len(sizes) else 0
    if pad_multiple > 1:
        max_n = ((max_n + pad_multiple - 1) // pad_multiple) * pad_multiple
    return max_n


def stack_agent_shards(images: np.ndarray, labels: np.ndarray,
                       user_groups: Dict[int, Sequence[int]],
                       num_agents: int,
                       pad_multiple: int = 1) -> AgentShards:
    """Gather each agent's indices into a padded stacked array."""
    sizes = np.array([len(user_groups.get(a, ())) for a in range(num_agents)],
                     dtype=np.int32)
    max_n = padded_max_n(sizes, pad_multiple)
    shp = images.shape[1:]
    out_img = np.zeros((num_agents, max_n) + shp, dtype=images.dtype)
    out_lbl = np.zeros((num_agents, max_n), dtype=np.int32)
    for a in range(num_agents):
        idxs = np.asarray(list(user_groups.get(a, ())), dtype=np.int64)
        if len(idxs) == 0:
            continue
        out_img[a, :len(idxs)] = images[idxs]
        out_lbl[a, :len(idxs)] = labels[idxs]
    return AgentShards(out_img, out_lbl, sizes)


def stack_uneven_shards(shard_images: List[np.ndarray],
                        shard_labels: List[np.ndarray],
                        pad_multiple: int = 1) -> AgentShards:
    """Stack pre-split per-user shards (fed-emnist style, uneven sizes)."""
    num_agents = len(shard_images)
    sizes = np.array([len(x) for x in shard_images], dtype=np.int32)
    max_n = padded_max_n(sizes, pad_multiple)
    shp = shard_images[0].shape[1:]
    dtype = shard_images[0].dtype
    out_img = np.zeros((num_agents, max_n) + shp, dtype=dtype)
    out_lbl = np.zeros((num_agents, max_n), dtype=np.int32)
    for a in range(num_agents):
        n = sizes[a]
        out_img[a, :n] = shard_images[a]
        out_lbl[a, :n] = shard_labels[a].astype(np.int32)
    return AgentShards(out_img, out_lbl, sizes)
