from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (  # noqa: F401
    RawDataset,
    FederatedData,
    get_datasets,
    get_federated_data,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.data.partition import (  # noqa: F401
    distribute_data,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.data.arrays import (  # noqa: F401
    AgentShards,
    stack_agent_shards,
)
