"""ctypes bindings for the native host data runtime (native/fl_host.cc).

The hot device path is XLA; this library covers the host-side setup
pipeline the reference runs in Python loops (src/utils.py:58-92 partitioner,
DataLoader collation): label-sorted partitioning and packing agent shards
into the padded [K, max_n, ...] device layout — threaded C++ behind a C ABI
(no pybind11 in this image; ctypes only). Dataset decode stays numpy
(zero-copy frombuffer).

Usage is always optional: every entry point has a numpy twin
(data/partition.py, data/arrays.py) and callers go through
`distribute_data`/`pack_shards` wrappers here that fall back transparently
when the library is unavailable (no compiler, build failure, or
FL_NATIVE_HOST=0). Parity is asserted in tests/test_native.py.

The library is built on demand with g++ into native/build/ the first time it
is requested.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

_REPO_ROOT = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC = os.path.join(_REPO_ROOT, "native", "fl_host.cc")
_LIB = os.path.join(_REPO_ROOT, "native", "build", "libfl_host.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_lib_failed = False


def _build() -> bool:
    # build to a unique temp path and rename into place atomically, so a
    # rebuild never truncates a .so another live process has dlopened and
    # concurrent builders don't interleave writes
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-Wall", _SRC,
           "-shared", "-pthread", "-o", tmp]
    try:
        os.makedirs(os.path.dirname(_LIB), exist_ok=True)
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            return False
        os.replace(tmp, _LIB)
    except (OSError, subprocess.TimeoutExpired):
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return True


def _load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None on any failure."""
    global _lib, _lib_failed
    if _lib is not None:
        return _lib
    if _lib_failed or os.environ.get("FL_NATIVE_HOST", "1") == "0":
        return None
    with _lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_LIB) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_LIB)):
            if not _build():
                _lib_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _lib_failed = True
            return None

        i8p = ctypes.POINTER(ctypes.c_uint8)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        lib.fl_distribute_data.restype = ctypes.c_int32
        lib.fl_distribute_data.argtypes = [i32p, ctypes.c_int64,
                                           ctypes.c_int32, ctypes.c_int32,
                                           ctypes.c_int32, i32p, i32p, i64p]
        lib.fl_pack_shards.restype = ctypes.c_int32
        lib.fl_pack_shards.argtypes = [i8p, ctypes.c_int64, ctypes.c_int64,
                                       i32p, i64p, i32p, ctypes.c_int32,
                                       ctypes.c_int64, i8p, i32p]
        lib.fl_pack_uneven.restype = ctypes.c_int32
        lib.fl_pack_uneven.argtypes = [ctypes.POINTER(i8p),
                                       ctypes.POINTER(i32p), i32p,
                                       ctypes.c_int32, ctypes.c_int64,
                                       ctypes.c_int64, i8p, i32p]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _ptr(a: np.ndarray, ctype):
    return a.ctypes.data_as(ctypes.POINTER(ctype))


# ------------------------------------------------------------ partition ---

def distribute_data(labels: np.ndarray, num_agents: int,
                    n_classes: int = 10,
                    class_per_agent: int = 10) -> Dict[int, List[int]]:
    """Native label-sorted partitioner; transparently falls back to the
    numpy implementation (data/partition.py) when the library is missing."""
    lib = _load()
    if lib is not None:
        n = len(labels)
        lbl = np.ascontiguousarray(labels, dtype=np.int32)
        counts = np.zeros(num_agents, dtype=np.int32)
        chunks = np.zeros(num_agents, dtype=np.int32)
        indices = np.zeros(max(n, 1), dtype=np.int64)
        rc = lib.fl_distribute_data(_ptr(lbl, ctypes.c_int32), n, num_agents,
                                    n_classes, class_per_agent,
                                    _ptr(counts, ctypes.c_int32),
                                    _ptr(chunks, ctypes.c_int32),
                                    _ptr(indices, ctypes.c_int64))
        if rc == 0:
            # the Python dict has a key for an agent iff it dealt >= 1 chunk
            # (even an empty one) — mirror that exactly
            out: Dict[int, List[int]] = {}
            pos = 0
            for a in range(num_agents):
                c = int(counts[a])
                if chunks[a] > 0:
                    out[a] = indices[pos:pos + c].tolist()
                pos += c
            return out
    from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
        partition)
    return partition.distribute_data(labels, num_agents, n_classes,
                                     class_per_agent)


# ----------------------------------------------------------------- pack ---

def pack_shards(images: np.ndarray, labels: np.ndarray,
                user_groups: Dict[int, Sequence[int]], num_agents: int,
                pad_multiple: int = 1):
    """Native padded gather into the [K, max_n, ...] layout; falls back to
    data/arrays.stack_agent_shards when unavailable."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.data.arrays import (
        AgentShards, padded_max_n, stack_agent_shards)

    lib = _load()
    # the numpy twin raises on labels/images length mismatch; don't let the
    # native path read past the labels buffer instead
    if (lib is None or not images.flags.c_contiguous
            or len(labels) != images.shape[0]):
        return stack_agent_shards(images, labels, user_groups, num_agents,
                                  pad_multiple)
    sizes = np.array([len(user_groups.get(a, ())) for a in range(num_agents)],
                     dtype=np.int32)
    max_n = padded_max_n(sizes, pad_multiple)
    if max_n == 0:
        return stack_agent_shards(images, labels, user_groups, num_agents,
                                  pad_multiple)
    indices = np.concatenate(
        [np.asarray(list(user_groups.get(a, ())), dtype=np.int64)
         for a in range(num_agents)]) if sizes.sum() else np.zeros(
             1, np.int64)
    item_bytes = int(np.prod(images.shape[1:])) * images.dtype.itemsize
    out_img = np.zeros((num_agents, max_n) + images.shape[1:],
                       dtype=images.dtype)
    out_lbl = np.zeros((num_agents, max_n), dtype=np.int32)
    lbl32 = np.ascontiguousarray(labels, dtype=np.int32)
    rc = lib.fl_pack_shards(
        images.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        images.shape[0], item_bytes,
        _ptr(lbl32, ctypes.c_int32), _ptr(indices, ctypes.c_int64),
        _ptr(sizes, ctypes.c_int32), num_agents, max_n,
        out_img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        _ptr(out_lbl, ctypes.c_int32))
    if rc != 0:
        return stack_agent_shards(images, labels, user_groups, num_agents,
                                  pad_multiple)
    return AgentShards(out_img, out_lbl, sizes)


def pack_uneven(shard_images: List[np.ndarray], shard_labels: List[np.ndarray],
                pad_multiple: int = 1):
    """Native padded stack of pre-split per-user shards (fed-emnist)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.data.arrays import (
        AgentShards, padded_max_n, stack_uneven_shards)

    lib = _load()
    num_agents = len(shard_images)
    # the native path memcpy's raw bytes: every shard must share the first
    # shard's dtype and per-item shape, and every label array must match its
    # image shard's length — else fall back to the value-casting numpy path
    # (which raises on genuine mismatches)
    if (lib is None or num_agents == 0
            or len(shard_labels) != num_agents
            or any(x.dtype != shard_images[0].dtype
                   or x.shape[1:] != shard_images[0].shape[1:]
                   for x in shard_images)
            or any(len(y) != len(x)
                   for x, y in zip(shard_images, shard_labels, strict=True))):
        return stack_uneven_shards(shard_images, shard_labels, pad_multiple)
    imgs = [np.ascontiguousarray(x) for x in shard_images]
    lbls = [np.ascontiguousarray(y, dtype=np.int32) for y in shard_labels]
    sizes = np.array([len(x) for x in imgs], dtype=np.int32)
    max_n = padded_max_n(sizes, pad_multiple)
    if max_n == 0:
        return stack_uneven_shards(shard_images, shard_labels, pad_multiple)
    dtype = imgs[0].dtype
    item_bytes = int(np.prod(imgs[0].shape[1:])) * dtype.itemsize
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    img_ptrs = (u8p * num_agents)(*[x.ctypes.data_as(u8p) for x in imgs])
    lbl_ptrs = (i32p * num_agents)(*[y.ctypes.data_as(i32p) for y in lbls])
    out_img = np.zeros((num_agents, max_n) + imgs[0].shape[1:], dtype=dtype)
    out_lbl = np.zeros((num_agents, max_n), dtype=np.int32)
    rc = lib.fl_pack_uneven(img_ptrs, lbl_ptrs, _ptr(sizes, ctypes.c_int32),
                            num_agents, item_bytes, max_n,
                            out_img.ctypes.data_as(u8p),
                            _ptr(out_lbl, ctypes.c_int32))
    if rc != 0:
        return stack_uneven_shards(shard_images, shard_labels, pad_multiple)
    return AgentShards(out_img, out_lbl, sizes)
