"""TPU-native federated-learning simulator with robust-learning-rate backdoor defense.

A brand-new JAX/XLA/Flax framework with the capabilities of the reference
`TinfoilHat0/Defending-Against-Backdoors-with-Robust-Learning-Rate` (AAAI 2021),
re-designed TPU-first:

- agents are a real parallel axis (``jax.vmap`` on one chip, ``shard_map`` over a
  ``jax.sharding.Mesh`` axis named ``"agents"`` on a slice/pod) instead of the
  reference's sequential Python loop (reference: src/federated.py:68-72);
- aggregation rules (FedAvg / coordinate-median / sign-majority / krum) and the
  robust-learning-rate defense are XLA collectives (``psum`` / ``all_gather``)
  over ICI (reference: src/aggregation.py:48-75 operates on an in-process dict);
- trojan-pattern backdoor injection, including the Distributed Backdoor Attack
  partitioning, is a jit-compiled device-side data transform driven by
  precomputed stamp masks (reference: src/utils.py:160-284 mutates stored
  dataset pixels with Python loops);
- models are Flax modules (reference: src/models.py);
- everything is deterministic under explicit ``jax.random`` keys (the reference
  is unseeded, SURVEY.md section 2.3.12).

Package layout::

    config.py   flag-parity CLI -> frozen dataclass config
    data/       dataset registry, label-sorted partitioner, padded agent stacks
    attack/     trojan pattern mask library + poisoning
    models/     Flax CNN_MNIST / CNN_CIFAR / ResNet-9
    ops/        numeric building blocks (sgd, clipping, aggregation rules, pallas)
    fl/         client local training, server aggregation, round step, eval
    faults/     fault injection: dropout/straggler/corrupt-payload sampling
                + the participation-mask aggregation protocol
    parallel/   mesh construction + shard_map round step
    utils/      metrics writers, checkpointing, misc
"""

__version__ = "0.1.0"

from defending_against_backdoors_with_robust_learning_rate_tpu.config import (  # noqa: F401
    Config,
    args_parser,
)
