"""Client local training — the reference's `Agent.local_train`
(src/agent.py:33-64) as a pure jittable function.

Reference semantics preserved:
- fresh SGD(momentum) state every round (src/agent.py:37; momentum buffer
  starts at zero — SURVEY.md 7.3.4);
- `local_ep` epochs, reshuffled each epoch (DataLoader shuffle=True,
  src/agent.py:28), last batch partial;
- per-minibatch global-grad-norm clip to 10 (src/agent.py:50);
- optional per-minibatch PGD projection of the cumulative update onto the
  L2 ball `clip` (src/agent.py:54-60, inside the batch loop — SURVEY.md 2.3.3);
- dropout active during local training;
- returns the flat update (final - initial); f32 here instead of the
  reference's f64 (SURVEY.md 2.3.2).

TPU-native shape discipline: the agent's shard is padded to `n_batches * bs`;
every agent runs an identical trace (`lax.scan` over epochs x batches). A
random shuffle sorts real samples in front of padding, so batch b's samples
are real iff their shuffled position < size; fully-padded batches are exact
no-ops (masked optimizer step). This function is `vmap`ped over the sampled
agents on one chip and `shard_map`ped over the `agents` mesh axis at scale.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.fl.common import (
    masked_ce)
from defending_against_backdoors_with_robust_learning_rate_tpu.ops import (
    loops, tree)
from defending_against_backdoors_with_robust_learning_rate_tpu.ops.sgd import (
    clip_by_global_norm, pgd_project, sgd_momentum_step)


def make_local_train(model, cfg, normalize):
    """Returns local_train(params0, images, labels, size, key) -> update pytree.

    images: [n_total, H, W, C] raw pixels, n_total a multiple of cfg.bs;
    labels: [n_total] int32; size: scalar int32 true shard size; key: PRNGKey.

    RLR_ABLATE (measurement-only, comma-separated): in-program ablations for
    the round-anatomy ladder (scripts/profile_round.py --ablate) — the ~13 ms
    per-dispatch floor through the TPU tunnel makes standalone micro-probes
    meaningless, so sinks are isolated by differencing FULL-round timings:
      noshuffle  — identity permutation (skips per-epoch uniform+argsort)
      nodropout  — deterministic forward (skips dropout RNG + masks)
      nogather   — ordered contiguous batches (skips the per-step row gather)
    Every ablation CHANGES TRAINING SEMANTICS; never set outside profiling.
    """
    bs = cfg.bs
    ablate = set(filter(None, os.environ.get("RLR_ABLATE", "").split(",")))
    if ablate:
        # loud on purpose: a leftover env var silently corrupts training
        print(f"[ABLATE] local training is running with {sorted(ablate)} "
              f"REMOVED — measurement mode, results are not real training",
              flush=True)

    def _local_train(params0, images, labels, size, key, ep_budget):
        n_total = images.shape[0]
        nb = n_total // bs
        # policy for ops/loops.maybe_unrolled_scan (XLA:CPU conv-in-while
        # slow path): trace short local loops as Python loops on CPU,
        # capped at 16 fwd+bwd steps to keep trace/compile time sane
        py_loops = loops.cpu_backend() and cfg.local_ep * nb <= 16
        params0 = tree.astype(params0, jnp.float32)

        def epoch_body(carry, xs):
            ep_key, ep_idx = xs
            params, mom = carry
            # straggler truncation (faults/): epochs past the agent's budget
            # zero every batch weight, so the already-masked optimizer step
            # (and the loss accumulation) become exact no-ops. When the
            # budget is the static local_ep (no stragglers configured), XLA
            # constant-folds ep_active=True away — the dense path's program
            # is unchanged.
            ep_active = ep_idx < ep_budget
            shuffle_key, drop_key = jax.random.split(ep_key)
            if "noshuffle" in ablate:
                perm = jnp.arange(n_total)  # real samples already in front
            else:
                r = jax.random.uniform(shuffle_key, (n_total,))
                r = jnp.where(jnp.arange(n_total) < size, r, 2.0)
                perm = jnp.argsort(r)      # real samples first, shuffled

            def batch_body(carry, b):
                params, mom = carry
                idx = jax.lax.dynamic_slice(perm, (b * bs,), (bs,))
                if "nogather" in ablate:
                    # remove only the IMAGE row gather; labels still gather
                    # through perm so the shuffle stays live — otherwise XLA
                    # DCEs uniform+argsort along with the gather and the
                    # delta misattributes the shuffle's cost (code review r3)
                    x = jax.lax.dynamic_slice_in_dim(images, b * bs, bs, 0)
                else:
                    x = jnp.take(images, idx, axis=0)
                y = jnp.take(labels, idx, axis=0)
                w = ((b * bs + jnp.arange(bs)) < size) & ep_active

                def loss_fn(p):
                    if "nodropout" in ablate:
                        logits = model.apply({"params": p}, normalize(x),
                                             train=False)
                    else:
                        logits = model.apply(
                            {"params": p}, normalize(x), train=True,
                            rngs={"dropout": jax.random.fold_in(drop_key, b)})
                    return masked_ce(logits, y, w)

                loss, grads = jax.value_and_grad(loss_fn)(params)
                grads = clip_by_global_norm(grads, 10.0)
                w_n = jnp.sum(w)
                params, mom = sgd_momentum_step(
                    params, mom, grads, cfg.client_lr, cfg.client_moment,
                    w_n > 0)
                if cfg.clip > 0:
                    params = pgd_project(params, params0, cfg.clip)
                return (params, mom), (loss * w_n, w_n)

            (params, mom), (loss_sums, w_sums) = loops.maybe_unrolled_scan(
                batch_body, (params, mom), jnp.arange(nb), py_loops)
            # sample-weighted epoch loss: padding batches contribute nothing
            ep_loss = jnp.sum(loss_sums) / jnp.maximum(jnp.sum(w_sums), 1.0)
            return (params, mom), ep_loss

        ep_keys = jax.random.split(key, cfg.local_ep)
        (params, _), ep_losses = loops.maybe_unrolled_scan(
            epoch_body, (params0, tree.zeros_like(params0)),
            (ep_keys, jnp.arange(cfg.local_ep)), py_loops)
        update = tree.sub(params, params0)
        return update, jnp.mean(ep_losses)

    if cfg.straggler_rate > 0:
        # faults path: callers pass a per-agent epoch budget (6th arg)
        return _local_train

    def local_train(params0, images, labels, size, key):
        # dense path: the static full budget constant-folds to a no-op
        return _local_train(params0, images, labels, size, key,
                            jnp.int32(cfg.local_ep))

    return local_train
