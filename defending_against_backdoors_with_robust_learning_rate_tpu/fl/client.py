"""Client local training — the reference's `Agent.local_train`
(src/agent.py:33-64) as a pure jittable function.

Reference semantics preserved:
- fresh SGD(momentum) state every round (src/agent.py:37; momentum buffer
  starts at zero — SURVEY.md 7.3.4);
- `local_ep` epochs, reshuffled each epoch (DataLoader shuffle=True,
  src/agent.py:28), last batch partial;
- per-minibatch global-grad-norm clip to 10 (src/agent.py:50);
- optional per-minibatch PGD projection of the cumulative update onto the
  L2 ball `clip` (src/agent.py:54-60, inside the batch loop — SURVEY.md 2.3.3);
- dropout active during local training;
- returns the flat update (final - initial); f32 here instead of the
  reference's f64 (SURVEY.md 2.3.2).

TPU-native shape discipline: the agent's shard is padded to `n_batches * bs`;
every agent runs an identical trace (`lax.scan` over epochs x batches). A
random shuffle sorts real samples in front of padding, so batch b's samples
are real iff their shuffled position < size; fully-padded batches are exact
no-ops (masked optimizer step). This function is `vmap`ped over the sampled
agents on one chip and `shard_map`ped over the `agents` mesh axis at scale.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.fl.common import (
    masked_ce)
from defending_against_backdoors_with_robust_learning_rate_tpu.ops import (
    loops, tree)
from defending_against_backdoors_with_robust_learning_rate_tpu.ops.sgd import (
    clip_by_global_norm, pgd_project, sgd_momentum_step)


def make_local_train(model, cfg, normalize):
    """Returns local_train(params0, images, labels, size, key) -> update pytree.

    images: [n_total, H, W, C] raw pixels, n_total a multiple of cfg.bs;
    labels: [n_total] int32; size: scalar int32 true shard size; key: PRNGKey.

    RLR_ABLATE (measurement-only, comma-separated): in-program ablations for
    the round-anatomy ladder (scripts/profile_round.py --ablate) — the ~13 ms
    per-dispatch floor through the TPU tunnel makes standalone micro-probes
    meaningless, so sinks are isolated by differencing FULL-round timings:
      noshuffle  — identity permutation (skips per-epoch uniform+argsort)
      nodropout  — deterministic forward (skips dropout RNG + masks)
      nogather   — ordered contiguous batches (skips the per-step row gather)
    Every ablation CHANGES TRAINING SEMANTICS; never set outside profiling.
    """
    bs = cfg.bs
    ablate = set(filter(None, os.environ.get("RLR_ABLATE", "").split(",")))
    if ablate:
        # loud on purpose: a leftover env var silently corrupts training
        print(f"[ABLATE] local training is running with {sorted(ablate)} "
              f"REMOVED — measurement mode, results are not real training",
              flush=True)

    def _local_train(params0, images, labels, size, key, ep_budget):
        n_total = images.shape[0]
        nb = n_total // bs
        # policy for ops/loops.maybe_unrolled_scan (XLA:CPU conv-in-while
        # slow path): trace short local loops as Python loops on CPU,
        # capped at 16 fwd+bwd steps to keep trace/compile time sane
        py_loops = loops.cpu_backend() and cfg.local_ep * nb <= 16
        params0 = tree.astype(params0, jnp.float32)

        def epoch_body(carry, xs):
            ep_key, ep_idx = xs
            params, mom = carry
            # straggler truncation (faults/): epochs past the agent's budget
            # zero every batch weight, so the already-masked optimizer step
            # (and the loss accumulation) become exact no-ops. When the
            # budget is the static local_ep (no stragglers configured), XLA
            # constant-folds ep_active=True away — the dense path's program
            # is unchanged.
            ep_active = ep_idx < ep_budget
            shuffle_key, drop_key = jax.random.split(ep_key)
            if "noshuffle" in ablate:
                perm = jnp.arange(n_total)  # real samples already in front
            else:
                r = jax.random.uniform(shuffle_key, (n_total,))
                r = jnp.where(jnp.arange(n_total) < size, r, 2.0)
                perm = jnp.argsort(r)      # real samples first, shuffled

            def batch_body(carry, b):
                params, mom = carry
                idx = jax.lax.dynamic_slice(perm, (b * bs,), (bs,))
                if "nogather" in ablate:
                    # remove only the IMAGE row gather; labels still gather
                    # through perm so the shuffle stays live — otherwise XLA
                    # DCEs uniform+argsort along with the gather and the
                    # delta misattributes the shuffle's cost (code review r3)
                    x = jax.lax.dynamic_slice_in_dim(images, b * bs, bs, 0)
                else:
                    x = jnp.take(images, idx, axis=0)
                y = jnp.take(labels, idx, axis=0)
                w = ((b * bs + jnp.arange(bs)) < size) & ep_active

                def loss_fn(p):
                    if "nodropout" in ablate:
                        logits = model.apply({"params": p}, normalize(x),
                                             train=False)
                    else:
                        logits = model.apply(
                            {"params": p}, normalize(x), train=True,
                            rngs={"dropout": jax.random.fold_in(drop_key, b)})
                    return masked_ce(logits, y, w)

                loss, grads = jax.value_and_grad(loss_fn)(params)
                grads = clip_by_global_norm(grads, 10.0)
                w_n = jnp.sum(w)
                params, mom = sgd_momentum_step(
                    params, mom, grads, cfg.client_lr, cfg.client_moment,
                    w_n > 0)
                if cfg.clip > 0:
                    params = pgd_project(params, params0, cfg.clip)
                return (params, mom), (loss * w_n, w_n)

            (params, mom), (loss_sums, w_sums) = loops.maybe_unrolled_scan(
                batch_body, (params, mom), jnp.arange(nb), py_loops)
            # sample-weighted epoch loss: padding batches contribute nothing
            ep_loss = jnp.sum(loss_sums) / jnp.maximum(jnp.sum(w_sums), 1.0)
            return (params, mom), ep_loss

        ep_keys = jax.random.split(key, cfg.local_ep)
        (params, _), ep_losses = loops.maybe_unrolled_scan(
            epoch_body, (params0, tree.zeros_like(params0)),
            (ep_keys, jnp.arange(cfg.local_ep)), py_loops)
        update = tree.sub(params, params0)
        return update, jnp.mean(ep_losses)

    if cfg.straggler_rate > 0:
        # faults path: callers pass a per-agent epoch budget (6th arg)
        return _local_train

    def local_train(params0, images, labels, size, key):
        # dense path: the static full budget constant-folds to a no-op
        return _local_train(params0, images, labels, size, key,
                            jnp.int32(cfg.local_ep))

    return local_train


def make_local_train_megabatch(model, cfg, normalize):
    """Megabatched local training (ISSUE 10, `--train_layout megabatch`):
    the whole client block advances through ONE traced step schedule with
    the client axis folded into the batch.

    mb_train(params0, images [m, n, ...], labels [m, n], sizes [m],
             keys [m, ...][, ep_budget [m]]) -> (updates [m, ...]-stacked
    pytree, losses [m]) — the exact output contract of
    `vmap(local_train)` over the same block.

    What folds, per minibatch step (vs the vmap layout's m logical
    [bs, ...] client programs):

    - the minibatch row gather runs ONCE over the [m*n, ...] flattened
      shard block (per-client perm indices offset into one flat index
      space) — one fat gather instead of m;
    - normalize runs over the folded [m*bs, ...] batch, and the
      per-client step masks (padding + straggler truncation) are
      constructed ON the fold as [m, bs] segment weights — each
      client's loss mean, loss mask and step-validity bit all read the
      same segment reduction (row sums of the folded weights), so
      masked-step semantics are preserved arithmetically;
    - the per-client parameter chains advance as ONE stacked [m, ...]
      tree through a shared optimizer tail (global-norm clip, masked
      momentum step, PGD projection — exact per-client arithmetic over
      the stacked trees).

    The model forward/backward stays batched over the client axis —
    per-client parameter chains and per-client dropout key streams make
    a shared-weight flat pass mathematically wrong after the first SGD
    step, and the measured XLA:CPU lowering of a single grad THROUGH
    the client-batched apply hits a ~6x slower grouped-conv backward
    path, so the grads come from the client-batched `value_and_grad`
    (identical math and keys; dropout masks are bit-identical). Parity
    with the vmap layout is ulp-bounded in f32
    (tests/test_megabatch.py). RLR_ABLATE measurement ablations apply
    to the vmap layout only."""
    bs = cfg.bs

    def client_loss(p, x, y, w, r):
        logits = model.apply({"params": p}, x, train=True,
                             rngs={"dropout": r})
        return masked_ce(logits, y, w)

    grad_clients = jax.vmap(jax.value_and_grad(client_loss))

    def client_opt_step(params0):
        """Per-client optimizer tail, vmapped over the stacked chains —
        the same clip/step/project ops the vmap layout runs per client."""
        def step(p, mom, g, valid):
            g = clip_by_global_norm(g, 10.0)
            p, mom = sgd_momentum_step(p, mom, g, cfg.client_lr,
                                       cfg.client_moment, valid)
            if cfg.clip > 0:
                p = pgd_project(p, params0, cfg.clip)
            return p, mom
        return jax.vmap(step, in_axes=(0, 0, 0, 0))

    def _mb_train(params0, images, labels, sizes, keys, ep_budget):
        m, n_total = images.shape[0], images.shape[1]
        nb = n_total // bs
        # same XLA:CPU conv-in-while policy (and cap) as the vmap layout
        py_loops = loops.cpu_backend() and cfg.local_ep * nb <= 16
        params0 = tree.astype(params0, jnp.float32)
        stack0 = tree.map(lambda p: jnp.broadcast_to(p, (m,) + p.shape),
                          params0)
        flat_images = images.reshape((m * n_total,) + images.shape[2:])
        flat_labels = labels.reshape(m * n_total)
        offsets = (jnp.arange(m, dtype=jnp.int32) * n_total)[:, None]
        opt_step = client_opt_step(params0)

        def epoch_body(carry, xs):
            ep_keys, ep_idx = xs              # [m, ...] keys, scalar idx
            params, mom = carry               # [m, ...]-stacked chains
            ep_active = ep_idx < ep_budget    # [m] straggler truncation
            sk_dk = jax.vmap(jax.random.split)(ep_keys)
            shuffle_keys, drop_keys = sk_dk[:, 0], sk_dk[:, 1]
            # per-client shuffle: real samples first, shuffled — the
            # identical draw as the vmap layout (same keys, same ops)
            r = jax.vmap(lambda k: jax.random.uniform(k, (n_total,)))(
                shuffle_keys)
            r = jnp.where(jnp.arange(n_total)[None, :] < sizes[:, None],
                          r, 2.0)
            perms = jnp.argsort(r, axis=1)    # [m, n_total]

            def batch_body(carry, b):
                params, mom = carry
                idx = jax.lax.dynamic_slice_in_dim(perms, b * bs, bs, 1)
                flat_idx = (idx + offsets).reshape(m * bs)
                # ONE gather over the flat [m*n, ...] block, normalized
                # as one [m*bs, ...] megabatch (elementwise — identical
                # values to the per-client pipeline)
                x = normalize(jnp.take(flat_images, flat_idx, axis=0))
                y = jnp.take(flat_labels, flat_idx, axis=0)
                w = ((b * bs + jnp.arange(bs))[None, :] < sizes[:, None]) \
                    & ep_active[:, None]      # [m, bs] segment weights
                rngs = jax.vmap(jax.random.fold_in, in_axes=(0, None))(
                    drop_keys, b)
                # client-batched fwd/bwd over the folded rows (see the
                # builder docstring for why the grad is NOT a single
                # grad-of-vmap); per-client means come out per segment
                per_client, grads = grad_clients(
                    params, x.reshape((m, bs) + x.shape[1:]),
                    y.reshape(m, bs), w, rngs)
                # segment reduction of the folded step masks: the same
                # [m] weights drive the loss bookkeeping AND the
                # masked-step validity bit
                w_n = jnp.sum(w.astype(jnp.float32), axis=1)
                params, mom = opt_step(params, mom, grads, w_n > 0)
                return (params, mom), (per_client * w_n, w_n)

            (params, mom), (loss_sums, w_sums) = loops.maybe_unrolled_scan(
                batch_body, (params, mom), jnp.arange(nb), py_loops)
            ep_loss = (jnp.sum(loss_sums, axis=0)
                       / jnp.maximum(jnp.sum(w_sums, axis=0), 1.0))
            return (params, mom), ep_loss

        ep_keys = jax.vmap(
            lambda k: jax.random.split(k, cfg.local_ep))(keys)
        (params, _), ep_losses = loops.maybe_unrolled_scan(
            epoch_body, (stack0, tree.zeros_like(stack0)),
            (jnp.swapaxes(ep_keys, 0, 1), jnp.arange(cfg.local_ep)),
            py_loops)
        return tree.sub(params, stack0), jnp.mean(ep_losses, axis=0)

    if cfg.straggler_rate > 0:
        # faults path: callers pass the per-client epoch budgets (6th arg)
        return _mb_train

    def mb_train(params0, images, labels, sizes, keys):
        # dense path: the static full budget constant-folds away
        return _mb_train(params0, images, labels, sizes, keys,
                         jnp.full((images.shape[0],), cfg.local_ep,
                                  jnp.int32))

    return mb_train
