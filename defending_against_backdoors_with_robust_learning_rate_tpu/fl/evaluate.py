"""Jitted evaluation: loss, accuracy, per-class accuracy.

Reference: `get_loss_n_accuracy` (src/utils.py:128-157) — batch loop with a
Python double-loop confusion matrix (the slowest part of the reference's
eval, SURVEY.md 3.5). Here the confusion matrix is a scatter-add inside a
`lax.scan` over fixed-shape batches; padding samples carry weight 0. The
10-class hardcoding is kept for parity (SURVEY.md 2.3.7)."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from defending_against_backdoors_with_robust_learning_rate_tpu.ops import loops


def pad_eval_set(images: np.ndarray, labels: np.ndarray, bs: int
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad to a multiple of bs and reshape to [nb, bs, ...] + weight mask."""
    n = len(labels)
    nb = max(1, -(-n // bs))
    pad = nb * bs - n
    if pad:
        images = np.concatenate([images, np.zeros((pad,) + images.shape[1:],
                                                  images.dtype)])
        labels = np.concatenate([labels, np.zeros((pad,), labels.dtype)])
    w = (np.arange(nb * bs) < n).astype(np.float32)
    return (images.reshape((nb, bs) + images.shape[1:]),
            labels.reshape(nb, bs).astype(np.int32),
            w.reshape(nb, bs))


def make_eval_fn(model, normalize, n_classes: int = 10):
    """Returns eval_fn(params, images[nb,bs,...], labels[nb,bs], w[nb,bs])
    -> (avg_loss, accuracy, per_class_accuracy[n_classes])."""

    @jax.jit
    def eval_fn(params, images, labels, weights):
        def body(carry, batch):
            loss_sum, correct, conf = carry
            x, y, w = batch
            logits = model.apply({"params": params}, normalize(x), train=False)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, y)
            pred = jnp.argmax(logits, axis=-1)
            loss_sum = loss_sum + jnp.sum(ce * w)
            correct = correct + jnp.sum((pred == y) * w)
            conf = conf.at[y, pred].add(w)
            return (loss_sum, correct, conf), None

        init = (jnp.float32(0.0), jnp.float32(0.0),
                jnp.zeros((n_classes, n_classes), jnp.float32))
        # XLA:CPU conv-in-while slow path (ops/loops.py): unroll short eval
        # loops; the cap is higher than local training's (32 vs 16) because
        # the fwd-only body is ~3x cheaper to trace/compile per step
        py_loops = loops.cpu_backend() and images.shape[0] <= 32
        (loss_sum, correct, conf), _ = loops.maybe_unrolled_scan(
            body, init, (images, labels, weights), py_loops)
        n = jnp.sum(weights)
        per_class = jnp.diag(conf) / jnp.maximum(jnp.sum(conf, axis=1), 1.0)
        # f32 rounding can push correct/n a hair above 1.0 (round-1
        # results.json recorded poison_acc=1.0000001); clamp the ratios.
        acc = jnp.clip(correct / n, 0.0, 1.0)
        return loss_sum / n, acc, jnp.clip(per_class, 0.0, 1.0)

    return eval_fn
