"""Multi-tenant tenant-pack round programs — the EXPERIMENT axis folded
into one resident program (ISSUE 13).

The scenario matrix (scripts/sweep_scenarios.py) is thousands of small
cells, and the experiment queue used to run them strictly back-to-back:
one small CNN per dispatch leaves the chip idle exactly the way
per-client vmap did before the PR-10 megabatch. This module applies the
megabatch trick one level up — the Podracer play (arXiv:2104.06272:
saturate accelerators by stacking many small workloads into one resident
program): E independent experiment replicas that SHARE program shapes
(same dataset, model, aggregation rule, fault/churn/attack structure)
run as a leading tenant axis of ONE jitted round program. Per-tenant
params advance as a stacked [E, ...] pytree; cohorts are sampled, locally
trained, fault-injected and aggregated together; metrics fan back out per
tenant through the existing MetricsDrain (service/tenancy.py).

What varies per tenant — the *scalar knobs* — enters as traced
[E]-vectors (`TenantKnobs`), so one compiled program serves the whole
pack AND every pack of the same shape:

    seed          per-tenant base key stream (params init + sampling +
                  training keys; keys are program ARGUMENTS, like solo)
    server_lr     the effective server LR (the aggr=='sign' rule is
                  resolved per tenant host-side)
    robustLR_threshold   the RLR vote threshold (a pack mixing defended
                  and undefended tenants builds the vote once; a tenant
                  with threshold 0 gets lr=+server_lr on every
                  coordinate — arithmetically the undefended update)
    attack_boost / attack_start / attack_stop / attack_every
                  the in-jit attack scale + schedule window
                  (attack/schedule.active_traced; the trivial (0, 0, 1)
                  triple evaluates to always-on)

Knobs that change SHAPES or program structure (dataset, m, bs, aggr,
telemetry level, fault rates, churn process, attack strategy, layouts)
stay queue-level: the pack key (utils/compile_cache.tenant_pack_key) is
derived from the AOT fingerprint's own field algebra, so shape- or
program-incompatible cells can never share a pack.

Exactness semantics: the tenant programs run the SAME ops with the same
keys as the solo paths — per-tenant metrics are ulp-close to solo runs
(vmap batching may re-associate reductions), and integer sign-vote
arithmetic is exact where the megabatch precedent pins it. Dataset
CONTENT is built once from the pack's base config: for disk-backed
datasets it is seed-free; the synthetic fallback draws from the base
seed, so per-tenant seeds vary the key streams, not the data
(tests/test_tenancy.py pins the parity contract).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
    CHAINED_INFO_KEYS, _round_core, host_takes_flags, make_block_trainer)
from defending_against_backdoors_with_robust_learning_rate_tpu.ops import loops

# the per-tenant scalar knobs — Config fields a tenant pack vectorizes as
# traced [E]-arrays. Everything else must agree across the pack
# (utils/compile_cache.tenant_pack_key drops exactly this set, plus the
# runtime fields, from the grouping key).
TENANT_KNOB_FIELDS = ("seed", "server_lr", "robustLR_threshold",
                      "attack_boost", "attack_start", "attack_stop",
                      "attack_every")


class TenantKnobs(NamedTuple):
    """The traced per-tenant scalar knobs, one [E]-vector per field (a
    scalar per field inside the tenant vmap). A NamedTuple so it is a
    pytree with a FIXED structure — the AOT fingerprint's arg avals stay
    stable across packs of the same width.

    ``rnd_offset`` is not a Config field: it is the scheduler's slot
    clock (service/scheduler.py). A cell backfilled into slot e at pack
    round p runs with offset -p, so its EFFECTIVE round index
    (rnd + offset) counts 1..rounds exactly like its solo twin — key
    folds, churn lifecycle and attack schedules all consume the
    effective index, keeping backfilled streams solo-exact. Every
    FIFO-path pack runs offset 0, which is arithmetically the historical
    program."""
    server_lr: jnp.ndarray      # [E] f32, the EFFECTIVE server lr
    rlr_threshold: jnp.ndarray  # [E] f32 (0 = undefended tenant)
    attack_boost: jnp.ndarray   # [E] f32
    attack_start: jnp.ndarray   # [E] i32
    attack_stop: jnp.ndarray    # [E] i32
    attack_every: jnp.ndarray   # [E] i32
    rnd_offset: jnp.ndarray     # [E] i32, slot clock (0 = pack clock)


def knob_vectors(cells, rnd_offsets=None) -> TenantKnobs:
    """Stack the E cell configs' scalar knobs into the traced vectors.
    The aggr=='sign' server-LR rule (config.effective_server_lr) is
    resolved here, per tenant, host-side. ``rnd_offsets`` is the
    scheduler's per-slot clock skew (None = the FIFO pack's zeros)."""
    E = len(cells)
    if rnd_offsets is None:
        rnd_offsets = [0] * E
    return TenantKnobs(
        server_lr=np.asarray([c.effective_server_lr for c in cells],
                             np.float32),
        rlr_threshold=np.asarray([float(c.robustLR_threshold)
                                  for c in cells], np.float32),
        attack_boost=np.asarray([c.attack_boost for c in cells],
                                np.float32),
        attack_start=np.asarray([c.attack_start for c in cells], np.int32),
        attack_stop=np.asarray([c.attack_stop for c in cells], np.int32),
        attack_every=np.asarray([c.attack_every for c in cells], np.int32),
        rnd_offset=np.asarray(rnd_offsets, np.int32),
    )


def knob_avals(E: int) -> TenantKnobs:
    """Abstract avals of the knob vectors for the AOT planners."""
    f32 = lambda: jax.ShapeDtypeStruct((E,), jnp.float32)  # noqa: E731
    i32 = lambda: jax.ShapeDtypeStruct((E,), jnp.int32)    # noqa: E731
    return TenantKnobs(server_lr=f32(), rlr_threshold=f32(),
                       attack_boost=f32(), attack_start=i32(),
                       attack_stop=i32(), attack_every=i32(),
                       rnd_offset=i32())


def canonical_rep(cfg, cells=None):
    """Normalize a pack-representative config: the knob fields collapse to
    canonical values so two packs differing only in knob VALUES share one
    program (and one AOT fingerprint). The only structural bit a knob
    carries — is the RLR vote built at all — survives as threshold 0/1,
    derived from the pack's cells when given."""
    rlr_on = (cfg.robustLR_threshold > 0 if cells is None
              else any(c.robustLR_threshold > 0 for c in cells))
    return cfg.replace(seed=0, server_lr=1.0,
                       robustLR_threshold=1 if rlr_on else 0,
                       attack_boost=1.0, attack_start=0, attack_stop=0,
                       attack_every=1)


def check(cfg) -> None:
    """Validate a tenant-pack rep config once, loudly, at engine/planner
    construction. Every refusal names its remediation — the queue's
    grouping (service/tenancy.py) routes ineligible cells to the serial
    path instead of crashing the pack."""
    if cfg.tenants < 1:
        raise ValueError(f"a tenant pack needs --tenants >= 1, got "
                         f"{cfg.tenants}")
    # E=1 is the degenerate pack — bit-identity with the untenanted path
    # is test-pinned (tests/test_tenancy.py); the queue still routes
    # singletons through the serial path (no packing win to pay for)
    reason = ineligible_reason(cfg)
    if reason:
        raise ValueError(f"--tenants {cfg.tenants}: {reason}")


def ineligible_reason(cfg) -> str:
    """Why this config's PROGRAM cannot be tenant-packed ('' = eligible).
    The tenant programs cover the device-resident sync surface (faults,
    churn, attacks and telemetry included); everything else keeps its
    solo path. Runtime/driver knobs (host_sampled, mesh) are judged by
    the queue's routing (service/tenancy.serial_reason) — this module is
    in the fingerprint audit's program-read scope and only consults
    program-tagged fields."""
    if cfg.diagnostics:
        return ("--diagnostics needs the per-tenant research scalars the "
                "pack never materializes; run those cells solo")
    if cfg.use_pallas:
        return ("--use_pallas bakes threshold/server_lr as kernel "
                "constants; the pack's per-tenant knobs are traced — "
                "run pallas cells solo")
    if cfg.debug_nan:
        return "--debug_nan (checkify) runs solo"
    # buffered (agg_mode) packs stack the carried (params, state) buffer
    # as a leading [E] axis (ISSUE 16); cohort-sampled packs share ONE
    # bank gather across tenants (the cohort draw is cohort_seed-driven,
    # identical for every tenant at the same effective round) — both are
    # pack-eligible now. The cohort constraint — rnd_offset must be 0 so
    # the shared draw stays shared — is a SCHEDULER admission rule
    # (service/scheduler.py never backfills a cohort pack mid-run), not a
    # program refusal.
    return ""


# --------------------------------------------------------------- programs ---

def make_tenant_step(cfg, model, normalize):
    """The per-tenant solo body the tenant vmap batches:
    step(carry, key, rnd, knobs, images, labels, sizes) ->
    (carry, info). Identical ops and key derivation as
    fl/rounds._make_sample_step's body — that is what makes per-tenant
    results ulp-close to solo runs — with the scalar knobs arriving
    traced instead of baked (fl/rounds._round_core `knobs`). Always takes
    the round index: the churn lifecycle and the per-tenant schedule
    gates consume it, and an unused lead argument is free.

    Two ISSUE-16 extensions, both no-ops on the historical path:
    * the tenant runs on its EFFECTIVE clock rnd + knobs.rnd_offset —
      churn lifecycle and attack schedule gates see the tenant's own
      round index, so a cell backfilled mid-pack is solo-exact
      (offset 0 is arithmetically the old program);
    * buffered mode carries (params, buffer state) as the step carry —
      fold_commit consumes the per-tenant knobs, and the vmapped carry
      stacks both halves along the tenant axis."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
        buffered)
    train_block = make_block_trainer(model, cfg, normalize)
    K, m = cfg.num_agents, cfg.agents_per_round
    want_flags = host_takes_flags(cfg)
    is_async = buffered.is_buffered(cfg)

    def step(carry, key, rnd, knobs, images, labels, sizes):
        params, astate = carry if is_async else (carry, None)
        rnd = rnd + knobs.rnd_offset  # the tenant's own round index
        k_sample, k_train, k_noise = jax.random.split(key, 3)
        with jax.named_scope("sample_gather"):
            sampled = jax.random.permutation(k_sample, K)[:m]
            imgs = jnp.take(images, sampled, axis=0)
            lbls = jnp.take(labels, sampled, axis=0)
            szs = jnp.take(sizes, sampled, axis=0)
        churn_active = None
        if cfg.churn_enabled:
            from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
                churn as churn_mod)
            with jax.named_scope("churn_mask"):
                churn_active = churn_mod.active_slots(cfg, sampled, rnd)
        result = _round_core(
            params, k_train, k_noise, imgs, lbls, szs,
            train_block=train_block, cfg=cfg,
            corrupt_flags=(sampled < cfg.num_corrupt
                           if want_flags else None),
            churn_active=churn_active, rnd=rnd, astate=astate, knobs=knobs)
        if is_async:
            new_params, train_loss, extras, new_astate = result
            return (new_params, new_astate), {
                "train_loss": train_loss, "sampled": sampled, **extras}
        new_params, train_loss, extras = result
        return new_params, {"train_loss": train_loss, "sampled": sampled,
                            **extras}

    return step


def _vmap_step(step):
    """Batch the solo body over the leading tenant axis: params/key/knobs
    map per tenant, the round index and the dataset stacks broadcast."""
    return jax.vmap(step, in_axes=(0, 0, None, 0, None, None, None))


def make_tenant_round_fn(cfg, model, normalize, images, labels, sizes):
    """Tenant-pack per-round fn:
    round(params_E, keys_E, rnd, knobs) -> (params_E, info) with info
    leaves [E]-stacked. Dataset stacks are jit ARGUMENTS bound at call
    time (the fl/rounds.bind_data discipline — closure arrays inline into
    the lowered HLO as dense constants)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    vstep = jax.jit(_vmap_step(make_tenant_step(cfg, model, normalize)))

    def bound(params_E, keys_E, rnd, knobs):
        return vstep(params_E, keys_E, rnd, knobs, images, labels, sizes)

    bound.jitted, bound.data = vstep, (images, labels, sizes)
    bound.family = "round" + compile_cache.family_suffix(cfg)
    return bound


def make_tenant_chained_fn(cfg, model, normalize, images, labels, sizes):
    """Tenant-pack chained block:
    chained(params_E, base_keys_E, round_ids, knobs) — a `lax.scan` over
    rounds of the tenant-vmapped body; round r's per-tenant key is
    `fold_in(base_key_e, r + rnd_offset_e)`, the driver loop's exact
    derivation at the tenant's EFFECTIVE round, so a chained pack matches
    dispatching the same pack rounds one at a time (and a backfilled
    tenant's key stream matches its solo twin). The carry — params_E, or
    (params_E, astate_E) in buffered mode — is donated (the
    chained-family contract, analysis/contracts.DONATED_FAMILIES)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    vstep = _vmap_step(make_tenant_step(cfg, model, normalize))

    @functools.partial(jax.jit, donate_argnums=0)
    def chained(params_E, base_keys_E, round_ids, knobs,
                images, labels, sizes):
        def body(params_E, rnd):
            keys = jax.vmap(
                lambda k, off: jax.random.fold_in(k, rnd + off))(
                base_keys_E, knobs.rnd_offset)
            new_params, info = vstep(params_E, keys, rnd, knobs,
                                     images, labels, sizes)
            out = {"train_loss": info["train_loss"],
                   "sampled": info["sampled"]}
            out.update({k: info[k] for k in CHAINED_INFO_KEYS if k in info})
            out.update({k: v for k, v in info.items()
                        if k.startswith(("tel_", "hlth_", "rep_"))})
            return new_params, out

        # XLA:CPU conv-in-while slow path (ops/loops.py): unroll short
        # chains, same cap as the solo chained families
        py_loops = loops.cpu_backend() and round_ids.shape[0] <= 16
        return loops.maybe_unrolled_scan(body, params_E, round_ids,
                                         py_loops)

    def bound(params_E, base_keys_E, round_ids, knobs):
        return chained(params_E, base_keys_E, round_ids, knobs,
                       images, labels, sizes)

    bound.jitted, bound.data = chained, (images, labels, sizes)
    bound.family = "chained" + compile_cache.family_suffix(cfg)
    return bound


def make_tenant_cohort_step(cfg, model, normalize):
    """Per-tenant cohort-sampled body the tenant vmap batches:
    step(carry, key, rnd, knobs, imgs, lbls, sizes) -> (carry, info) —
    fl/rounds.make_cohort_step with the knobs traced (ISSUE 16 gap 3).

    Data arrives as the SHARED [m, ...] cohort stacks, host-gathered ONCE
    per round for the whole pack (vmap broadcasts them): the cohort draw
    (data/cohort.sample_cohort) is cohort_seed-driven — NOT a knob field —
    so every tenant at the same effective round draws the same ids, and
    one indexed bank gather on the prefetch thread serves all E tenants.
    That is also why cohort packs admit no mid-run backfill: a nonzero
    rnd_offset would skew one tenant's draw away from the shared gather
    (service/scheduler.py pins cohort-pack offsets to 0; the in-program
    draw still consumes the effective round so the invariant is 'offsets
    equal', degrading loudly in parity tests rather than silently)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
        cohort as cohort_mod)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
        buffered)
    from defending_against_backdoors_with_robust_learning_rate_tpu.health import (
        sentinel as health_sentinel)
    train_block = make_block_trainer(model, cfg, normalize)
    want_flags = host_takes_flags(cfg)
    is_async = buffered.is_buffered(cfg)

    def step(carry, key, rnd, knobs, imgs, lbls, sizes):
        params, astate = carry if is_async else (carry, None)
        rnd = rnd + knobs.rnd_offset
        with jax.named_scope("cohort_sample"):
            ids, active = cohort_mod.sample_cohort(cfg, rnd)
        if health_sentinel.has_quarantine(cfg):
            active = active & health_sentinel.quarantine_mask(cfg, ids)
        k_train, k_noise = jax.random.split(key)
        res = _round_core(
            params, k_train, k_noise, imgs, lbls, sizes,
            train_block=train_block, cfg=cfg,
            corrupt_flags=((ids < cfg.num_corrupt) & active
                           if want_flags else None),
            churn_active=active, rnd=rnd, astate=astate, knobs=knobs)
        if is_async:
            new_params, train_loss, extras, new_astate = res
            return ((new_params, new_astate),
                    {"train_loss": train_loss, "sampled": ids, **extras})
        new_params, train_loss, extras = res
        return new_params, {"train_loss": train_loss, "sampled": ids,
                            **extras}

    step.takes_round = True
    return step


def make_tenant_cohort_round_fn(cfg, model, normalize):
    """Tenant-pack cohort round fn:
    round(carry_E, keys_E, rnd, knobs, imgs, lbls, sizes) with the
    cohort stacks broadcast across tenants (gathered once per round by
    the engine's prefetch thread). Data is NOT bound here — cohort rows
    change every round, so they stay call-time arguments exactly like the
    solo cohort path."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    vstep = jax.jit(_vmap_step(make_tenant_cohort_step(cfg, model,
                                                       normalize)))

    def bound(carry_E, keys_E, rnd, knobs, imgs, lbls, sizes):
        return vstep(carry_E, keys_E, rnd, knobs, imgs, lbls, sizes)

    bound.jitted = vstep
    bound.family = "round_cohort" + compile_cache.family_suffix(cfg)
    return bound


def make_tenant_eval_fn(model, normalize, n_classes: int = 10):
    """Tenant-stacked eval: eval(params_E, images, labels, weights) ->
    ([E] loss, [E] acc, [E, n_classes] per-class) — ONE dispatch
    evaluates the whole pack on the shared (broadcast) eval set."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.evaluate import (
        make_eval_fn)
    eval_fn = make_eval_fn(model, normalize, n_classes)
    # vmap traces THROUGH the inner jit; the outer jit is the dispatch
    return jax.jit(jax.vmap(eval_fn, in_axes=(0, None, None, None)))


def stack_params(solo_params_list):
    """[E x solo pytree] -> one [E, ...]-stacked pytree (per-tenant params
    initialized from each tenant's own seed, bitwise the solo init)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *solo_params_list)


def tenant_slice(tree, e: int):
    """Index one tenant's slice out of an [E, ...]-stacked pytree of
    host-fetched values (the metrics fan-out's counterpart to
    `stack_params`)."""
    return jax.tree_util.tree_map(lambda x: x[e], tree)
