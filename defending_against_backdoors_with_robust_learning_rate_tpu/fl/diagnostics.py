"""Research diagnostics — the reference's C13 subsystem re-built jit-first.

Reference: aggregation.py:77-191 (commented out of the round loop at
aggregation.py:43-44; controlled by --top_frac). Components:

- `clip_updates`  (aggregation.py:77-81): server-side L2 clip of each agent
  update to `clip` — never called in the reference; provided for completeness.
- update-norm logging (`plot_norms`, aggregation.py:83-100): average L2 of
  honest vs corrupt updates, scalars `Norms/Avg_Honest_L2` /
  `Norms/Avg_Corrupt_L2`.
- `fisher_diag` (`comp_diag_fisher`, aggregation.py:102-129): diagonal Fisher
  information over the poisoned val set. Quirk preserved: despite computing
  log_softmax, the reference differentiates the *raw target logits*
  (aggregation.py:121-124 gathers from `outputs`, not `log_all_probs`); we do
  the same. `adv=False` relabels everything to `base_class`
  (aggregation.py:117-118). Per-batch squared grads are accumulated divided
  by the dataset size.
- `sign_agreement` (`plot_sign_agreement`, aggregation.py:132-191): ranks
  parameters by adversarial vs honest Fisher mass, intersects the top
  `top_frac` with the RLR-maximized/minimized coordinate sets, and logs seven
  `Sign/*` L2 scalars plus the cumulative net movement.

The Fisher pass is a jitted `lax.scan` (no Python batch loop); the set
algebra runs host-side at `snap` cadence on flat vectors (ravel_pytree at
this analysis boundary only).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.ops import tree


def clip_updates(stacked_updates, clip: float):
    """Server-side per-agent L2 clip (aggregation.py:77-81):
    u <- u / max(1, ||u||/clip), per agent row."""
    denom = jnp.maximum(1.0, per_agent_norms(stacked_updates) / clip)  # [m]

    def leaf(u):
        shape = (-1,) + (1,) * (u.ndim - 1)
        return u / denom.reshape(shape)
    return tree.map(leaf, stacked_updates)


def per_agent_norms(stacked_updates):
    """[m] L2 norms of the stacked agent updates (plot_norms input)."""
    def leaf_sq(u):
        return jnp.sum(jnp.square(u.reshape(u.shape[0], -1)), axis=1)
    sq = sum(leaf_sq(u) for u in jax.tree_util.tree_leaves(stacked_updates))
    return jnp.sqrt(sq)


def norm_scalars(norms, sampled_ids, num_corrupt: int) -> Dict[str, float]:
    """Average honest/corrupt update norms (aggregation.py:83-100); the
    corrupt set is `sampled id < num_corrupt` (agent.py:19)."""
    norms = np.asarray(norms)
    corrupt = np.asarray(sampled_ids) < num_corrupt
    out = {}
    if (~corrupt).any():
        out["Norms/Avg_Honest_L2"] = float(norms[~corrupt].mean())
    if corrupt.any():
        out["Norms/Avg_Corrupt_L2"] = float(norms[corrupt].mean())
    return out


def make_fisher_fn(model, normalize):
    """fisher(params, images[nb,bs,...], labels[nb,bs], w[nb,bs]) -> pytree of
    diagonal Fisher estimates (aggregation.py:102-129 semantics)."""

    @jax.jit
    def fisher(params, images, labels, weights):
        n = jnp.sum(weights)

        def batch_grad_sq(carry, batch):
            x, y, w = batch

            def target_logit_sum(p):
                logits = model.apply({"params": p}, normalize(x), train=False)
                picked = jnp.take_along_axis(logits, y[:, None], axis=1)[:, 0]
                return jnp.sum(picked * w)

            g = jax.grad(target_logit_sum)(params)
            carry = tree.map(lambda c, gi: c + jnp.square(gi) / n, carry, g)
            return carry, None

        init = tree.zeros_like(params)
        out, _ = jax.lax.scan(batch_grad_sq, init, (images, labels, weights))
        return out

    return fisher


def sign_agreement(lr_flat: np.ndarray, update_flat: np.ndarray,
                   fisher_adv_flat: np.ndarray, fisher_hon_flat: np.ndarray,
                   top_frac: int, server_lr: float,
                   cum_net_mov: float) -> Tuple[Dict[str, float], float]:
    """The Sign/* scalar family (aggregation.py:132-191). Returns
    (scalars, new_cum_net_mov)."""
    n_idxs = top_frac
    adv_top = np.argsort(fisher_adv_flat)[-n_idxs:]
    hon_top = np.argsort(fisher_hon_flat)[-n_idxs:]
    min_idxs = np.nonzero(lr_flat == -server_lr)[0]
    max_idxs = np.nonzero(lr_flat == server_lr)[0]

    max_adv = np.intersect1d(adv_top, max_idxs)
    max_hon = np.intersect1d(hon_top, max_idxs)
    min_adv = np.intersect1d(adv_top, min_idxs)
    min_hon = np.intersect1d(hon_top, min_idxs)

    def l2(idxs_a, idxs_b):
        only = np.setdiff1d(idxs_a, idxs_b)
        return float(np.linalg.norm(update_flat[only]))

    max_adv_l2 = l2(max_adv, max_hon)
    max_hon_l2 = l2(max_hon, max_adv)
    min_adv_l2 = l2(min_adv, min_hon)
    min_hon_l2 = l2(min_hon, min_adv)

    net_adv = max_adv_l2 - min_adv_l2
    net_hon = max_hon_l2 - min_hon_l2
    cum_net_mov += net_hon - net_adv
    scalars = {
        "Sign/Hon_Maxim_L2": max_hon_l2,
        "Sign/Adv_Maxim_L2": max_adv_l2,
        "Sign/Adv_Minim_L2": min_adv_l2,
        "Sign/Hon_Minim_L2": min_hon_l2,
        "Sign/Adv_Net_L2": net_adv,
        "Sign/Hon_Net_L2": net_hon,
        "Sign/Model_Net_L2_Cumulative": cum_net_mov,
    }
    return scalars, cum_net_mov
