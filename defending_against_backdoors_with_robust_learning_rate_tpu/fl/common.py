"""Shared pieces of the train/eval compute path."""

from __future__ import annotations

import jax.numpy as jnp
import optax


def make_normalizer(mean, std, raw_is_normalized: bool):
    """Raw pixels -> model input. For uint8 datasets this is ToTensor+Normalize
    (x/255 - mean)/std with the reference constants (src/utils.py:101,113-116);
    fedemnist inputs are already normalized floats (identity)."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)

    def norm(x):
        x = x.astype(jnp.float32)
        if raw_is_normalized:
            return x
        return (x / 255.0 - mean) / std
    return norm


def masked_ce(logits, labels, weights):
    """Cross-entropy mean over the real (unpadded) samples of a batch —
    matches nn.CrossEntropyLoss's batch mean (src/agent.py:47) when the batch
    is partially padding."""
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    w = weights.astype(jnp.float32)
    return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)


def masked_ce_segments(logits, labels, weights, num_segments):
    """`masked_ce` over a client-folded [m*bs, ...] megabatch (ISSUE 10):
    ONE cross-entropy pass over the flat batch, then the per-client
    means recovered by segment-sum over the batch axis. Segments are
    the m equal [bs]-sized client blocks of the fold, so the
    segment-sum specializes to a reshape + row reduction.

    Per-client step masks (padding, straggler truncation) arrive
    already folded into `weights`, so a masked-out sample contributes
    nothing to its client's mean — the same arithmetic as the
    per-client `masked_ce`, reorganized (reduction order may differ at
    the ulp level).

    NOTE this is the LOSS-side fold only: differentiating one summed
    loss through the client-batched apply measured ~6x slower on
    XLA:CPU (grouped-conv backward), so fl/client.py's megabatch
    trainer takes its grads from the client-batched `value_and_grad`
    and uses this reduction for parity oracles and loss bookkeeping.

    Returns (total_loss, per_client_loss [m], per_client_weight [m])."""
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    w = weights.astype(jnp.float32)
    seg_ce = jnp.sum((ce * w).reshape(num_segments, -1), axis=1)
    seg_w = jnp.sum(w.reshape(num_segments, -1), axis=1)
    per_client = seg_ce / jnp.maximum(seg_w, 1.0)
    return jnp.sum(per_client), per_client, seg_w
