"""Shared pieces of the train/eval compute path."""

from __future__ import annotations

import jax.numpy as jnp
import optax


def make_normalizer(mean, std, raw_is_normalized: bool):
    """Raw pixels -> model input. For uint8 datasets this is ToTensor+Normalize
    (x/255 - mean)/std with the reference constants (src/utils.py:101,113-116);
    fedemnist inputs are already normalized floats (identity)."""
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)

    def norm(x):
        x = x.astype(jnp.float32)
        if raw_is_normalized:
            return x
        return (x / 255.0 - mean) / std
    return norm


def masked_ce(logits, labels, weights):
    """Cross-entropy mean over the real (unpadded) samples of a batch —
    matches nn.CrossEntropyLoss's batch mean (src/agent.py:47) when the batch
    is partially padding."""
    ce = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    w = weights.astype(jnp.float32)
    return jnp.sum(ce * w) / jnp.maximum(jnp.sum(w), 1.0)
