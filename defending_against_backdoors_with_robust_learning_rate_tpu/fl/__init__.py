from defending_against_backdoors_with_robust_learning_rate_tpu.fl.client import (  # noqa: F401
    make_local_train,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (  # noqa: F401
    make_round_fn,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.evaluate import (  # noqa: F401
    make_eval_fn,
    pad_eval_set,
)
