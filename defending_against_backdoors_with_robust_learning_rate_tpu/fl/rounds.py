"""The FL round step — one jitted function per round.

Reference: the round loop body of src/federated.py:65-74 (sequential Python
loop over sampled agents, dict of updates, in-process aggregation). Here the
whole round is ONE compiled XLA program: client sampling
(`jax.random.permutation`, replacing the unseeded np.random.choice at
src/federated.py:68), a `vmap` over the m sampled agents' local training, the
aggregation rule + RLR defense, and the global parameter update. No snapshot/
restore dance (src/federated.py:66-72) is needed because local training is a
pure function of the global params.

Two data modes:
- device-resident (fmnist/cifar10): all K agent shards live in HBM; the
  sampled m shards are gathered *inside* jit.
- host-sampled (fedemnist, 3383 users): the driver gathers the sampled
  shards on host and feeds them as arguments (fixed [m, ...] shapes, so one
  compilation serves every round).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
    buffered)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.client import (
    make_local_train, make_local_train_megabatch)
from defending_against_backdoors_with_robust_learning_rate_tpu.health import (
    sentinel as health_sentinel)
from defending_against_backdoors_with_robust_learning_rate_tpu.ops import loops
from defending_against_backdoors_with_robust_learning_rate_tpu.ops.aggregate import (
    aggregate_updates, apply_aggregate, robust_lr)

# fault observability scalars (faults/model.fault_scalars) that chained
# blocks carry through their lax.scan alongside train_loss
FAULT_INFO_KEYS = ("fault_dropped", "fault_straggled", "fault_voters")
# everything a chained scan carries per-round besides train_loss/tel_*:
# the fault counters, the churn away count (service/churn.py) and the
# buffered-async fill/commit/staleness scalars (fl/buffered.py)
CHAINED_INFO_KEYS = (FAULT_INFO_KEYS + ("churn_away",)
                     + buffered.ASYNC_INFO_KEYS)


def _pallas_applicable(cfg) -> bool:
    """The fused Pallas server step covers the (weighted-FedAvg or signSGD
    [+ RLR], no server noise) paths — the paper's headline configurations.
    Diagnostics need the explicit lr tree, which the fused kernel never
    materializes; the faults path — and the churn path, which rides the
    same participation mask — needs the mask threaded through the vote,
    which the fused kernel does not take; defense telemetry
    (obs/telemetry.py) likewise needs the explicit lr/aggregate trees, so
    any --telemetry level falls back to the jnp path."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    # cohort-sampled rounds always carry the active mask (duplicate /
    # churn-absent padding slots must be excluded from aggregation), which
    # the fused kernel does not take — same fallback as faults/churn.
    # In-jit attack strategies transform the updates BEFORE the server
    # step, which the fused kernel's one-pass read would skip.
    # tenant packs (fl/tenancy.py) carry per-tenant thresholds/LRs as
    # traced knobs, which the fused kernel bakes as Python floats
    # a quarantine set (health/monitor.py QUARANTINE rung) rides the
    # participation mask, which the fused kernel does not take — same
    # fallback as faults/churn
    return (bool(cfg.use_pallas) and cfg.aggr in ("avg", "sign")
            and cfg.noise == 0 and not cfg.diagnostics
            and not cfg.faults_enabled and not cfg.churn_enabled
            and not attack_registry.in_jit(cfg)
            and not compile_cache.is_cohort_mode(cfg)
            and not buffered.is_buffered(cfg)
            and cfg.tenants == 0
            and not health_sentinel.has_quarantine(cfg)
            and cfg.telemetry == "off"
            # the reputation lane (obs/reputation.py) reads the explicit
            # sign-sum tree the fused kernel never materializes — an
            # EXPLICIT --reputation on falls back like telemetry ("auto"
            # instead resolves the lane off and keeps the kernel)
            and cfg.reputation != "on")


def host_takes_flags(cfg) -> bool:
    """Whether the host-sampled per-round step takes the trailing [m] bool
    corrupt-slot flags argument: the faults path needs them for
    --faults_spare_corrupt participation, full telemetry for the
    honest-vs-corrupt cosine split, and the in-jit attack strategies
    (attack/registry.py) to know which rows to transform. Single source
    for the driver, the AOT aval planner (utils/compile_cache.
    plan_programs) and the step builders — their signatures must agree."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    return (cfg.faults_enabled or cfg.telemetry == "full"
            or attack_registry.in_jit(cfg))


def step_takes_round(cfg) -> bool:
    """Whether the round step takes the round index as a traced int32
    lead argument: the churn lifecycle is a function of time
    (service/churn.py), so is diurnal traffic (data/traffic.py), and so
    is a scheduled in-jit attack (attack/schedule.py). Single source for
    the step builders here and in parallel/rounds.py, the driver's
    dispatch (train.py) and the AOT aval planner — their signatures must
    agree. (Cohort steps always take the round index regardless — their
    sampling consumes it.)"""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    return (cfg.churn_enabled or cfg.traffic_enabled
            or attack_registry.needs_round(cfg))


def vmap_agents(local_train, params, imgs, lbls, sizes, keys,
                chunk: int = 0, ep_budget=None):
    """vmap local training over the leading agents axis, optionally in
    sequential chunks of `chunk` agents (`lax.map` over chunk groups).

    Chunking is the HBM lever for big models: peak activation memory scales
    with the number of simultaneously-trained agents (40 agents x bs 256 of
    ResNet-9 stashes ~19 GB — over a v5e chip's 16 GB), so `--agent_chunk c`
    trades a factor m/c of round latency for a factor m/c of activation
    memory. Results are independent of the chunking (each agent's training
    is independent); chunk must divide the (per-device) agent count, else
    the full vmap runs.

    `ep_budget` ([m] int32, faults/) rides the same agents axis when the
    straggler fault is configured — local_train then takes it as a sixth
    per-agent argument."""
    extra = () if ep_budget is None else (ep_budget,)
    vt = jax.vmap(local_train, in_axes=(None,) + (0,) * (4 + len(extra)))
    return _run_chunked(vt, params, imgs, lbls, sizes, keys, chunk, extra)


def megabatch_agents(mb_train, params, imgs, lbls, sizes, keys,
                     chunk: int = 0, ep_budget=None):
    """Run the megabatched block trainer (fl/client.py,
    `--train_layout megabatch`) over the [m, ...] client block,
    optionally in sequential chunks of `chunk` clients — the same HBM
    lever (and the same divisibility rule) as `vmap_agents`: each chunk
    group megabatches its own [chunk*bs, ...] fold, so peak activation
    memory scales with `chunk` while results stay independent of the
    chunking."""
    extra = () if ep_budget is None else (ep_budget,)
    return _run_chunked(mb_train, params, imgs, lbls, sizes, keys, chunk,
                        extra)


def _run_chunked(block_fn, params, imgs, lbls, sizes, keys, chunk, extra):
    """The chunk-scan scaffold shared by BOTH training layouts:
    `block_fn(params, imgs, lbls, sizes, keys, *extra)` over the whole
    [m, ...] block, or over sequential [chunk, ...] groups — one policy
    (divisor rule, CPU unroll cap) so the layouts can never drift."""
    m = imgs.shape[0]
    if 0 < chunk < m and m % chunk != 0:
        # falling back to the full block would reproduce the exact
        # compile-time OOM this flag exists to prevent — fail loudly
        raise ValueError(
            f"--agent_chunk {chunk} does not divide the agent block of {m} "
            f"(per-device agent count); pick a divisor or 0 for the full "
            f"block")
    if chunk <= 0 or chunk >= m:
        return block_fn(params, imgs, lbls, sizes, keys, *extra)
    nc = m // chunk

    def resh(a):
        return a.reshape((nc, chunk) + a.shape[1:])

    def body(carry, args):
        return carry, block_fn(params, *args)

    # routed through maybe_unrolled_scan: XLA:CPU executes convs inside
    # while-loops via a slow reference path (ops/loops.py), so short chunk
    # loops are traced flat on the CPU backend
    _, (updates, losses) = loops.maybe_unrolled_scan(
        body, 0, tuple(resh(a) for a in (imgs, lbls, sizes, keys) + extra),
        loops.cpu_backend() and nc <= 16)
    return (jax.tree_util.tree_map(
        lambda u: u.reshape((m,) + u.shape[2:]), updates),
        losses.reshape(m))


def make_block_trainer(model, cfg, normalize):
    """The layout-dispatched client-block trainer (ISSUE 10):
    train_block(params, imgs, lbls, sizes, keys, chunk=0, ep_budget=None)
    -> (updates [m, ...]-stacked, losses [m]).

    `vmap` (default) batches the per-client local_train with jax.vmap;
    `megabatch` folds the client axis into the batch
    (fl/client.make_local_train_megabatch). Selection consults
    compile_cache.resolved_train_layout — the single source that also
    degrades megabatch to vmap under --diagnostics — so every round
    builder (vmap/sharded/host/cohort x per-round/chained) picks the
    layout through one door."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    if compile_cache.resolved_train_layout(cfg) == "megabatch":
        mb_train = make_local_train_megabatch(model, cfg, normalize)

        def train_block(params, imgs, lbls, sizes, keys, chunk=0,
                        ep_budget=None):
            return megabatch_agents(mb_train, params, imgs, lbls, sizes,
                                    keys, chunk, ep_budget=ep_budget)
        return train_block
    local_train = make_local_train(model, cfg, normalize)

    def train_block(params, imgs, lbls, sizes, keys, chunk=0,
                    ep_budget=None):
        return vmap_agents(local_train, params, imgs, lbls, sizes, keys,
                           chunk, ep_budget=ep_budget)
    return train_block


def _round_core(params, k_train, k_noise, imgs, lbls, sizes, *,
                train_block, cfg, corrupt_flags=None, churn_active=None,
                rnd=None, astate=None, knobs=None):
    """Shared round body: vmapped local training + aggregation + update.

    With faults configured (cfg.faults_enabled) the round additionally
    draws the per-agent fault pattern from the round key (faults/model.py),
    truncates stragglers' epochs, injects corrupt payloads, validates
    payloads server-side, and aggregates over the resulting participation
    mask (faults/masking.py). `corrupt_flags` marks which sampled slots
    hold malicious agents (for --faults_spare_corrupt).

    `churn_active` ([m] bool, service/churn.py: the sampled clients'
    lifecycle availability this round) ANDs into the same participation
    mask — an away client's update never reaches aggregation, exactly
    like a dropped one, with zero extra collectives. A churn-only round
    (no fault rates) routes through the masking path too; an all-away
    cohort degrades to a parameter-preserving no-op via guard_empty.

    An in-jit attack strategy (attack/registry.py) transforms the
    corrupt rows right after local training — BEFORE fault injection and
    server-side payload validation, so --payload_norm_cap and the robust
    aggregators see the attacker's payload the way a real server would.
    `rnd` (traced int32, or None when the step has no round channel)
    feeds the attack schedule gate.

    `astate` (fl/buffered.py carried buffer state) routes the aggregation
    tail through the buffered-async fold instead of the immediate
    aggregate+apply; the straggler draw then delays the upload (latency
    draw) instead of truncating epochs, and the return grows a fourth
    element (the advanced buffer state).

    `knobs` (fl/tenancy.TenantKnobs of traced scalars — this tenant's
    slice of the pack's [E]-vectors, arriving through the tenant vmap)
    overrides the per-experiment scalar constants the solo paths bake in:
    server_lr, the RLR threshold, the attack boost and the schedule
    window. None (every solo path) keeps the Python constants — the
    traced program is bit-for-bit the historical one."""
    m = imgs.shape[0]
    agent_keys = jax.random.split(k_train, m)
    draw = None
    ep_budget = None
    if cfg.faults_enabled:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            model as fmodel)
        draw = fmodel.sample_faults(cfg, fmodel.fault_key(k_noise), m,
                                    corrupt_flags)
        if cfg.straggler_rate > 0:
            # buffered mode repurposes the straggler flags as the arrival
            # latency draw — a slow client uploads LATE (full epochs)
            # instead of truncated; the builder's signature still takes
            # the budget, so hand it the full-epoch constant
            ep_budget = (draw.ep_budget if astate is None
                         else jnp.full((m,), cfg.local_ep, jnp.int32))
    with jax.named_scope("local_train"):
        updates, losses = train_block(params, imgs, lbls, sizes,
                                      agent_keys, cfg.agent_chunk,
                                      ep_budget=ep_budget)
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    if attack_registry.in_jit(cfg):
        if knobs is not None:
            # tenant pack: every tenant carries its own schedule triple
            # and boost as traced knobs (attack/schedule.active_traced —
            # the trivial (0, 0, 1) triple evaluates to always-on, so
            # unscheduled tenants match the solo gate-free fast path)
            from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
                schedule as attack_schedule)
            gate = attack_schedule.active_traced(
                knobs.attack_start, knobs.attack_stop, knobs.attack_every,
                rnd)
            updates = attack_registry.apply_update_attack(
                cfg, updates, corrupt_flags, gate,
                boost=knobs.attack_boost)
        else:
            updates = attack_registry.apply_update_attack(
                cfg, updates, corrupt_flags,
                attack_registry.schedule_active(cfg, rnd))
    mask = None
    extras = {}
    if draw is not None:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            masking, model as fmodel)
        if cfg.corrupt_rate > 0:
            updates = fmodel.inject_corrupt(updates, draw.corrupt,
                                            cfg.corrupt_mode)
        mask = draw.participate & fmodel.payload_valid(
            updates, cfg.payload_norm_cap)
        extras = fmodel.fault_scalars(draw, mask)
    if churn_active is not None:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            masking)
        from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
            churn as churn_mod)
        mask = churn_active if mask is None else mask & churn_active
        # the mask always joins aggregation (cohort shortfall padding
        # rides it too), but Churn/* and churn-shaped Faults/* series
        # are emitted only when churn is actually configured — a plain
        # cohort run must not grow series that make it read as a churn
        # or faults run (its padding already shows in fault_voters
        # whenever faults are on)
        if draw is not None:
            extras["fault_voters"] = masking.count_f32(mask)
            if cfg.churn_enabled:
                extras["churn_away"] = churn_mod.churn_away(churn_active)
        elif cfg.churn_enabled:
            extras = churn_mod.churn_only_scalars(churn_active, mask)
    if astate is not None:
        # buffered-async tail (fl/buffered.py): this tick's updates fold
        # into the carried buffer by arrival level; params advance only
        # when the commit gate fires. lr/agg are the buffer's current
        # vote — telemetry describes the commit decision either way.
        with jax.named_scope("buffered_fold"):
            T = buffered.latency(
                cfg, k_noise, draw.straggler if draw is not None else None)
            contribs = buffered.tick_contributions(cfg, updates, sizes,
                                                   mask, T)
            new_params, new_astate, lr, agg, a_extras, vote_sign = \
                buffered.fold_commit(cfg, params, astate, contribs,
                                     k_noise, m, knobs=knobs)
        extras.update(a_extras)
        if cfg.telemetry != "off":
            from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
                telemetry)
            extras.update(telemetry.compute(
                cfg, updates, lr if cfg.robustLR_threshold > 0 else None,
                agg, mask=mask, corrupt_flags=corrupt_flags,
                sign_sums=vote_sign,
                vote_range=buffered.vote_range(cfg)))
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            reputation as rep_mod)
        if rep_mod.reputation_on(cfg):
            # agreement vs the BUFFER's accumulated sign vote (the
            # electorate the commit decision actually thresholds) —
            # elementwise vs the replicated vote_sign tree, zero
            # collectives
            from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
                masking)
            u_rep = (updates if mask is None
                     else masking.zero_masked(updates, mask))
            extras["rep_agree"] = rep_mod.agree_rows(u_rep, vote_sign,
                                                     mask=mask)
            extras["rep_norm"] = rep_mod.norm_rows(u_rep, mask=mask)
        if health_sentinel.health_on(cfg):
            with jax.named_scope("health"):
                extras.update(health_sentinel.sentinel(
                    cfg, updates, new_params, mask=mask))
        return new_params, jnp.mean(losses), extras, new_astate
    if _pallas_applicable(cfg):   # never taken when faults are configured
        from defending_against_backdoors_with_robust_learning_rate_tpu.ops.pallas_rlr import (
            fused_rlr_avg_apply)
        new_params = fused_rlr_avg_apply(
            params, updates, sizes.astype(jnp.float32),
            float(cfg.robustLR_threshold), cfg.effective_server_lr,
            interpret=jax.default_backend() != "tpu", mode=cfg.aggr)
        extras = {}
        if health_sentinel.health_on(cfg):
            # the sentinel reads the stacked updates + committed params
            # with plain jnp reductions OUTSIDE the fused kernel — the
            # kernel's one-pass HBM property is untouched
            with jax.named_scope("health"):
                extras = health_sentinel.sentinel(cfg, updates, new_params)
        return new_params, jnp.mean(losses), extras
    slr = (cfg.effective_server_lr if knobs is None
           else knobs.server_lr)
    with jax.named_scope("aggregate_rlr"):
        if cfg.robustLR_threshold > 0:
            thr_base = None if knobs is None else knobs.rlr_threshold
            thr = (masking.rlr_threshold(cfg, mask, base=thr_base)
                   if mask is not None
                   else (float(cfg.robustLR_threshold)
                         if knobs is None else knobs.rlr_threshold))
            lr = robust_lr(updates, thr, slr, mask=mask)
        else:
            lr = slr
        agg = aggregate_updates(updates, sizes, cfg, k_noise, mask=mask)
        if mask is not None:
            # all payloads dropped/rejected -> zero aggregate, no-op round
            agg = masking.guard_empty(agg, mask)
        new_params = apply_aggregate(params, lr, agg)
    if cfg.telemetry != "off":
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            telemetry)
        extras.update(telemetry.compute(
            cfg, updates, lr if cfg.robustLR_threshold > 0 else None, agg,
            mask=mask, corrupt_flags=corrupt_flags))
    from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
        reputation as rep_mod)
    if rep_mod.reputation_on(cfg):
        # per-client agreement vs the committed sign vote: derived from
        # the SAME zero-masked updates the vote counted, so the
        # electorate matches robust_lr's — elementwise reductions only,
        # zero collectives (the *_rep CheckSpec pins)
        if mask is not None:
            from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
                masking)
            u_rep = masking.zero_masked(updates, mask)
        else:
            u_rep = updates
        extras["rep_agree"] = rep_mod.agree_rows(
            u_rep, rep_mod.sign_sums_from(u_rep), mask=mask)
        extras["rep_norm"] = rep_mod.norm_rows(u_rep, mask=mask)
    if cfg.diagnostics:
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl.diagnostics import (
            per_agent_norms)
        from jax.flatten_util import ravel_pytree
        extras["agent_norms"] = per_agent_norms(updates)
        if cfg.robustLR_threshold > 0:
            extras["lr_flat"] = ravel_pytree(lr)[0]
    if health_sentinel.health_on(cfg):
        with jax.named_scope("health"):
            extras.update(health_sentinel.sentinel(
                cfg, updates, new_params, mask=mask))
    return new_params, jnp.mean(losses), extras


def make_chained(step, data, family: str = "chained"):
    """Wrap a step(params, key, *data) fn into chained(params, base_key,
    round_ids): a `lax.scan` over rounds, round r keyed by
    `fold_in(base_key, r)` (the driver loop's exact derivation — chained
    blocks match per-round dispatch to ~1 ulp — same ops and keys,
    fusion may round differently). Shared by the
    single-device and sharded paths; info is reduced to the scannable
    train_loss/sampled leaves.

    `data` (the K-agent dataset stacks) is bound OUTSIDE the jit and passed
    as arguments at call time: a jit-closed-over array is inlined into the
    lowered program as a dense constant — for fedemnist-scale stacks that
    is a ~0.5 GiB HLO no compile service should (or will) swallow."""
    # churn steps take the round index (the scan already carries it)
    takes_round = getattr(step, "takes_round", False)

    @functools.partial(jax.jit, donate_argnums=0)
    def chained(params, base_key, round_ids, *data_args):
        def body(params, rnd):
            lead = (rnd,) if takes_round else ()
            new_params, info = step(params, jax.random.fold_in(base_key, rnd),
                                    *lead, *data_args)
            out = {"train_loss": info["train_loss"],
                   "sampled": info["sampled"]}
            out.update({k: info[k] for k in CHAINED_INFO_KEYS if k in info})
            # telemetry, health-sentinel and reputation ([m] rep_agree)
            # values ride the scan stacked per-round, like the fault
            # counters
            out.update({k: v for k, v in info.items()
                        if k.startswith(("tel_", "hlth_", "rep_"))})
            return new_params, out

        # XLA:CPU conv-in-while slow path (ops/loops.py): unroll short
        # chains; each chain step is a whole round so the cap stays small
        py_loops = loops.cpu_backend() and round_ids.shape[0] <= 16
        return loops.maybe_unrolled_scan(body, params, round_ids, py_loops)

    def bound(params, base_key, round_ids):
        return chained(params, base_key, round_ids, *data)

    bound.jitted, bound.data = chained, data   # for lowering-size tests
    bound.family = family   # AOT manifest name (utils/compile_cache.py)
    return bound


def _make_sample_step(cfg, model, normalize):
    """Shared sample-and-step fn: step(params, key, images, labels, sizes).

    Samples the round's m agents, gathers their device-resident shards
    in-jit, and runs the round core. The key-derivation order (sample, train,
    noise) matches parallel/rounds.py so the sharded and single-device paths
    are comparable round-for-round — and both the per-round and chained fns
    wrap THIS fn, which is what makes chained execution match
    per-round dispatch (same ops/keys; ~1 ulp fusion differences).

    The dataset stacks are ARGUMENTS, not closure captures: jit inlines
    closed-over arrays into the lowered HLO as dense constants (measured
    ~1 GiB of StableHLO for the fedemnist stacks, rejected by remote
    compile services and re-shipped on every compile)."""
    train_block = make_block_trainer(model, cfg, normalize)
    K, m = cfg.num_agents, cfg.agents_per_round
    is_async = buffered.is_buffered(cfg)

    def body(carry, key, rnd, images, labels, sizes):
        # buffered mode: the step's first argument is the (params,
        # buffer-state) carry — one pytree the chained scan, the AOT
        # avals, checkpointing and donation all treat as "the params"
        params, astate = carry if is_async else (carry, None)
        k_sample, k_train, k_noise = jax.random.split(key, 3)
        with jax.named_scope("sample_gather"):
            sampled = jax.random.permutation(k_sample, K)[:m]
            imgs = jnp.take(images, sampled, axis=0)
            lbls = jnp.take(labels, sampled, axis=0)
            szs = jnp.take(sizes, sampled, axis=0)
        # faults need the corrupt-slot flags for participation; full
        # telemetry needs them for the honest/corrupt cosine split
        # (host_takes_flags is the single source of that condition)
        want_flags = host_takes_flags(cfg)
        churn_active = None
        if cfg.churn_enabled:
            from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
                churn as churn_mod)
            with jax.named_scope("churn_mask"):
                churn_active = churn_mod.active_slots(cfg, sampled, rnd)
        if cfg.traffic_enabled:
            # diurnal traffic presence (data/traffic.py) composes into
            # the same participation mask as churn — an unreachable
            # client is excluded arithmetically, zero extra collectives
            from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
                traffic as traffic_mod)
            with jax.named_scope("traffic_mask"):
                t_present = traffic_mod.present_slots(cfg, sampled, rnd)
            churn_active = (t_present if churn_active is None
                            else churn_active & t_present)
        if health_sentinel.has_quarantine(cfg):
            # quarantined clients (health/monitor.py QUARANTINE rung)
            # leave the electorate through the participation mask — a
            # traced-constant membership test, the churn protocol
            qmask = health_sentinel.quarantine_mask(cfg, sampled)
            churn_active = (qmask if churn_active is None
                            else churn_active & qmask)
        res = _round_core(
            params, k_train, k_noise, imgs, lbls, szs,
            train_block=train_block, cfg=cfg,
            corrupt_flags=(sampled < cfg.num_corrupt
                           if want_flags else None),
            churn_active=churn_active, rnd=rnd, astate=astate)
        if is_async:
            new_params, train_loss, extras, new_astate = res
            return ((new_params, new_astate),
                    {"train_loss": train_loss, "sampled": sampled,
                     **extras})
        new_params, train_loss, extras = res
        return new_params, {"train_loss": train_loss, "sampled": sampled,
                            **extras}

    if step_takes_round(cfg):
        # churn — and a scheduled in-jit attack — need the round index
        # in-program (the lifecycle phase / attack window is a function
        # of time, not of the round key): the step grows a traced int32
        # `rnd` argument, threaded by the driver / the chained scan
        def step(params, key, rnd, images, labels, sizes):
            return body(params, key, rnd, images, labels, sizes)
        step.takes_round = True
        return step

    def step(params, key, images, labels, sizes):
        return body(params, key, jnp.int32(0), images, labels, sizes)
    step.takes_round = False
    return step


def bind_data(step_jit, data, family: str = "round"):
    """(params, key[, rnd], *data) jitted fn -> (params, key[, rnd]) fn
    with the dataset stacks bound at call time (passed as jit arguments
    every call; one compilation serves every round since shapes never
    change). The optional `rnd` lead argument is the churn path's round
    index (service/churn.py)."""
    def bound(params, key, *lead):
        return step_jit(params, key, *lead, *data)

    bound.jitted, bound.data = step_jit, data   # for lowering-size tests
    bound.family = family   # AOT manifest name (utils/compile_cache.py)
    return bound


def make_round_fn(cfg, model, normalize, images, labels, sizes):
    """Device-resident round fn: round(params, key) -> (params, metrics).

    images/labels/sizes are the full K-agent stacked arrays (jnp, on device).
    """
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    return bind_data(jax.jit(_make_sample_step(cfg, model, normalize)),
                     (images, labels, sizes),
                     family=("round_diag" if cfg.diagnostics
                             else "round"
                             + compile_cache.family_suffix(cfg)))


def make_chained_round_fn(cfg, model, normalize, images, labels, sizes):
    """Round-chained fn: chained(params, base_key, round_ids) -> (params, info).

    Fuses a whole block of FL rounds into ONE compiled program via `lax.scan`
    over the round ids — the per-round host dispatch of the reference loop
    (src/federated.py:65) disappears entirely. Round r's key is
    `fold_in(base_key, r)`, exactly the driver loop's derivation, so a chained
    block matches dispatching the same rounds one at a time (~1 ulp).

    info leaves are stacked per-round ([n_chain, ...]). Diagnostics extras are
    not supported here (the driver runs diagnostic snap rounds unchained).
    """
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    plain = cfg.replace(diagnostics=False)
    return make_chained(_make_sample_step(plain, model, normalize),
                        (images, labels, sizes),
                        family="chained"
                        + compile_cache.family_suffix(plain))


def make_host_step(cfg, model, normalize, take_flags=None):
    """Unjitted host-sampled step(params, key, imgs, lbls, sizes) — the
    shared body of the per-round and chained host fns (key split into
    k_train/k_noise matches bit-for-bit between them).

    With faults — or full telemetry — configured the step takes a sixth
    argument: the [m] bool `corrupt_flags` for the sampled slots (the
    driver computes it from the host-sampled ids — in-jit sampling isn't
    available to derive it here; single source: `host_takes_flags`).
    `take_flags=False` forces the flag-free signature: the chained host
    scan has no per-round flag channel, so it degrades the telemetry
    cosine split to all-honest instead of changing its calling
    convention."""
    if cfg.churn_enabled:
        # the host-sampled program never sees the sampled client ids, so
        # the in-program lifecycle draw has nothing to hash; host-side
        # churn-aware cohorting is future work (ROADMAP). Fail loudly
        # rather than silently running a churn-free round.
        raise ValueError(
            "client churn (--churn_available < 1) is not supported in "
            "host-sampled mode; run device-resident (--host_sampled off)")
    if cfg.traffic_enabled:
        # same contract as churn: the diurnal presence draw needs the
        # sampled client ids, which the host-sampled program never sees
        raise ValueError(
            "diurnal traffic (--traffic diurnal) is not supported in "
            "host-sampled mode; run device-resident or cohort-sampled")
    if buffered.is_buffered(cfg):
        # same contract as churn: the buffered arrival draw and carried
        # buffer have no host-sampled channel (fl/buffered.check names
        # the remediation) — fail loudly rather than silently syncing
        raise ValueError(
            "--agg_mode buffered is not supported in host-sampled mode; "
            "run device-resident (--host_sampled off) or cohort-sampled "
            "(--cohort_sampled on)")
    if health_sentinel.has_quarantine(cfg):
        # same contract as churn: the host-sampled program never sees the
        # sampled client ids the quarantine membership test hashes
        raise ValueError(
            "--quarantine is not supported in host-sampled mode (the "
            "program never sees the sampled client ids); run "
            "device-resident (--host_sampled off) or cohort-sampled")
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    if attack_registry.needs_round(cfg):
        # same contract as churn: the per-round host step has no round
        # channel for the schedule gate to read. Fail loudly rather than
        # silently running the attack always-on (or never).
        raise ValueError(
            f"--attack {cfg.attack} with a schedule "
            f"(attack_start/attack_stop/attack_every) is not supported "
            f"in host-sampled mode; run device-resident "
            f"(--host_sampled off) or cohort-sampled")
    if take_flags is False and attack_registry.in_jit(cfg):
        # the chained host scan has no per-round flag channel; a silently
        # unapplied attack would corrupt every scenario row downstream
        raise ValueError(
            f"--attack {cfg.attack} transforms updates in-jit and needs "
            f"the corrupt-slot flags, which the chained host scan does "
            f"not carry — the driver must dispatch host-sampled attack "
            f"rounds unchained (train.py disables --chain here)")
    train_block = make_block_trainer(model, cfg, normalize)
    if take_flags is None:
        take_flags = host_takes_flags(cfg)

    if take_flags:
        def step(params, key, imgs, lbls, sizes, corrupt_flags):
            k_train, k_noise = jax.random.split(key)
            new_params, train_loss, extras = _round_core(
                params, k_train, k_noise, imgs, lbls, sizes,
                train_block=train_block, cfg=cfg,
                corrupt_flags=corrupt_flags)
            return new_params, {"train_loss": train_loss, **extras}
        return step

    def step(params, key, imgs, lbls, sizes):
        k_train, k_noise = jax.random.split(key)
        new_params, train_loss, extras = _round_core(
            params, k_train, k_noise, imgs, lbls, sizes,
            train_block=train_block, cfg=cfg)
        return new_params, {"train_loss": train_loss, **extras}

    return step


def make_round_fn_host(cfg, model, normalize):
    """Host-sampled round fn: round(params, key, imgs, lbls, sizes).

    The driver samples agent ids and gathers their shards host-side (the
    fedemnist path: 3383 users, 1% sampled per round, src/runner.sh:34)."""
    return jax.jit(make_host_step(cfg, model, normalize))


def make_chained_host(step):
    """Wrap an unjitted host step into chained(params, base_key, round_ids,
    imgs, lbls, sizes) over [chain, m, ...] shard-stack blocks: a `lax.scan`
    whose round r consumes block row r and key `fold_in(base_key, r)` — the
    driver loop's exact derivation, so a chained host block matches
    dispatching the same rounds one at a time (~1 ulp fusion differences).

    This lifts the r2 restriction that host-sampled mode pays one host
    dispatch + gather per round (the fedemnist-scale path, ref
    src/runner.sh:34-38 at 500 rounds): the driver prefetches a whole
    block's shard stacks and the TPU runs `chain` rounds per dispatch.
    Shared by the single-device and sharded host paths — and by the
    cohort-sampled steps (data/cohort.py), whose ``takes_round`` signature
    gets the scanned round index threaded through (the scan already
    carries it), so a chained cohort block recomputes its per-round
    cohort ids, corrupt flags and churn mask in-program."""
    takes_round = getattr(step, "takes_round", False)

    @functools.partial(jax.jit, donate_argnums=0)
    def chained(params, base_key, round_ids, imgs, lbls, sizes):
        def body(params, xs):
            rnd, im, lb, sz = xs
            lead = (rnd,) if takes_round else ()
            new_params, info = step(
                params, jax.random.fold_in(base_key, rnd), *lead, im, lb, sz)
            out = {"train_loss": info["train_loss"]}
            out.update({k: info[k] for k in CHAINED_INFO_KEYS if k in info})
            out.update({k: v for k, v in info.items()
                        if k.startswith(("tel_", "hlth_", "rep_"))})
            return new_params, out

        # XLA:CPU conv-in-while slow path (ops/loops.py): unroll short chains
        py_loops = loops.cpu_backend() and round_ids.shape[0] <= 16
        return loops.maybe_unrolled_scan(
            body, params, (round_ids, imgs, lbls, sizes), py_loops)

    return chained


def make_chained_round_fn_host(cfg, model, normalize):
    """Chained host-sampled rounds: chained(params, base_key, round_ids,
    imgs, lbls, sizes) with [chain, m, ...] blocks (diagnostics unsupported;
    the driver runs diagnostic snap rounds unchained). take_flags=False:
    the scan carries no per-round corrupt flags (under faults the driver
    disables host chaining entirely; under full telemetry the cosine
    split degrades to all-honest)."""
    return make_chained_host(
        make_host_step(cfg.replace(diagnostics=False), model, normalize,
                       take_flags=False))


# ------------------------------------------------------- cohort-sampled ---

def make_cohort_step(cfg, model, normalize):
    """Unjitted cohort-sampled step(params, key, rnd, imgs, lbls, sizes) —
    the population/cohort-split round body (ISSUE 7).

    Data arrives host-gathered like the host-sampled path (fixed [m, ...]
    stacks from the client bank, data/bank.py), but the cohort ids are
    recomputed IN-PROGRAM from the traced round index (data/cohort.py) —
    the same seeded draw the driver's gather mirrored — so:

    - corrupt flags are real client ids (``ids < num_corrupt``), making
      Defense/* cosine splits and Faults/* rates functions of cohort
      MEMBERSHIP (a round that samples no corrupt client reports a zero
      corrupt electorate, test-pinned);
    - the churn lifecycle mask composes (cohorts are sampled from the
      churn-present set — the host-sampled + churn refusal is retired);
    - the chained scan needs no flag side-channel: flags re-derive from
      the scanned round index, so chaining survives faults and full
      telemetry keeps its honest/corrupt split.

    The [m] ``active`` mask (False = duplicate / churn-absent shortfall
    padding) always joins the participation-mask protocol: padded slots
    are excluded from aggregation arithmetically, like dropped clients."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
        cohort as cohort_mod)
    train_block = make_block_trainer(model, cfg, normalize)
    want_flags = host_takes_flags(cfg)
    is_async = buffered.is_buffered(cfg)

    def step(carry, key, rnd, imgs, lbls, sizes):
        params, astate = carry if is_async else (carry, None)
        with jax.named_scope("cohort_sample"):
            ids, active = cohort_mod.sample_cohort(cfg, rnd)
        if health_sentinel.has_quarantine(cfg):
            # quarantined cohort members join the shortfall-padding /
            # churn-absence protocol: excluded from aggregation through
            # the active mask, zero extra collectives
            active = active & health_sentinel.quarantine_mask(cfg, ids)
        k_train, k_noise = jax.random.split(key)
        res = _round_core(
            params, k_train, k_noise, imgs, lbls, sizes,
            train_block=train_block, cfg=cfg,
            corrupt_flags=((ids < cfg.num_corrupt) & active
                           if want_flags else None),
            churn_active=active, rnd=rnd, astate=astate)
        if is_async:
            new_params, train_loss, extras, new_astate = res
            return ((new_params, new_astate),
                    {"train_loss": train_loss, "sampled": ids, **extras})
        new_params, train_loss, extras = res
        return new_params, {"train_loss": train_loss, "sampled": ids,
                            **extras}

    step.takes_round = True
    return step


def make_cohort_round_fn(cfg, model, normalize):
    """Cohort-sampled round fn: round(params, key, rnd, imgs, lbls, sizes).
    The driver mirrors the in-program draw (data/cohort.sample_cohort) to
    gather the cohort's bank rows; one compilation serves every round."""
    return jax.jit(make_cohort_step(cfg, model, normalize))


def make_chained_cohort_round_fn(cfg, model, normalize):
    """Chained cohort rounds: chained(params, base_key, round_ids, imgs,
    lbls, sizes) over [chain, m, ...] bank-row blocks. Unlike the plain
    host chain, faults and the full-telemetry cosine split survive
    chaining — the scanned round index re-derives the flags in-program."""
    return make_chained_host(
        make_cohort_step(cfg.replace(diagnostics=False), model, normalize))
