"""Buffered-asynchronous aggregation (FedBuff-shape): stop paying the
straggler barrier.

Every sync round program barriers on the slowest client — stragglers are
*modeled* (faults/) but their latency is still fully paid, the opposite of
the production shape the ROADMAP targets. ``--agg_mode buffered`` turns the
round loop into a stream of *ticks*: each tick trains the sampled cohort
against the CURRENT committed params, but an update only *arrives* at the
server after its seeded latency draw elapses — a straggling client's
update lands T ticks later with staleness T (the arrival draw rides the
straggler machinery: the same Bernoulli ``--straggler_rate`` draw selects
who is slow; in buffered mode it delays the upload instead of truncating
epochs). The server folds each arrival into a persistent
staleness-weighted buffer (weight ``1/(1+T)^a``, ``--async_staleness_exp``)
plus per-staleness counters and sign-vote accumulators, and commits an
aggregate — avg/sign ± RLR via the shared
``ops/aggregate.rlr_from_sign_sum`` — only once ``--async_buffer_k``
updates have arrived. Params advance ONLY at commits, so an update drawn
in commit window v and arriving in window v+1 was genuinely computed
against stale params: the electorate of every commit mixes staleness
levels, which is exactly the regime the RLR sign vote has never been
measured under (the per-staleness Defense/* split answers it).

Design properties, inherited from the faults/churn idiom:

- **pure function of (client, round)**: the latency draw derives from the
  round's fault key (``faults/model.fault_key`` + its own fold_in tag), so
  arrivals are reproducible under --seed, identical between per-round and
  chained dispatch, identical across every device of a mesh (replicated
  keys — no collective to agree on who is late), and exactly mirrorable
  on host (``host_latency_draw``, the churn/cohort host-mirror idiom).
- **fixed shapes, carried state**: not-yet-arrived contributions live in a
  bounded pending ladder (``async_max_staleness`` stacked partial sums —
  summation is commutative, so per-(remaining-ticks) partial sums lose no
  information the fold needs); the whole buffer state is ONE pytree
  carried through the chained scan and through the digest-verified
  checkpoint (crash-exact recovery of a mid-buffer kill is the chaos
  drill's acceptance).
- **zero extra collectives**: the fold is elementwise on the replicated
  (leaf layout) or scattered (bucket layout) shard; the sharded paths
  reuse the sync plan's psums on the per-level stacked partial sums and
  pack the tiny count/weight/loss lanes into one vector psum, so the
  ``*_async`` contract specs pin the SAME budgets as the sync families.
- **degenerate-case parity**: with K=m, staleness 0 (no stragglers) and
  ``async_staleness_exp=0``, every tick's arrivals are the full cohort,
  the commit gate fires every tick, and the fold arithmetic degenerates
  to the sync path's exact op sequence — bit-identical for sign (integer
  sign-sums are order-free), ulp-close for avg (tests/test_buffered.py).

Unsupported compositions refuse loudly (``check``): the order-statistic
aggregators (comed/trmean/krum/rfa) need the individual updates a running
sum cannot reconstruct; ``--diagnostics`` needs per-round lr/update trees
of a committed round; the fused Pallas kernel never materializes the
buffer; host-sampled mode has no cohort-id channel for the arrival draw
(cohort-sampled mode is the supported large-population surface).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.ops import tree
from defending_against_backdoors_with_robust_learning_rate_tpu.ops.aggregate import (
    apply_aggregate, gaussian_noise_like, rlr_from_sign_sum)

# fold_in tag separating the arrival-latency stream from the fault draws
# it rides next to (faults/model.FAULTS_KEY_TAG idiom)
ASYNC_KEY_TAG = 0xA51C

# info-dict keys every buffered tick emits (train.py writes them as
# Async/* rows; the chained scan carries them like the fault counters)
ASYNC_INFO_KEYS = ("async_fill", "async_committed", "async_stale_hist")


def is_buffered(cfg) -> bool:
    """Single source of the mode decision (config validation happens in
    ``check``; this predicate must stay cheap — it gates every builder)."""
    mode = getattr(cfg, "agg_mode", "sync")
    if mode not in ("sync", "buffered"):
        raise ValueError(f"agg_mode must be 'sync' or 'buffered', "
                         f"got {mode!r}")
    return mode == "buffered"


def buffer_k(cfg) -> int:
    """The commit threshold K (FedBuff's buffer size). 0 = auto: the
    cohort size m, so a staleness-0 run commits every tick and reproduces
    the sync cadence."""
    return int(cfg.async_buffer_k) or cfg.agents_per_round


def wants_sign(cfg) -> bool:
    """Whether the buffer carries sign-vote accumulators: the RLR vote
    and the sign aggregate consume them, and the full-telemetry
    per-staleness split votes over them."""
    return (cfg.robustLR_threshold > 0 or cfg.aggr == "sign"
            or cfg.telemetry == "full")


def max_staleness(cfg) -> int:
    return int(cfg.async_max_staleness)


def vote_range(cfg) -> int:
    """Margin-bucketization range for the buffered electorate: between
    commits the accumulated sign-sum magnitude can exceed the cohort
    size m (it approaches the commit gate K plus a tick's arrivals), so
    the vote-margin histograms bucketize over [0, K + m] instead of the
    sync path's [0, m] — without this a full buffer saturates the top
    bucket and the margin mean leaves [0, 1]."""
    return buffer_k(cfg) + cfg.agents_per_round


def has_pending(cfg) -> bool:
    """Whether arrivals can be delayed at all: without stragglers every
    draw is latency 0 and the pending ladder (and the per-level stacking)
    is never materialized — the parity fast path."""
    return cfg.straggler_rate > 0


def check(cfg) -> None:
    """Loud refusals for unsupported compositions, before any build —
    the megabatch/bucket refusal idiom (each names its remediation)."""
    if not is_buffered(cfg):
        return
    if cfg.aggr not in ("avg", "sign"):
        raise ValueError(
            f"--agg_mode buffered folds running sums; the order-statistic "
            f"aggregator --aggr {cfg.aggr} needs the individual updates "
            f"a buffer cannot reconstruct — use --aggr avg|sign (± RLR) "
            f"or --agg_mode sync")
    if cfg.diagnostics:
        raise ValueError(
            "--agg_mode buffered does not support --diagnostics (the "
            "Norms/Sign research scalars describe one committed round's "
            "lr/update trees, which a partially-filled buffer never "
            "has); re-run with --agg_mode sync, or drop --diagnostics")
    if cfg.use_pallas:
        raise ValueError(
            "--agg_mode buffered does not support --use_pallas (the "
            "fused server kernel consumes the round's updates in one "
            "pass and never materializes the carried buffer); re-run "
            "with --agg_mode sync, or drop --use_pallas")
    # (host-sampled mode is refused by the step builders and the engine
    # — fl/rounds.make_host_step, parallel/rounds.make_sharded_host_step,
    # train.RoundEngine — which own the host_sampled resolution; reading
    # the runtime-provenance field here would trip the fingerprint audit)
    if int(cfg.async_buffer_k) < 0:
        raise ValueError(f"--async_buffer_k must be >= 0 "
                         f"(0 = auto: the cohort size), got "
                         f"{cfg.async_buffer_k}")
    if cfg.async_staleness_exp < 0:
        raise ValueError(f"--async_staleness_exp must be >= 0, got "
                         f"{cfg.async_staleness_exp}")
    if max_staleness(cfg) < 1:
        raise ValueError(f"--async_max_staleness must be >= 1, got "
                         f"{cfg.async_max_staleness}")


def banner(cfg) -> str:
    if not is_buffered(cfg):
        return ""
    return (f"[async] buffered aggregation: commit every "
            f"{buffer_k(cfg)} arrivals, staleness weight "
            f"1/(1+T)^{cfg.async_staleness_exp}, max latency "
            f"{max_staleness(cfg)} tick(s) "
            f"(straggler_rate {cfg.straggler_rate} drives the arrival "
            f"draw; fl/buffered.py)")


# --------------------------------------------------------------- the draw ---

def latency(cfg, k_noise, straggler):
    """[m] int32 arrival latency in ticks, or None when no client can be
    late. Rides the straggler machinery: ``straggler`` is the fault
    draw's Bernoulli straggler flags ([m] bool, faults/model.py); a slow
    client's latency is uniform in [1, async_max_staleness] — or, under
    --traffic diurnal, heavy-tailed log-normal (data/traffic.py
    latency_quantile, same clip range) — uploads mostly land next tick
    with a genuine tail of very-late arrivals. Keyed off the round's
    fault stream with its own fold_in tag, so existing fault draws are
    untouched and the draw replicates across a mesh; the flat path keeps
    the historical randint bit-for-bit."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
        model as fmodel)
    if not has_pending(cfg) or straggler is None:
        return None
    k = jax.random.fold_in(fmodel.fault_key(k_noise), ASYNC_KEY_TAG)
    if cfg.traffic_enabled:
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            traffic as traffic_mod)
        u = jax.random.uniform(k, straggler.shape)
        t = traffic_mod.latency_quantile(cfg, u, max_staleness(cfg))
    else:
        t = jax.random.randint(k, straggler.shape, 1,
                               max_staleness(cfg) + 1)
    return jnp.where(straggler, t, 0)


def host_latency_draw(cfg, rnd, seed=None, m=None, cohort=False):
    """Host mirror of the (straggler, latency) draw the round program
    makes at round ``rnd`` — the same jax ops the traced path runs, so
    the answer is bit-identical (the churn / cohort host-mirror idiom).
    Returns an [m] numpy int32 vector of latencies. ``seed`` is the
    run's --seed, passed explicitly by the caller: the round keys are
    program ARGUMENTS (runtime provenance), so the mirror takes the seed
    the same way the program takes its key. ``cohort`` selects the
    cohort-step key derivation — those steps split the round key 2-ways
    (k_train, k_noise) where the device-resident sample step splits it
    3-ways (k_sample, k_train, k_noise); mirroring the wrong one would
    silently draw a different stream.

    The scenario sweep charges a sync round a simulated duration of
    ``1 + max(T)`` ticks from this draw (the barrier pays the slowest
    client's latency) vs a buffered tick's 1 — the sim clock that makes
    'buffered makes progress where sync waits' a measured number."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
        model as fmodel)
    m = m or cfg.agents_per_round
    key = jax.random.fold_in(jax.random.PRNGKey(seed or 0), rnd)
    k_noise = (jax.random.split(key)[1] if cohort
               else jax.random.split(key, 3)[2])
    k_strag = jax.random.split(fmodel.fault_key(k_noise), 3)[1]
    strag = jax.random.uniform(k_strag, (m,)) < cfg.straggler_rate
    t = latency(cfg, k_noise, strag)
    if t is None:
        return np.zeros((m,), np.int32)
    return np.asarray(t, np.int32)


# ----------------------------------------------------------- carried state ---

def _zeros_like_tree(params):
    return tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)


def _stacked_zeros(params, n: int):
    return tree.map(lambda p: jnp.zeros((n,) + p.shape, jnp.float32),
                    params)


def init_state(cfg, params, per_bin: bool = False):
    """The carried buffer state (a plain dict pytree), zero-initialized.
    Structure is a pure function of the config (the AOT fingerprint keys
    every field that shapes it):

      count        f32 []        arrivals since the last commit
      stale        f32 [S+1]     arrivals per staleness bin since commit
      buf          tree          staleness-weighted update sum   (avg)
      wsum         f32 []        staleness-weighted weight sum   (avg)
      sign         tree          sign-vote accumulator           (vote)
      pend_*       stacked       not-yet-arrived partial sums, indexed by
                                 ticks-until-arrival              (stragglers)
      bin_sign     [S+1]-stacked per-staleness sign accumulators
                                 (``per_bin``: the vmap full-telemetry
                                 Defense split)

    ``per_bin`` is the caller's layout decision: the vmap path carries the
    per-staleness accumulators under --telemetry full; the sharded paths
    degrade the per-bin split (a documented degradation like the chained
    host cosine split) rather than paying per-bin collectives."""
    S = max_staleness(cfg)
    state = {"count": jnp.float32(0.0),
             "stale": jnp.zeros((S + 1,), jnp.float32)}
    if cfg.aggr == "avg":
        state["buf"] = _zeros_like_tree(params)
        state["wsum"] = jnp.float32(0.0)
    if wants_sign(cfg):
        state["sign"] = _zeros_like_tree(params)
    if has_pending(cfg):
        if cfg.aggr == "avg":
            state["pend_buf"] = _stacked_zeros(params, S)
            state["pend_wsum"] = jnp.zeros((S,), jnp.float32)
        if wants_sign(cfg):
            state["pend_sign"] = _stacked_zeros(params, S)
        state["pend_cnt"] = jnp.zeros((S, S + 1), jnp.float32)
    if per_bin and cfg.telemetry == "full":
        state["bin_sign"] = _stacked_zeros(params, S + 1)
    return state


def state_avals(cfg, params_aval, per_bin: bool = False):
    """ShapeDtypeStruct twin of ``init_state`` for the AOT planners."""
    shaped = jax.eval_shape(
        lambda p: init_state(cfg, p, per_bin=per_bin), params_aval)
    return shaped


# ------------------------------------------------------- tick contributions ---

def _level_weights(cfg, T):
    """Per-slot staleness weight 1/(1+T)^a; None when a == 0 (the weight
    is then exactly 1 and the multiply is skipped — parity fast path)."""
    a = float(cfg.async_staleness_exp)
    if a == 0.0 or T is None:
        return None
    return (1.0 + T.astype(jnp.float32)) ** jnp.float32(-a)


def tick_contributions(cfg, updates, sizes, mask, T):
    """One tick's arrival contributions from the trained block.

    ``updates`` leaves are [mb, ...] (the full cohort, or a device's
    local block on the sharded paths); ``sizes`` [mb]; ``mask`` the [mb]
    participation mask or None; ``T`` the [mb] latency draw or None.

    Returns a dict of partial sums — plain leaf shapes when ``T`` is None
    (everything arrives now: the parity fast path whose op sequence is
    exactly the sync aggregation's), else [S+1]-stacked by latency level:

      buf   staleness-weighted update sums      (avg)
      sign  sign sums                            (vote)
      wsum  weighted counts  [S+1] / scalar      (avg)
      cnt   arrival counts   [S+1] / scalar

    Pure local compute — the sharded callers psum these (same collective
    count as the sync plan: the stacking rides the existing psums)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
        masking)
    avg = cfg.aggr == "avg"
    sgn = wants_sign(cfg)
    w = sizes.astype(jnp.float32)
    sw = _level_weights(cfg, T)
    if sw is not None:
        w = w * sw
    out = {}
    if T is None:
        if mask is not None:
            updates = masking.zero_masked(updates, mask)
            w = jnp.where(mask, w, 0.0)
            out["cnt"] = masking.count_f32(mask)
        else:
            out["cnt"] = jnp.float32(updates_m(updates))
        if avg:
            out["wsum"] = jnp.sum(w)

            def leaf_avg(u):
                wshape = (-1,) + (1,) * (u.ndim - 1)
                return jnp.sum(u * w.reshape(wshape), axis=0)
            out["buf"] = tree.map(leaf_avg, updates)
        if sgn:
            out["sign"] = tree.map(
                lambda u: jnp.sum(jnp.sign(u), axis=0), updates)
        return out

    S = max_staleness(cfg)
    valid = mask if mask is not None else jnp.ones(T.shape, bool)
    cnt, wsum, bufs, signs = [], [], [], []
    for s in range(S + 1):
        lvl = valid & (T == s)
        wl = jnp.where(lvl, w, 0.0)
        cnt.append(masking.count_f32(lvl))
        if avg:
            wsum.append(jnp.sum(wl))
        zeroed = masking.zero_masked(updates, lvl)
        if avg:
            def leaf_avg(u, wl=wl):
                wshape = (-1,) + (1,) * (u.ndim - 1)
                return jnp.sum(u * wl.reshape(wshape), axis=0)
            bufs.append(tree.map(leaf_avg, zeroed))
        if sgn:
            signs.append(tree.map(
                lambda u: jnp.sum(jnp.sign(u), axis=0), zeroed))
    out["cnt"] = jnp.stack(cnt)
    if avg:
        out["wsum"] = jnp.stack(wsum)
        out["buf"] = _stack_trees(bufs)
    if sgn:
        out["sign"] = _stack_trees(signs)
    return out


def updates_m(updates) -> int:
    return jax.tree_util.tree_leaves(updates)[0].shape[0]


def _stack_trees(trees):
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)


# ------------------------------------------------------------- fold + commit ---

def _roll_pend(pend, contrib_tail):
    """pend [S, ...] advances one tick: slot i holds what arrives i+1
    ticks from now. The head (arriving now) was consumed by the caller;
    the freshly-drawn level-(i+1) contribution joins slot i."""
    return tree.map(
        lambda p, c: jnp.concatenate([p[1:], jnp.zeros_like(p[:1])]) + c,
        pend, contrib_tail)


def fold_commit(cfg, params, state, contribs, k_noise, m, knobs=None):
    """Fold one tick's (global) contributions into the carried buffer,
    commit when the gate fires, return the advanced carry.

    Purely elementwise/replicated — the sharded callers hand over
    already-psum'd contributions, so this function adds ZERO collectives
    on any layout. Returns ``(new_params, new_state, lr, agg, extras,
    vote_sign)``; ``lr``/``agg`` are the commit decision's trees (the
    hypothetical commit on non-commit ticks — telemetry reads the
    buffer's current vote either way), ``extras`` the Async/* scalars
    plus (per-bin state present) the per-staleness Defense split, and
    ``vote_sign`` the buffer's accumulated sign-sum tree (None without a
    vote) — handed to telemetry so the margin histogram describes the
    BUFFERED electorate without issuing any collective of its own.

    ``knobs`` (fl/tenancy.TenantKnobs — this tenant's slice of the
    pack's traced [E]-vectors) overrides the server-LR and RLR-threshold
    scalars the solo paths bake in as Python constants; the STRUCTURAL
    decisions (is the vote built, is the threshold scaled) stay on
    ``cfg``, which the pack canonicalizes (fl/tenancy.canonical_rep
    collapses thresholds to the 0/1 vote bit) — everything the overrides
    touch is elementwise, so the collective plan is knob-free."""
    S = max_staleness(cfg)
    avg = cfg.aggr == "avg"
    sgn = wants_sign(cfg)
    pend = has_pending(cfg)
    stacked = "cnt" in contribs and getattr(contribs["cnt"], "ndim", 0) > 0
    if pend and not stacked:
        # stragglers always draw latencies, so pending state implies
        # level-stacked contributions; an unstacked caller would
        # silently strand the pending head — refuse instead
        raise ValueError(
            "buffered fold: pending state requires level-stacked "
            "contributions (a caller passed single-level sums on a "
            "straggler_rate > 0 config)")

    # ---- arrivals: this tick's level-0 contribution + the pending head
    if stacked:
        arr_bins = jnp.zeros((S + 1,), jnp.float32).at[0].set(
            contribs["cnt"][0])
        arr_wsum = contribs["wsum"][0] if avg else None
        arr_buf = (tree.map(lambda c: c[0], contribs["buf"])
                   if avg else None)
        arr_sign = (tree.map(lambda c: c[0], contribs["sign"])
                    if sgn else None)
    else:
        arr_bins = jnp.zeros((S + 1,), jnp.float32).at[0].set(
            contribs["cnt"])
        arr_wsum = contribs.get("wsum")
        arr_buf = contribs.get("buf")
        arr_sign = contribs.get("sign")
    new_state = {}
    if pend and stacked:
        arr_bins = arr_bins + state["pend_cnt"][0]
        if avg:
            arr_wsum = arr_wsum + state["pend_wsum"][0]
            arr_buf = tree.map(lambda a, p: a + p[0], arr_buf,
                               state["pend_buf"])
            new_state["pend_buf"] = _roll_pend(
                state["pend_buf"], tree.map(lambda c: c[1:],
                                            contribs["buf"]))
            new_state["pend_wsum"] = (jnp.concatenate(
                [state["pend_wsum"][1:], jnp.zeros((1,), jnp.float32)])
                + contribs["wsum"][1:])
        if sgn:
            arr_sign = tree.map(lambda a, p: a + p[0], arr_sign,
                                state["pend_sign"])
            new_state["pend_sign"] = _roll_pend(
                state["pend_sign"], tree.map(lambda c: c[1:],
                                             contribs["sign"]))
        # per-(remaining, staleness-bin) counts: a level-s draw arrives s
        # ticks out into bin s — jnp.eye's superdiagonal routes it
        route = jnp.eye(S + 1, dtype=jnp.float32)[1:] \
            * contribs["cnt"][1:, None]
        new_state["pend_cnt"] = (jnp.concatenate(
            [state["pend_cnt"][1:], jnp.zeros((1, S + 1), jnp.float32)])
            + route)

    # ---- fold
    count1 = state["count"] + jnp.sum(arr_bins)
    stale1 = state["stale"] + arr_bins
    if avg:
        buf1 = tree.add(state["buf"], arr_buf)
        wsum1 = state["wsum"] + arr_wsum
    if sgn:
        sign1 = tree.add(state["sign"], arr_sign)
    bin1 = None
    if "bin_sign" in state:
        # per-staleness vote accumulators (the Defense split): a
        # contribution's bin is its latency level, known at draw time —
        # accumulated here (at draw) so the split needs no per-bin
        # pending ladder; the buffer itself still folds at arrival.
        # Unstacked contributions are all level 0 — pad into bin 0.
        if stacked:
            contrib_sign = contribs["sign"]
        else:
            contrib_sign = tree.map(
                lambda c: jnp.pad(c[None], [(0, S)] + [(0, 0)] * c.ndim),
                arr_sign)
        bin1 = tree.map(lambda b, c: b + c, state["bin_sign"],
                        contrib_sign)

    # ---- commit decision (computed every tick, applied via `where` — one
    # compiled program serves every fill level)
    K = buffer_k(cfg)
    commit = count1 >= K
    slr = (cfg.effective_server_lr if knobs is None
           else knobs.server_lr)
    thr = (float(cfg.robustLR_threshold) if knobs is None
           else knobs.rlr_threshold)
    if cfg.robustLR_threshold > 0 and cfg.rlr_threshold_mode == "scaled":
        # the buffered electorate is the buffer, not the cohort: scale
        # against the arrivals actually voting
        thr = thr * count1 / jnp.float32(m)
    lr = (tree.map(lambda s: rlr_from_sign_sum(s, thr, slr), sign1)
          if cfg.robustLR_threshold > 0 else slr)
    if avg:
        # guard the empty buffer (0/0) exactly like masking.guard_empty:
        # a zero aggregate makes the commit a parameter-preserving no-op
        agg = tree.map(
            lambda b: jnp.where(count1 > 0, b / wsum1,
                                jnp.zeros_like(b)), buf1)
    else:
        agg = tree.map(lambda s: jnp.where(count1 > 0, jnp.sign(s),
                                           jnp.zeros_like(s)), sign1)
    if cfg.noise > 0:
        agg = tree.add(agg, gaussian_noise_like(agg, k_noise,
                                                cfg.noise * cfg.clip))
    committed = apply_aggregate(params, lr, agg)
    new_params = tree.map(lambda c, p: jnp.where(commit, c, p),
                          committed, params)

    # ---- reset-on-commit
    def z(x):
        return jnp.where(commit, jnp.zeros_like(x), x)

    new_state["count"] = z(count1)
    new_state["stale"] = z(stale1)
    if avg:
        new_state["buf"] = tree.map(z, buf1)
        new_state["wsum"] = z(wsum1)
    if sgn:
        new_state["sign"] = tree.map(z, sign1)

    extras = {"async_fill": count1,
              "async_committed": commit.astype(jnp.float32),
              "async_stale_hist": stale1}
    if bin1 is not None:
        extras.update(_per_bin_split(cfg, bin1, sign1, agg, count1,
                                     stale1, thr))
        new_state["bin_sign"] = tree.map(z, bin1)
    return (new_params, new_state, lr, agg, extras,
            sign1 if sgn else None)


def _per_bin_split(cfg, bin_sign, sign_total, agg, count1, stale1, thr):
    """The per-staleness-bin Defense split (vmap, --telemetry full):

    - ``tel_stale_flip``  [S+1]: fraction of coordinates the RLR vote
      would flip if bin b voted ALONE, at the threshold scaled to the
      bin's electorate (thr * n_b / n) — how much of the defense's bite
      each staleness level would draw by itself;
    - ``tel_stale_cos``   [S+1]: cosine of bin b's accumulated sign vote
      to the committed aggregate — whether stale voters still point where
      the commit goes (0 for an empty bin, the telemetry NaN rule).
    """
    S = max_staleness(cfg)
    leaves_bin = jax.tree_util.tree_leaves(bin_sign)
    leaves_agg = jax.tree_util.tree_leaves(agg)
    total_coords = sum(x.size // (S + 1) for x in leaves_bin)
    n_eff = jnp.maximum(count1, 1.0)
    thr_b = thr * stale1 / n_eff            # [S+1]
    flips = jnp.zeros((S + 1,), jnp.float32)
    dots = jnp.zeros((S + 1,), jnp.float32)
    bsq = jnp.zeros((S + 1,), jnp.float32)
    asq = jnp.float32(0.0)
    for b, a in zip(leaves_bin, leaves_agg, strict=True):
        bf = b.reshape(S + 1, -1)
        af = a.reshape(-1).astype(jnp.float32)
        flips = flips + jnp.sum(
            (jnp.abs(bf) < thr_b[:, None]).astype(jnp.float32), axis=1)
        dots = dots + bf @ af
        bsq = bsq + jnp.sum(bf * bf, axis=1)
        asq = asq + jnp.sum(af * af)
    cos = dots * jax.lax.rsqrt(bsq * asq + 1e-12)
    return {"tel_stale_flip": flips / total_coords,
            "tel_stale_cos": jnp.where(stale1 > 0, cos, 0.0)}
