"""Experiment configuration: flag-compatible CLI over a frozen dataclass.

Flag-name/default parity with the reference CLI (reference: src/options.py:4-74,
20 flags). Differences, all deliberate and documented:

- ``--device`` (reference: src/options.py:67-68 picks cuda:0/cpu) is replaced by
  TPU-native placement flags ``--mesh`` and ``--platform``; ``--device`` is still
  accepted and ignored (with a warning) so reference command lines keep working.
- ``--num_workers`` (DataLoader threads, reference: src/options.py:70-71) is
  accepted and ignored: data is device-resident, there is no loader.
- New flags: ``--seed`` (the reference is unseeded, SURVEY.md 2.3.12; we add
  determinism), ``--arch`` (BASELINE.json configs[3-4] require ResNet-9 on
  cifar10 in addition to the faithful CNN), ``--dtype`` (bf16 compute on the
  MXU, f32 default for curve parity), ``--data_dir``, ``--log_dir``,
  ``--checkpoint_dir``/``--resume`` (SURVEY.md section 5.4: checkpointing is
  absent in the reference and added here), ``--mesh`` (number of devices on the
  ``agents`` mesh axis; 0 = all local devices, 1 = single-device vmap path).

Semantics preserved exactly (reference: src/federated.py:23): ``server_lr`` is
forced to 1.0 unless ``aggr == 'sign'``.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Config:
    # --- reference flag surface (names + defaults match src/options.py) ---
    data: str = "fmnist"            # fmnist | cifar10 | fedemnist | synthetic
    num_agents: int = 10            # K
    agent_frac: float = 1.0         # C, fraction of agents sampled per round
    num_corrupt: int = 0            # first num_corrupt agent ids are malicious
    rounds: int = 200               # R communication rounds
    aggr: str = "avg"               # avg | comed | sign | trmean | krum | rfa
    local_ep: int = 2               # E local epochs
    bs: int = 256                   # B local batch size
    client_lr: float = 0.1
    client_moment: float = 0.9
    server_lr: float = 1.0          # only used as-is for aggr='sign'
    base_class: int = 5             # backdoor source class
    target_class: int = 7           # backdoor target class
    poison_frac: float = 0.0        # fraction of base-class samples to trojan
    pattern_type: str = "plus"      # plus | square | copyright | apple
    robustLR_threshold: int = 0     # >0 enables the RLR defense
    clip: float = 0.0               # >0 enables client-side PGD L2 projection
    noise: float = 0.0              # >0 adds N(0, noise*clip) server noise
    top_frac: int = 100             # sign-agreement diagnostic top-k params
    snap: int = 1                   # eval every `snap` rounds

    # --- TPU-native additions ---
    platform: str = ""              # "" = default backend; "cpu"/"tpu" override
    seed: int = 0
    # multi-host (DCN) rendezvous — one process per host; all empty/0 means
    # single-process (or cloud auto-detection inside jax.distributed)
    coordinator: str = ""           # host:port of process 0
    num_processes: int = 0          # total processes in the job
    process_id: int = -1            # this process's id; -1 = auto
    arch: str = "auto"              # auto | cnn | resnet9
    dtype: str = "f32"              # f32 | bf16 (compute dtype on the MXU)
    rng_impl: str = "auto"          # auto: hardware RNG (rbg) on TPU,
                                    # threefry elsewhere; threefry | rbg
                                    # force. Measured +13% round throughput
                                    # on v5e (threefry dropout-mask bits
                                    # are 15% of the round). A checkpoint
                                    # must resume under the impl that
                                    # wrote it (key data shapes differ).
    mesh: int = 1                   # devices on the `agents` mesh axis; 0 = all
    agg_layout: str = "leaf"        # leaf | bucket — sharded aggregation
                                    # collective shape (parallel/rounds.py):
                                    # leaf = one psum per parameter leaf
                                    # (2L+2 on the flagship; free on one
                                    # chip); bucket = flatten updates into
                                    # fixed-size buckets, ONE reduce-
                                    # scatter per bucket, avg + RLR vote
                                    # computed on the scattered shard, one
                                    # all-gather of the LR-scaled result
                                    # (parallel/buckets.py — the pod
                                    # shape). leaf stays the default until
                                    # the TPU A/B lands (bench.py
                                    # --agg_layout)
    train_layout: str = "vmap"      # vmap | megabatch — local-training
                                    # compute layout (fl/client.py):
                                    # vmap = per-client [bs, ...] steps
                                    # batched by jax.vmap (the historical
                                    # path); megabatch = the client axis
                                    # folds into the batch — one
                                    # [m*bs, ...] gather + normalize
                                    # pass per minibatch step, step
                                    # masks folded into per-client
                                    # segment weights, the parameter
                                    # chains advancing as one stacked
                                    # [m, ...] tree (grads from the
                                    # client-batched backward — see
                                    # fl/client.py for why not a single
                                    # grad-of-vmap). Parity is ulp-bounded in
                                    # f32 (tests/test_megabatch.py);
                                    # collective plan unchanged. vmap
                                    # stays the default until the TPU
                                    # A/B lands (bench.py --train_layout)
    chain: int = 1                  # rounds fused per dispatch via lax.scan
                                    # (capped at `snap`; >1 kills per-round
                                    # host dispatch overhead, bit-identical)
    host_prefetch: int = 2          # host-sampled mode: dispatch UNITS of
                                    # shard stacks gathered + device_put
                                    # ahead of the compute (0 = synchronous;
                                    # a unit is one round, or `chain` rounds
                                    # when chained — up to N+2 units
                                    # resident: N queued + 1 in the
                                    # worker's hand + 1 retained for
                                    # supervised retry)
    host_sampled: str = "auto"      # auto: shard stacks above the device-
                                    # resident budget (2 GiB) gather on host
                                    # per round; on/off forces the mode
    agent_chunk: int = 0            # >0: train agents in sequential chunks
                                    # of this size (lax.map) — divides peak
                                    # activation HBM by m/chunk for big
                                    # models; must divide the per-device
                                    # agent count (else full vmap)
    remat: bool = False             # blockwise rematerialization of the
                                    # model's forward (ResNet-9): backward
                                    # recomputes activations instead of
                                    # stashing them (exact, saves HBM)
    remat_policy: str = "block"     # block: recompute everything per block;
                                    # conv: save the conv (MXU) outputs and
                                    # recompute only the elementwise tail
                                    # (~3x saved bytes, no conv recompute)
    # --- fault injection & elastic participation (faults/) ---
    dropout_rate: float = 0.0       # per-round Bernoulli client dropout
    straggler_rate: float = 0.0     # per-round straggler probability
    straggler_epochs: int = 1       # local epochs a straggler completes
    corrupt_rate: float = 0.0       # per-round corrupt-payload probability
    corrupt_mode: str = "nan"       # nan | huge (1e30 finite constant)
    payload_norm_cap: float = 0.0   # >0: server rejects updates with L2
                                    # norm above the cap (validation mask)
    faults_spare_corrupt: bool = False  # attackers never drop out (the
                                    # adversarial participation model)
    rlr_threshold_mode: str = "abs"  # abs: paper's absolute vote count;
                                    # scaled: threshold * n_eff / m keeps
                                    # the required agreement fraction
                                    # invariant under churn
    # --- buffered-async aggregation (fl/buffered.py, FedBuff-shape) ---
    agg_mode: str = "sync"          # sync | buffered — sync barriers every
                                    # round on the slowest client (the
                                    # historical path, bit-identical);
                                    # buffered folds each arriving update
                                    # into a persistent staleness-weighted
                                    # buffer carried across ticks and
                                    # commits an aggregate only when
                                    # --async_buffer_k updates have
                                    # arrived. Arrival latency rides the
                                    # straggler draw: a straggling
                                    # client's update lands T ticks later
                                    # with staleness T (no epoch
                                    # truncation in buffered mode).
                                    # avg/sign (± RLR) only; refuses
                                    # pallas/--diagnostics/host-sampled.
    async_buffer_k: int = 0         # arrivals per commit (FedBuff's K);
                                    # 0 = auto: the cohort size m (then
                                    # staleness-0 runs commit every tick,
                                    # reproducing the sync path)
    async_staleness_exp: float = 0.0  # staleness-weight exponent a: an
                                    # arrival with staleness T folds with
                                    # weight 1/(1+T)^a; 0 = unweighted
                                    # (every arrival counts fully)
    async_max_staleness: int = 4    # max latency draw T (ticks) for a
                                    # straggling client; bounds the
                                    # carried pending-arrival state and
                                    # the staleness telemetry bins
    # --- adaptive-adversary attack registry (attack/registry.py) ---
    attack: str = "static"          # static | dba | boost | signflip —
                                    # the corrupt cohort's strategy:
                                    # static = the paper's trojan (data
                                    # poisoning only, bitwise the
                                    # pre-registry path); dba = the full
                                    # pattern dealt across corrupt agents
                                    # (attack/dba.py); boost / signflip =
                                    # in-jit update transforms applied
                                    # inside the round program
    attack_boost: float = 1.0       # model-replacement scale on corrupt
                                    # updates (boost: x+boost, signflip:
                                    # x-boost); 1.0 = magnitude-preserving
    attack_start: int = 0           # attack schedule (attack/schedule.py,
                                    # pure function of the traced round
                                    # index; rounds are 1-based): dormant
                                    # before this round
    attack_stop: int = 0            # 0 = never stop; start=k, stop=k+1
                                    # is the one-shot attack
    attack_every: int = 1           # intermittent: fire every n-th round
                                    # from attack_start
    # --- online RLR-threshold adaptation (attack/adapt.py) ---
    rlr_adapt: str = "off"          # off | on — the service driver
                                    # adapts --robustLR_threshold from
                                    # mid-run Defense/* telemetry at eval
                                    # boundaries (needs --telemetry full
                                    # + --checkpoint_dir; service mode)
    rlr_adapt_every: int = 2        # decide at most every N eval
                                    # boundaries (hysteresis)
    # --- client churn: arrive/depart/rejoin lifecycles (service/churn.py) ---
    churn_available: float = 1.0    # fraction of lifecycle phases a client
                                    # is present; 1.0 = always there (the
                                    # dense path, bit-identical); <1 routes
                                    # the round through the participation
                                    # mask with away clients excluded
    churn_period: int = 32          # rounds per lifecycle phase: a client's
                                    # stays/absences last whole phases, so
                                    # departures persist (unlike per-round
                                    # dropout) and rejoins happen on phase
                                    # boundaries
    churn_seed: int = 0             # seeds the lifecycle streams —
                                    # independent of --seed so the cohort
                                    # process can be re-drawn without
                                    # touching any training key stream
    # --- million-client population axis (data/bank.py + data/cohort.py) ---
    cohort_sampled: str = "auto"    # auto | on | off — decouple population
                                    # from cohort: the round program takes
                                    # the traced round index, recomputes
                                    # the seeded cohort ids in-program,
                                    # and trains only the gathered [m,...]
                                    # cohort stacks. auto turns on at
                                    # populations >= 4096 clients
                                    # (utils/compile_cache.is_cohort_mode)
    cohort_size: int = 0            # per-round cohort m; 0 = the legacy
                                    # floor(num_agents * agent_frac)
    cohort_seed: int = 0            # seeds the cohort stream — its own
                                    # program field (like churn_seed) so
                                    # cohorts can be re-drawn without
                                    # touching any training key stream
    partitioner: str = "label_shards"  # client-bank partitioner:
                                    # label_shards (the paper's exact
                                    # dealing scheme) | dirichlet |
                                    # pathological (per-client-seeded,
                                    # scale to millions of clients)
    dirichlet_alpha: float = 0.5    # Dir(alpha) class-mixture concentration
    classes_per_client: int = 2     # pathological: distinct classes/client
    samples_per_client: int = 0     # virtual-partitioner shard size;
                                    # 0 = auto clamp(n/K, 16, 4096)
    bank_dir: str = ""              # client-bank root ("" = auto under
                                    # data_dir, else log_dir)
    bank_shard_clients: int = 65536  # clients per bank index-shard file
                                    # (IO layout only — bank content is
                                    # provably layout-independent)
    bank_build_workers: int = 1     # parallel bank-build subprocesses
                                    # (data/bank.py): whole shard files
                                    # per worker, published bank bitwise
                                    # identical to the serial build —
                                    # a throughput knob like the shard
                                    # layout, never a content input
    # --- trace-shaped diurnal traffic (data/traffic.py, ISSUE 17) ---
    traffic: str = "flat"           # flat | diurnal — flat keeps every
                                    # path bit-identical; diurnal gives
                                    # each client a seeded timezone and a
                                    # raised-cosine daily availability
                                    # curve feeding the participation
                                    # mask, plus log-normal (heavy-tail)
                                    # buffered-mode latency
    traffic_seed: int = 0           # seeds the traffic streams —
                                    # independent of --seed (the
                                    # churn_seed idiom)
    traffic_peak_frac: float = 0.8  # availability at a client's local
                                    # daily peak
    traffic_trough_frac: float = 0.1  # availability at the local trough
                                    # (devices charging / offline at
                                    # night)
    traffic_day_rounds: int = 64    # rounds per simulated day (the
                                    # diurnal period; timezone offsets
                                    # spread client local time uniformly
                                    # over it)
    traffic_latency_sigma: float = 0.8  # log-normal sigma of the
                                    # buffered-mode staleness draw
                                    # (heavier tail = more very-late
                                    # uploads), clipped to max_staleness
    # --- multi-tenant megabatched sweeps (fl/tenancy.py, ISSUE 13) ---
    tenants: int = 0                # >0: this config is a TENANT PACK of E
                                    # independent experiment replicas run
                                    # as one resident program — the
                                    # experiment axis folded the way
                                    # megabatch folded the client axis.
                                    # Per-tenant scalar knobs (seed,
                                    # server_lr, robustLR_threshold,
                                    # attack_boost, schedule gates) enter
                                    # as traced [E]-vectors; knobs that
                                    # change shapes stay queue-level.
                                    # 0 = the untenanted (solo) paths,
                                    # bit-for-bit the historical programs.
                                    # Normally set by the experiment queue
                                    # (service/queue.py --tenants), not by
                                    # hand.
    # --- in-program health lane + auto-recovery (health/, ISSUE 14) ---
    health: str = "on"              # on | off — the always-on in-jit
                                    # numerics sentinel (health/sentinel):
                                    # per-round nonfinite update counts,
                                    # committed-params finite bit and the
                                    # cohort update-norm mass emitted as
                                    # Health/* rows, with ZERO added
                                    # collectives (the sharded scalars
                                    # pack into the loss psum's lanes).
                                    # off removes the lane from the
                                    # traced program (the bench A/B arm)
    health_policy: str = "record"   # abort | recover | record — what a
                                    # numerics incident does
                                    # (health/monitor.py): abort raises
                                    # (--debug_nan forces this), record
                                    # warns loudly and keeps the metrics
                                    # flowing (the sweep default: a NaN
                                    # cell is recorded-and-skipped),
                                    # recover arms the service driver's
                                    # ladder (discard -> rollback ->
                                    # quarantine -> halt)
    health_z_threshold: float = 6.0  # loss z-score (vs the carried EMA
                                    # baseline) above which a boundary is
                                    # an incident
    health_spike_factor: float = 10.0  # update-norm spike trigger: norm >
                                    # factor x its EMA baseline
    defense_flip_frac_hi: float = 0.5  # Defense/Flip_Fraction above which
                                    # a boundary counts as a defense
                                    # anomaly (health/monitor.py). The
                                    # default is the PR-15 heuristic;
                                    # calibrate it from the reputation
                                    # plane's measured flip quantiles
                                    # (README "Defense observability")
    defense_low_margin_hi: float = 0.25  # low-vote-margin mass above which
                                    # a boundary counts as a defense
                                    # anomaly; same calibration source
                                    # (Reputation/* quantiles) as
                                    # defense_flip_frac_hi
    quarantine: str = ""            # comma-separated client ids excluded
                                    # from every round's participation
                                    # mask (the ladder's QUARANTINE rung
                                    # writes this; a traced program
                                    # constant — the churn protocol,
                                    # zero extra collectives)
    bank_verify: bool = False       # verify the client bank's per-shard
                                    # sha256 sidecars on open (data/bank):
                                    # a corrupted indices-*.bin fails
                                    # loudly naming the shard instead of
                                    # feeding garbage batches
    # --- continuous-service driver (service/driver.py) ---
    service_rounds: int = 0         # serve(): total rounds to stream; 0 =
                                    # indefinitely (until the stop file
                                    # <log_dir>/service.stop appears)
    service_retries: int = 3        # supervised retries per failed unit
    service_backoff_s: float = 0.25  # exponential-backoff base (doubles
                                    # per attempt)
    service_deadline_s: float = 0.0  # per-unit soft deadline; a unit past
                                    # it classifies as wedged (0 = off)
    service_keep_ckpts: int = -1    # checkpoints retained on disk (keep-K
                                    # pruning). -1 = auto: keep everything
                                    # in the one-shot trainer, 3 under
                                    # serve() (which checkpoints forever
                                    # and must bound the directory);
                                    # 0 = keep everything explicitly
    chaos: str = ""                 # deterministic fault-injection spec
                                    # (service/chaos.py), e.g.
                                    # "kill@7,corrupt_ckpt@4,wedge@3"
    # --- compile persistence & async dispatch (utils/compile_cache.py) ---
    compile_cache: bool = True      # persistent XLA cache + serialized-
                                    # executable AOT bank (warm starts skip
                                    # XLA entirely); --no_compile_cache
                                    # opts out
    compile_cache_dir: str = ""     # cache root ("" = $RLR_COMPILE_CACHE_DIR
                                    # or ~/.cache/rlr_fl — stable across
                                    # runs by design)
    async_metrics: bool = True      # per-round scalars stay on device and
                                    # drain on a background thread (no
                                    # blocking host sync in the round
                                    # loop); --sync_metrics opts out.
                                    # Diagnostics/debug_nan/multi-process
                                    # runs are always synchronous.
    # --- observability (obs/) ---
    telemetry: str = "off"          # off | basic | full — in-jit defense
                                    # telemetry (obs/telemetry.py): norm
                                    # percentiles + RLR flip fraction
                                    # (basic), + vote-margin histogram and
                                    # honest/corrupt cosine split (full).
                                    # off adds NOTHING to the traced
                                    # program: training is bit-identical.
    reputation: str = "auto"        # auto | on | off — the per-client
                                    # defense-provenance lanes
                                    # (obs/reputation.py): every round the
                                    # traced program additionally emits
                                    # per-sampled-client rep_agree
                                    # (fraction of parameter coordinates
                                    # whose update sign matches the
                                    # committed sign vote) and rep_norm
                                    # (update L2 — the magnitude signal
                                    # the sign vote cannot carry) scalars,
                                    # mask-aware,
                                    # with ZERO added collectives, folded
                                    # host-side into a longitudinal
                                    # per-client suspicion ledger
                                    # (Reputation/* rows, rep/* events).
                                    # auto = on whenever a sign vote
                                    # exists (robustLR_threshold > 0 or
                                    # aggr='sign') and the fused Pallas
                                    # server step is not in use; off
                                    # removes the lane — training and
                                    # every metrics surface bit-identical
    rep_population_cap: int = 100000  # dense per-client dict up to this
                                    # population; above it the tracker
                                    # switches to a count-min sketch +
                                    # top-k heavy-hitter ledger so RSS
                                    # stays O(cohort + k) at 10M clients
    rep_topk: int = 64              # heavy-hitter ledger width (ranked
                                    # suspects surfaced per boundary)
    rep_streak: int = 3             # consecutive vote-losing boundaries
                                    # before a client crosses the
                                    # suspicion threshold (rep/suspect
                                    # ledger event; observe-only — the
                                    # health ladder owns quarantine)
    spans: bool = True              # host-side round-trace spans
                                    # (obs/spans.py): trace.json in the run
                                    # dir + Spans/* aggregates in
                                    # metrics.jsonl; --no_spans opts out
    heartbeat: bool = True          # atomically-rewritten status.json
                                    # (obs/heartbeat.py) for the session
                                    # stall detectors; --no_heartbeat
    status_file: str = ""           # heartbeat path ("" = <log_dir>/
                                    # status.json — a stable path the
                                    # watchers can find without knowing
                                    # the run name)
    events: str = "on"              # on | off — the service event ledger
                                    # (obs/events.py): every lifecycle
                                    # transition (retries, ladder rungs,
                                    # adaptation moves, chaos injections,
                                    # checkpoint save/restore, AOT bank
                                    # hit/miss) as one typed, seq-numbered
                                    # record in <run_dir>/events.jsonl;
                                    # off arms nothing and the metrics
                                    # stream is bit-identical
    flight: str = "on"              # on | off — the incident flight
                                    # recorder (obs/flight.py): a bounded
                                    # per-round ring of span durations /
                                    # dispatch gaps / drain depth / HBM
                                    # stats, streamed crash-exactly to
                                    # <run_dir>/flight.jsonl and dumped
                                    # atomically to flight.json on any
                                    # warn/error incident; host-side only,
                                    # training is bit-identical either way
    trigger_profile: str = "off"    # on | off — anomaly-triggered
                                    # profiling (obs/trigger.py): a flight-
                                    # window span z-score or a supervisor/
                                    # health incident arms the round
                                    # profiler for a bounded capture (max
                                    # 2/run) and ledgers the device split
                                    # as obs/trigger_* events. Off by
                                    # default: arming is timing-dependent,
                                    # so byte-identity drills keep it off
    metrics_port: int = 0           # >0: serve GET /metrics (Prometheus
                                    # exposition text, obs/export.py) on
                                    # this port from the service driver;
                                    # 0 = no HTTP exporter
    metrics_textfile: str = ""      # path for the atomically-rewritten
                                    # Prometheus textfile export
                                    # (node_exporter textfile-collector
                                    # format); "" = off
    data_dir: str = "./data"
    log_dir: str = "./logs"
    checkpoint_dir: str = ""        # "" disables checkpointing
    resume: bool = False
    eval_bs: int = 1024
    profile_dir: str = ""           # "" disables jax.profiler traces
    profile_rounds: int = 0         # >0: capture a jax.profiler window of
                                    # this many STEADY rounds (never the
                                    # compile unit) into <run_dir>/profile
                                    # (or --profile_dir), parse it into
                                    # Device/* + Memory/* attribution rows
                                    # (obs/attribution.py) and the run
                                    # report; 0 = off, bit-identical
    use_pallas: bool = False        # fused RLR+aggregate TPU kernel
    debug_nan: bool = False         # checkify float guards in the round fn
    diagnostics: bool = False       # Norms/* + Sign/* research scalars (C13)
    tensorboard: bool = True        # JSONL metrics always; TB optional
    # synthetic-data knobs (used when `data` is missing on disk or 'synthetic')
    synth_train_size: int = 2048
    synth_val_size: int = 512
    synth_hardness: float = 0.0     # 0 = easy separable prototypes; >0 mixes
                                    # a shared background into the prototypes,
                                    # raises pixel noise and adds label noise
                                    # so val_acc climbs over tens of rounds
                                    # instead of saturating immediately

    @property
    def faults_enabled(self) -> bool:
        """Any nonzero fault rate — or a payload-norm cap, which needs the
        server-side validation + participation mask to act — routes the
        round through the faults path (faults/); all-off keeps the dense
        path bit-for-bit."""
        return (self.dropout_rate > 0 or self.straggler_rate > 0
                or self.corrupt_rate > 0 or self.payload_norm_cap > 0)

    @property
    def churn_enabled(self) -> bool:
        """Client churn is on when availability is a real fraction. The
        lifecycle mask then joins the participation-mask protocol
        (faults/masking.py); 1.0 keeps the dense path bit-for-bit."""
        return self.churn_available < 1.0

    @property
    def traffic_enabled(self) -> bool:
        """Diurnal traffic is on when the model is not flat. The presence
        mask then joins the participation-mask protocol exactly like
        churn; "flat" keeps every path bit-for-bit."""
        return self.traffic != "flat"

    @property
    def effective_server_lr(self) -> float:
        """server_lr is forced to 1.0 unless aggr=='sign' (src/federated.py:23)."""
        return self.server_lr if self.aggr == "sign" else 1.0

    @property
    def agents_per_round(self) -> int:
        """The per-round cohort m: an explicit --cohort_size wins (the
        population/cohort decoupling knob, ISSUE 7); otherwise the
        reference's floor(K * C) (src/federated.py:68)."""
        import math

        if self.cohort_size > 0:
            return self.cohort_size
        return max(1, math.floor(self.num_agents * self.agent_frac))

    @property
    def n_classes(self) -> int:
        # the reference hardcodes 10 everywhere, incl. fedemnist eval
        # (src/utils.py:128, SURVEY.md 2.3.7); we keep 10 for parity.
        return 10

    @property
    def image_shape(self):
        if self.data in ("fmnist", "fedemnist"):
            return (28, 28, 1)
        if self.data in ("cifar10", "synthetic"):
            return (32, 32, 3) if self.data == "cifar10" else (8, 8, 1)
        raise ValueError(f"unknown dataset {self.data!r}")

    @property
    def model_arch(self) -> str:
        if self.arch != "auto":
            return self.arch
        return "cnn"

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


# --- field provenance (fingerprint audit, analysis/fingerprint_audit.py) ---
# Every Config field must declare where it lives; the static-analysis CI
# gate fails closed on a new field missing here. Classes
# (analysis/contracts.py):
#   program  shapes the traced round/eval program -> MUST be in the AOT
#            fingerprint (never in compile_cache.EXCLUDED_FIELDS)
#   shape    only changes array shapes -> pinned by the example-arg avals;
#            fingerprinting is harmless, exclusion allowed when an aval
#            provably carries it
#   data     changes dataset CONTENT, never the program
#   runtime  driver/IO knob -> MUST be excluded from the fingerprint
#            (fingerprinting one recompiles identical programs)
FIELD_PROVENANCE = {
    "data": "program",            # selects model family + image geometry
    "num_agents": "program",      # K: in-jit sampling range
    "agent_frac": "program",      # m = floor(K*C): vmap width
    "num_corrupt": "program",     # krum/trmean trim, corrupt-slot flags
    "rounds": "runtime",          # dispatch count only
    "aggr": "program",
    "local_ep": "program",        # scan trip count
    "bs": "program",              # batch shapes
    "client_lr": "program",       # baked into the SGD step
    "client_moment": "program",
    "server_lr": "program",
    "base_class": "data",         # poisoning source; host-side stamping
    "target_class": "data",
    "poison_frac": "data",
    "pattern_type": "data",
    "robustLR_threshold": "program",
    "clip": "program",
    "noise": "program",
    "top_frac": "runtime",        # host-side Sign/* set algebra only
    "snap": "runtime",            # eval cadence; schedule not program
    "platform": "runtime",        # backend is fingerprinted directly
    "seed": "runtime",            # keys are program ARGUMENTS
    "coordinator": "runtime",     # process_count is fingerprinted
    "num_processes": "runtime",
    "process_id": "runtime",
    "arch": "program",
    "dtype": "program",
    "rng_impl": "runtime",        # the RESOLVED impl is fingerprinted via
                                  # jax_default_prng_impl; 'auto' must not
                                  # split from its resolution
    "mesh": "runtime",            # sharded families are never banked; the
                                  # mesh-independent eval/vmap programs
                                  # should be shared across mesh settings
    "agg_layout": "program",      # selects the sharded aggregation
                                  # collective plan (per-leaf psums vs
                                  # bucketed reduce-scatter) — a traced
                                  # program difference
    "train_layout": "program",    # selects the local-training compute
                                  # layout (vmapped per-client steps vs
                                  # the megabatched [m*bs] fold) — a
                                  # traced program difference; the
                                  # fingerprint keys the RESOLVED layout
                                  # (compile_cache.resolved_train_layout
                                  # normalizes the --diagnostics degrade)
    "chain": "shape",             # round_ids aval pins the block length
    "host_prefetch": "runtime",
    "host_sampled": "runtime",    # selects the family; family names key
                                  # the fingerprint already
    "agent_chunk": "program",     # chunked lax.map vs full vmap
    "remat": "program",
    "remat_policy": "program",
    "dropout_rate": "program",    # faults path is traced
    "straggler_rate": "program",
    "straggler_epochs": "program",
    "corrupt_rate": "program",
    "corrupt_mode": "program",
    "payload_norm_cap": "program",
    "faults_spare_corrupt": "program",
    "rlr_threshold_mode": "program",
    "agg_mode": "program",         # selects the buffered-async round
                                   # program (fl/buffered.py carried
                                   # buffer state + fold/commit are
                                   # traced) — distinct *_async families
    "async_buffer_k": "program",   # baked into the traced commit gate
    "async_staleness_exp": "program",  # baked into the traced staleness
                                       # weight
    "async_max_staleness": "program",  # shapes the carried pending state
                                       # and the latency draw range
    "attack": "program",           # selects the in-jit update transform
                                   # (boost/signflip are traced; the
                                   # data-side strategies shape bank/shard
                                   # CONTENT — fingerprinting those too is
                                   # harmless, and one field can carry
                                   # only one class)
    "attack_boost": "program",     # baked into the traced row scale
    "attack_start": "program",     # baked into the traced schedule gate
    "attack_stop": "program",
    "attack_every": "program",
    "tenants": "program",          # E>0 selects the *_mt tenant-pack
                                   # program families (fl/tenancy.py):
                                   # the tenant axis is a traced leading
                                   # dimension of every carried array, so
                                   # the tenant count must split the AOT
                                   # cache (the [E, ...] avals pin it too)
    "rlr_adapt": "runtime",        # service-driver adaptation policy —
                                   # applied by REBUILDING programs with a
                                   # new robustLR_threshold, never read in
                                   # a trace
    "rlr_adapt_every": "runtime",
    "churn_available": "program",  # churn path is traced (service/churn.py
                                   # draws ride the round program)
    "churn_period": "program",
    "churn_seed": "program",       # baked into the traced lifecycle key
                                   # (PRNGKey(churn_seed) is a program
                                   # constant, unlike --seed whose keys are
                                   # program ARGUMENTS)
    "cohort_sampled": "runtime",   # selects the cohort program families;
                                   # family names key the fingerprint
    "cohort_size": "program",      # m: vmap width + in-program sampling
    "cohort_seed": "program",      # baked into the traced cohort draw
                                   # (data/cohort.py, like churn_seed)
    "partitioner": "data",         # shapes bank CONTENT, never the program
    "dirichlet_alpha": "data",
    "classes_per_client": "data",
    "samples_per_client": "shape",  # cohort-row length via the bank's
                                    # padded max_n -> pinned by the avals
    "bank_dir": "runtime",         # storage location only
    "bank_build_workers": "runtime",  # build throughput only — the
                                   # published bank is bitwise identical
                                   # at any worker count (data/bank.py)
    "traffic": "program",          # traffic path is traced
                                   # (data/traffic.py draws ride the
                                   # round program, like churn)
    "traffic_seed": "program",     # baked into the traced traffic key
                                   # (the churn_seed idiom)
    "traffic_peak_frac": "program",    # availability-curve shape enters
    "traffic_trough_frac": "program",  # the traced presence draw
    "traffic_day_rounds": "program",   # diurnal period (traced modulus)
    "traffic_latency_sigma": "program",  # traced buffered staleness draw
    "bank_shard_clients": "runtime",  # IO shard layout; bank content is
                                      # layout-independent (test-pinned)
    "health": "program",           # the in-jit sentinel adds outputs to
                                   # (and packs lanes into) the traced
                                   # round program — a program difference
                                   # like telemetry
    "health_policy": "runtime",    # host-side incident policy; never
                                   # read in a trace
    "health_z_threshold": "runtime",   # host-side EMA judgement knobs
    "health_spike_factor": "runtime",  # (health/monitor.py)
    "defense_flip_frac_hi": "runtime",   # host-side defense-anomaly
    "defense_low_margin_hi": "runtime",  # judgement thresholds
                                         # (health/monitor.py), calibrated
                                         # from Reputation/* quantiles —
                                         # never read in a trace
    "quarantine": "program",       # the quarantined-id set is a traced
                                   # membership constant (the churn_seed
                                   # idiom: baked in, keys the cache)
    "bank_verify": "runtime",      # open-time IO verification only
    "service_rounds": "runtime",   # service/driver.py streaming budget
    "service_retries": "runtime",  # supervisor policy (service/supervisor)
    "service_backoff_s": "runtime",
    "service_deadline_s": "runtime",
    "service_keep_ckpts": "runtime",
    "chaos": "runtime",            # fault injection is host-side only
    "compile_cache": "runtime",
    "compile_cache_dir": "runtime",
    "async_metrics": "runtime",
    "telemetry": "program",       # adds outputs to the traced program
    "reputation": "program",      # the per-client agreement lane adds
                                  # outputs to (and rides the existing
                                  # reductions of) the traced round
                                  # program — a program difference like
                                  # telemetry/health
    "rep_population_cap": "runtime",  # host-side tracker representation
    "rep_topk": "runtime",            # knobs (obs/reputation.py) — never
    "rep_streak": "runtime",          # read in a trace
    "spans": "runtime",
    "heartbeat": "runtime",
    "status_file": "runtime",
    "events": "runtime",          # ledger IO only; never read in a trace
    "flight": "runtime",          # ring buffer + stream IO only
    "trigger_profile": "runtime",  # arms the profiler; never in a trace
    "metrics_port": "runtime",    # exporter transport knobs
    "metrics_textfile": "runtime",
    "data_dir": "runtime",
    "log_dir": "runtime",
    "checkpoint_dir": "runtime",
    "resume": "runtime",
    "eval_bs": "shape",           # eval batch geometry via pad_eval_set
    "profile_dir": "runtime",
    "profile_rounds": "runtime",  # sampled profiler window; observation
                                  # only, never shapes the program
    "use_pallas": "program",
    "debug_nan": "program",       # checkify instruments the program (AOT
                                  # bank is off, but the XLA cache is not)
    "diagnostics": "program",     # per-family normalization in fingerprint()
    "tensorboard": "runtime",
    "synth_train_size": "shape",
    "synth_val_size": "shape",
    "synth_hardness": "data",
}


def _add_reference_flags(p: argparse.ArgumentParser) -> None:
    d = Config()
    p.add_argument("--data", type=str, default=d.data,
                   help="dataset we want to train on")
    p.add_argument("--num_agents", type=int, default=d.num_agents,
                   help="number of agents:K")
    p.add_argument("--agent_frac", type=float, default=d.agent_frac,
                   help="fraction of agents per round:C")
    p.add_argument("--num_corrupt", type=int, default=d.num_corrupt,
                   help="number of corrupt agents")
    p.add_argument("--rounds", type=int, default=d.rounds,
                   help="number of communication rounds:R")
    p.add_argument("--aggr", type=str, default=d.aggr,
                   help="aggregation function "
                        "(avg|comed|sign|trmean|krum|rfa)")
    p.add_argument("--local_ep", type=int, default=d.local_ep,
                   help="number of local epochs:E")
    p.add_argument("--bs", type=int, default=d.bs, help="local batch size: B")
    p.add_argument("--client_lr", type=float, default=d.client_lr,
                   help="clients learning rate")
    p.add_argument("--client_moment", type=float, default=d.client_moment,
                   help="clients momentum")
    p.add_argument("--server_lr", type=float, default=d.server_lr,
                   help="servers learning rate for signSGD")
    p.add_argument("--base_class", type=int, default=d.base_class,
                   help="base class for backdoor attack")
    p.add_argument("--target_class", type=int, default=d.target_class,
                   help="target class for backdoor attack")
    p.add_argument("--poison_frac", type=float, default=d.poison_frac,
                   help="fraction of dataset to corrupt for backdoor attack")
    p.add_argument("--pattern_type", type=str, default=d.pattern_type,
                   help="shape of bd pattern")
    p.add_argument("--robustLR_threshold", type=int, default=d.robustLR_threshold,
                   help="break ties when votes sum to 0")
    p.add_argument("--clip", type=float, default=d.clip,
                   help="weight clip to -clip,+clip")
    p.add_argument("--noise", type=float, default=d.noise,
                   help="server-side gaussian noise std multiplier (times clip)")
    p.add_argument("--top_frac", type=int, default=d.top_frac,
                   help="compare fraction of signs")
    p.add_argument("--snap", type=int, default=d.snap,
                   help="do inference in every num of snap rounds")
    # accepted-and-ignored reference flags (GPU-loop specific)
    p.add_argument("--device", type=str, default=None,
                   help="[ignored] reference GPU selector; use --mesh/--platform")
    p.add_argument("--num_workers", type=int, default=0,
                   help="[ignored] reference DataLoader workers; data is device-resident")


def _add_tpu_flags(p: argparse.ArgumentParser) -> None:
    d = Config()
    p.add_argument("--platform", type=str, default=d.platform,
                   help="jax platform override (cpu|tpu); empty = default")
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--arch", type=str, default=d.arch,
                   help="auto|cnn|resnet9 (BASELINE.json configs[3-4])")
    p.add_argument("--dtype", type=str, default=d.dtype, help="f32|bf16")
    p.add_argument("--rng_impl", choices=("auto", "threefry", "rbg"),
                   default=d.rng_impl,
                   help="PRNG bit generator: auto = hardware RNG (rbg) on "
                        "TPU (+13%% measured round throughput), threefry "
                        "elsewhere; checkpoints must resume under the impl "
                        "that wrote them")
    p.add_argument("--coordinator", type=str, default=d.coordinator,
                   help="multi-host: host:port of process 0 "
                        "(jax.distributed rendezvous)")
    p.add_argument("--num_processes", type=int, default=d.num_processes,
                   help="multi-host: total processes (one per host)")
    p.add_argument("--process_id", type=int, default=d.process_id,
                   help="multi-host: this process's id; -1 = auto")
    p.add_argument("--mesh", type=int, default=d.mesh,
                   help="devices on the `agents` mesh axis (0=all local devices)")
    p.add_argument("--agg_layout", choices=("leaf", "bucket"),
                   default=d.agg_layout,
                   help="sharded aggregation collective shape: leaf = one "
                        "psum per parameter leaf (single-chip shape); "
                        "bucket = bucketed reduce-scatter + all-gather of "
                        "the LR-scaled result with the RLR vote computed "
                        "on the scattered shard (pod shape, "
                        "parallel/buckets.py)")
    p.add_argument("--train_layout", choices=("vmap", "megabatch"),
                   default=d.train_layout,
                   help="local-training compute layout: vmap = per-client "
                        "[bs, ...] steps batched by jax.vmap; megabatch = "
                        "fold the client axis into the batch — one "
                        "[m*bs, ...] pass per minibatch step with a "
                        "client-segmented loss/grad reduction "
                        "(fl/client.py; ulp-bounded parity, identical "
                        "collective plan). Degrades to vmap under "
                        "--diagnostics")
    p.add_argument("--chain", type=int, default=d.chain,
                   help="rounds fused into one compiled lax.scan dispatch "
                        "(capped at --snap so eval cadence is unchanged)")
    p.add_argument("--host_prefetch", type=int, default=d.host_prefetch,
                   help="host-sampled mode: dispatch units (1 round, or "
                        "--chain rounds when chained) of shard stacks "
                        "gathered + device_put ahead of the compute "
                        "(0=synchronous; device memory holds up to N+2 "
                        "units in flight)")
    p.add_argument("--host_sampled", choices=("auto", "on", "off"),
                   default=d.host_sampled,
                   help="force host-sampled shard gathering on/off "
                        "(auto: stacks above the 2 GiB device-resident "
                        "budget gather on host per round)")
    p.add_argument("--agent_chunk", type=int, default=d.agent_chunk,
                   help="train agents in sequential chunks of this size "
                        "(divides peak activation HBM; must divide the "
                        "per-device agent count)")
    p.add_argument("--remat", action="store_true",
                   help="blockwise rematerialization of the model forward "
                        "(ResNet-9): recompute activations in backward "
                        "instead of stashing them — exact, saves HBM")
    p.add_argument("--remat_policy", type=str, default=d.remat_policy,
                   choices=("block", "conv"),
                   help="remat flavor: block = recompute everything; conv "
                        "= save conv (MXU) outputs, recompute only the "
                        "elementwise tail")
    p.add_argument("--dropout_rate", type=float, default=d.dropout_rate,
                   help="per-round Bernoulli client dropout probability "
                        "(faults/: dropped agents are masked out of "
                        "aggregation; at least one agent always survives)")
    p.add_argument("--straggler_rate", type=float, default=d.straggler_rate,
                   help="per-round straggler probability; a straggler's "
                        "local training truncates to --straggler_epochs")
    p.add_argument("--straggler_epochs", type=int, default=d.straggler_epochs,
                   help="local epochs a straggler completes (capped at "
                        "--local_ep)")
    p.add_argument("--corrupt_rate", type=float, default=d.corrupt_rate,
                   help="per-round corrupt-payload probability; garbage "
                        "updates are caught by server-side payload "
                        "validation and masked out")
    p.add_argument("--corrupt_mode", choices=("nan", "huge"),
                   default=d.corrupt_mode,
                   help="corrupt-payload flavor: nan (caught by the finite "
                        "check) or huge (1e30 finite — needs "
                        "--payload_norm_cap or a robust aggregator)")
    p.add_argument("--payload_norm_cap", type=float,
                   default=d.payload_norm_cap,
                   help=">0: server rejects updates whose L2 norm exceeds "
                        "the cap (joins the participation mask)")
    p.add_argument("--faults_spare_corrupt", action="store_true",
                   help="malicious agents (id < num_corrupt) never drop "
                        "out: the adversarial participation model that "
                        "thins the RLR defense's honest majority")
    p.add_argument("--rlr_threshold_mode", choices=("abs", "scaled"),
                   default=d.rlr_threshold_mode,
                   help="RLR vote threshold under faults: abs = paper's "
                        "absolute count; scaled = threshold * n_eff / m")
    p.add_argument("--agg_mode", choices=("sync", "buffered"),
                   default=d.agg_mode,
                   help="aggregation mode (fl/buffered.py): sync = every "
                        "round barriers on the slowest client (the "
                        "historical path); buffered = FedBuff-shape — "
                        "arriving updates fold into a persistent "
                        "staleness-weighted buffer carried across ticks, "
                        "the server commits when --async_buffer_k have "
                        "arrived, and a straggling client's update lands "
                        "T ticks later with staleness T (avg/sign ± RLR "
                        "only)")
    p.add_argument("--async_buffer_k", type=int, default=d.async_buffer_k,
                   help="buffered mode: arrivals per commit (0 = auto: "
                        "the cohort size m — staleness-0 then reproduces "
                        "the sync path)")
    p.add_argument("--async_staleness_exp", type=float,
                   default=d.async_staleness_exp,
                   help="buffered mode: staleness-weight exponent a — an "
                        "arrival with staleness T folds with weight "
                        "1/(1+T)^a (0 = unweighted)")
    p.add_argument("--async_max_staleness", type=int,
                   default=d.async_max_staleness,
                   help="buffered mode: max latency draw in ticks for a "
                        "straggling client (bounds the carried pending "
                        "state and the staleness telemetry bins)")
    p.add_argument("--attack", choices=("static", "dba", "boost",
                                        "signflip"),
                   default=d.attack,
                   help="adaptive-adversary strategy (attack/registry.py):"
                        " static = the paper's trojan (bitwise the legacy "
                        "poison path); dba = distributed trigger split "
                        "across corrupt agents; boost = model-replacement "
                        "scaling of corrupt updates; signflip = RLR-aware "
                        "anti-vote (corrupt updates negated)")
    p.add_argument("--attack_boost", type=float, default=d.attack_boost,
                   help="corrupt-update scale for the in-jit strategies "
                        "(boost applies +x, signflip applies -x)")
    p.add_argument("--attack_start", type=int, default=d.attack_start,
                   help="attack schedule: dormant before this round "
                        "(late-start; rounds are 1-based; in-jit "
                        "strategies only)")
    p.add_argument("--attack_stop", type=int, default=d.attack_stop,
                   help="attack schedule: inactive from this round on "
                        "(0 = never; start=k stop=k+1 is one-shot)")
    p.add_argument("--attack_every", type=int, default=d.attack_every,
                   help="attack schedule: fire every n-th round from "
                        "--attack_start (intermittent)")
    p.add_argument("--rlr_adapt", choices=("off", "on"),
                   default=d.rlr_adapt,
                   help="service mode: adapt --robustLR_threshold online "
                        "from mid-run Defense/* telemetry at eval "
                        "boundaries (attack/adapt.py; needs --telemetry "
                        "full and --checkpoint_dir)")
    p.add_argument("--rlr_adapt_every", type=int, default=d.rlr_adapt_every,
                   help="threshold-adaptation cadence: decide at most "
                        "every N eval boundaries")
    p.add_argument("--churn_available", type=float, default=d.churn_available,
                   help="client-churn availability: fraction of lifecycle "
                        "phases a client is present (service/churn.py); "
                        "1.0 = no churn (bit-identical dense path)")
    p.add_argument("--churn_period", type=int, default=d.churn_period,
                   help="rounds per churn lifecycle phase — stays/absences "
                        "last whole phases, so departures persist and "
                        "rejoins land on phase boundaries")
    p.add_argument("--churn_seed", type=int, default=d.churn_seed,
                   help="seeds the client lifecycle streams (independent "
                        "of --seed)")
    p.add_argument("--cohort_sampled", choices=("auto", "on", "off"),
                   default=d.cohort_sampled,
                   help="population/cohort decoupling (data/bank.py + "
                        "data/cohort.py): the round trains a seeded "
                        "per-round cohort gathered from a sharded "
                        "memory-mapped client bank — host/HBM memory is "
                        "constant in population size (auto: on at >= "
                        "4096 clients)")
    p.add_argument("--cohort_size", type=int, default=d.cohort_size,
                   help="per-round cohort size m (0 = the legacy "
                        "floor(num_agents * agent_frac))")
    p.add_argument("--cohort_seed", type=int, default=d.cohort_seed,
                   help="seeds the per-round cohort draw (independent of "
                        "--seed; a program constant like --churn_seed)")
    p.add_argument("--partitioner",
                   choices=("label_shards", "dirichlet", "pathological"),
                   default=d.partitioner,
                   help="client-bank partitioner: label_shards = the "
                        "paper's dealing scheme (exact, small K); "
                        "dirichlet / pathological = per-client-seeded "
                        "non-IID draws that scale to millions of clients")
    p.add_argument("--dirichlet_alpha", type=float,
                   default=d.dirichlet_alpha,
                   help="Dirichlet class-mixture concentration (smaller = "
                        "more skewed clients)")
    p.add_argument("--classes_per_client", type=int,
                   default=d.classes_per_client,
                   help="pathological partitioner: distinct classes each "
                        "client sees")
    p.add_argument("--samples_per_client", type=int,
                   default=d.samples_per_client,
                   help="virtual-partitioner shard size (0 = auto "
                        "clamp(n_samples/population, 16, 4096))")
    p.add_argument("--bank_dir", type=str, default=d.bank_dir,
                   help="client-bank root (default: "
                        "<data_dir>/client_banks/, else under log_dir)")
    p.add_argument("--bank_shard_clients", type=int,
                   default=d.bank_shard_clients,
                   help="clients per bank index-shard file (IO layout "
                        "only; content is layout-independent)")
    p.add_argument("--bank_build_workers", type=int,
                   default=d.bank_build_workers,
                   help="parallel bank-build subprocesses (data/bank.py; "
                        "whole shard files per worker — the published "
                        "bank is bitwise identical at any worker count)")
    p.add_argument("--traffic", choices=("flat", "diurnal"),
                   default=d.traffic,
                   help="traffic model (data/traffic.py): flat = every "
                        "path bit-identical; diurnal = seeded per-client "
                        "timezones + raised-cosine daily availability "
                        "into the participation mask, log-normal "
                        "buffered latency")
    p.add_argument("--traffic_seed", type=int, default=d.traffic_seed,
                   help="seeds the traffic streams (independent of "
                        "--seed; a program constant like --churn_seed)")
    p.add_argument("--traffic_peak_frac", type=float,
                   default=d.traffic_peak_frac,
                   help="diurnal availability at a client's local daily "
                        "peak")
    p.add_argument("--traffic_trough_frac", type=float,
                   default=d.traffic_trough_frac,
                   help="diurnal availability at the local trough")
    p.add_argument("--traffic_day_rounds", type=int,
                   default=d.traffic_day_rounds,
                   help="rounds per simulated day (the diurnal period)")
    p.add_argument("--traffic_latency_sigma", type=float,
                   default=d.traffic_latency_sigma,
                   help="log-normal sigma of the buffered-mode staleness "
                        "draw (clipped to [1, max_staleness])")
    p.add_argument("--tenants", type=int, default=d.tenants,
                   help="multi-tenant pack width E (fl/tenancy.py): >0 "
                        "runs E independent experiment replicas as one "
                        "resident *_mt program with per-tenant seeds/"
                        "thresholds/LRs as traced [E]-vectors; normally "
                        "driven by the experiment queue "
                        "(service/queue.py --tenants), 0 = solo paths")
    p.add_argument("--health", choices=("on", "off"), default=d.health,
                   help="in-program numerics health lane "
                        "(health/sentinel.py): per-round nonfinite "
                        "counts + committed-params finite bit + update-"
                        "norm mass as Health/* rows, zero added "
                        "collectives; off removes the lane (bench A/B)")
    p.add_argument("--health_policy", choices=("abort", "recover",
                                               "record"),
                   default=d.health_policy,
                   help="numerics-incident policy (health/monitor.py): "
                        "abort raises (--debug_nan forces it), record "
                        "warns and keeps recording (sweep default), "
                        "recover arms the service driver's recovery "
                        "ladder (discard -> rollback -> quarantine -> "
                        "halt)")
    p.add_argument("--health_z_threshold", type=float,
                   default=d.health_z_threshold,
                   help="loss z-score vs the carried EMA above which a "
                        "boundary counts as a health incident")
    p.add_argument("--health_spike_factor", type=float,
                   default=d.health_spike_factor,
                   help="update-norm spike trigger: norm > factor x its "
                        "EMA baseline")
    p.add_argument("--defense_flip_frac_hi", type=float,
                   default=d.defense_flip_frac_hi,
                   help="Defense/Flip_Fraction above which a boundary is "
                        "a defense anomaly (health/monitor.py); calibrate "
                        "from the reputation plane's measured quantiles")
    p.add_argument("--defense_low_margin_hi", type=float,
                   default=d.defense_low_margin_hi,
                   help="low-vote-margin mass above which a boundary is a "
                        "defense anomaly; same Reputation/* calibration "
                        "source as --defense_flip_frac_hi")
    p.add_argument("--reputation", choices=("auto", "on", "off"),
                   default=d.reputation,
                   help="per-client defense-provenance lanes "
                        "(obs/reputation.py): rep_agree + rep_norm per "
                        "sampled client with zero added collectives, "
                        "folded into a longitudinal suspicion ledger "
                        "(Reputation/* rows, rep/* events). auto = on "
                        "when a sign vote exists and pallas is off; off "
                        "is bit-identical")
    p.add_argument("--rep_population_cap", type=int,
                   default=d.rep_population_cap,
                   help="population above which the reputation tracker "
                        "switches from a dense per-client dict to a "
                        "count-min sketch + top-k heavy-hitter ledger")
    p.add_argument("--rep_topk", type=int, default=d.rep_topk,
                   help="reputation heavy-hitter ledger width (ranked "
                        "suspects surfaced per eval boundary)")
    p.add_argument("--rep_streak", type=int, default=d.rep_streak,
                   help="consecutive vote-losing boundaries before a "
                        "client crosses the suspicion threshold "
                        "(rep/suspect event; observe-only)")
    p.add_argument("--quarantine", type=str, default=d.quarantine,
                   help="comma-separated client ids excluded from every "
                        "round's participation mask (the recovery "
                        "ladder's QUARANTINE rung; zero extra "
                        "collectives — the churn protocol)")
    p.add_argument("--bank_verify", action="store_true",
                   help="verify the client bank's per-shard sha256 "
                        "sidecars on open; a corrupted indices-*.bin "
                        "fails loudly naming the shard")
    p.add_argument("--service_rounds", type=int, default=d.service_rounds,
                   help="service mode: total rounds to stream (0 = run "
                        "until <log_dir>/service.stop appears)")
    p.add_argument("--service_retries", type=int, default=d.service_retries,
                   help="service mode: supervised retries per failed "
                        "dispatch/eval/checkpoint unit")
    p.add_argument("--service_backoff_s", type=float,
                   default=d.service_backoff_s,
                   help="service mode: exponential-backoff base seconds "
                        "(doubles per retry)")
    p.add_argument("--service_deadline_s", type=float,
                   default=d.service_deadline_s,
                   help="service mode: per-unit soft deadline in seconds; "
                        "a unit exceeding it is classified wedged (0=off)")
    p.add_argument("--service_keep_ckpts", type=int,
                   default=d.service_keep_ckpts,
                   help="checkpoints retained on disk (keep-K pruning; "
                        "-1 = auto: keep everything one-shot, 3 in "
                        "service mode; 0 = keep everything)")
    p.add_argument("--chaos", type=str, default=d.chaos,
                   help="deterministic fault-injection spec for the "
                        "service driver (service/chaos.py), e.g. "
                        "'kill@7,corrupt_ckpt@4,wedge@3,slow_eval@2'")
    p.add_argument("--no_compile_cache", action="store_true",
                   help="disable the persistent XLA compilation cache and "
                        "the serialized-executable AOT bank "
                        "(utils/compile_cache.py)")
    p.add_argument("--compile_cache_dir", type=str, default=d.compile_cache_dir,
                   help="compile-cache root (default: $RLR_COMPILE_CACHE_DIR "
                        "or ~/.cache/rlr_fl)")
    p.add_argument("--telemetry", choices=("off", "basic", "full"),
                   default=d.telemetry,
                   help="in-jit defense telemetry (obs/telemetry.py): "
                        "basic = update-norm percentiles + RLR flip "
                        "fraction; full adds the vote-margin histogram "
                        "and honest/corrupt cosine split. Scalars stay "
                        "on device and ride the async metrics drain; "
                        "off is bit-identical to a build without it")
    p.add_argument("--no_spans", action="store_true",
                   help="disable the host-side round-trace spans "
                        "(obs/spans.py: trace.json + Spans/* aggregates)")
    p.add_argument("--no_heartbeat", action="store_true",
                   help="disable the status.json heartbeat "
                        "(obs/heartbeat.py)")
    p.add_argument("--status_file", type=str, default=d.status_file,
                   help="heartbeat path (default <log_dir>/status.json)")
    p.add_argument("--events", choices=("on", "off"), default=d.events,
                   help="service event ledger (obs/events.py): every "
                        "lifecycle transition as a typed, seq-numbered "
                        "record in <run_dir>/events.jsonl (off arms "
                        "nothing; the metrics stream is bit-identical)")
    p.add_argument("--flight", choices=("on", "off"), default=d.flight,
                   help="incident flight recorder (obs/flight.py): "
                        "bounded per-round ring streamed crash-exactly "
                        "to <run_dir>/flight.jsonl, snapshotted to "
                        "flight.json on any incident")
    p.add_argument("--trigger_profile", choices=("on", "off"),
                   default=d.trigger_profile,
                   help="anomaly-triggered profiling (obs/trigger.py): "
                        "a flight-window z-score or an incident arms "
                        "the round profiler for a bounded capture "
                        "(max 2/run) and ledgers the device split")
    p.add_argument("--metrics_port", type=int, default=d.metrics_port,
                   help=">0: serve GET /metrics (Prometheus exposition "
                        "text) on this port from the service driver "
                        "(obs/export.py)")
    p.add_argument("--metrics_textfile", type=str,
                   default=d.metrics_textfile,
                   help="path for the atomically-rewritten Prometheus "
                        "textfile export (node_exporter "
                        "textfile-collector format)")
    p.add_argument("--sync_metrics", action="store_true",
                   help="force the synchronous metrics path (float() host "
                        "sync every eval boundary) instead of the async "
                        "background drain")
    p.add_argument("--data_dir", type=str, default=d.data_dir)
    p.add_argument("--log_dir", type=str, default=d.log_dir)
    p.add_argument("--checkpoint_dir", type=str, default=d.checkpoint_dir)
    p.add_argument("--resume", action="store_true")
    p.add_argument("--eval_bs", type=int, default=d.eval_bs)
    p.add_argument("--profile_dir", type=str, default=d.profile_dir)
    p.add_argument("--profile_rounds", type=int, default=d.profile_rounds,
                   help=">0: sample a jax.profiler capture window of this "
                        "many steady rounds and attribute device time "
                        "(obs/attribution.py: Device/* + Memory/* rows, "
                        "run report input); 0 = off")
    p.add_argument("--use_pallas", action="store_true")
    p.add_argument("--debug_nan", action="store_true",
                   help="instrument the round program with checkify float "
                        "checks (raises on the first NaN/inf)")
    p.add_argument("--diagnostics", action="store_true",
                   help="log Norms/* and Sign/* research scalars "
                        "(the reference's dead-code diagnostics, C13)")
    p.add_argument("--no_tensorboard", action="store_true")
    p.add_argument("--synth_train_size", type=int, default=d.synth_train_size)
    p.add_argument("--synth_val_size", type=int, default=d.synth_val_size)
    p.add_argument("--synth_hardness", type=float, default=d.synth_hardness,
                   help="0=easy separable synthetic task; 0..1 mixes "
                        "prototypes toward a shared background, raises pixel "
                        "noise and adds label noise (learning curves become "
                        "non-trivial)")


def args_parser(argv: Optional[list] = None) -> Config:
    """Parse CLI flags into a Config (reference: src/options.py:4-74)."""
    p = argparse.ArgumentParser(
        description="TPU-native robust-learning-rate federated learning")
    _add_reference_flags(p)
    _add_tpu_flags(p)
    ns = p.parse_args(argv)
    if ns.device is not None:
        print(f"[config] --device={ns.device} ignored: placement is TPU-mesh "
              f"native, use --mesh / JAX_PLATFORMS")
    fields = {f.name for f in dataclasses.fields(Config)}
    kw = {k: v for k, v in vars(ns).items() if k in fields}
    kw["tensorboard"] = not ns.no_tensorboard
    kw["compile_cache"] = not ns.no_compile_cache
    kw["async_metrics"] = not ns.sync_metrics
    kw["spans"] = not ns.no_spans
    kw["heartbeat"] = not ns.no_heartbeat
    return Config(**kw)


def print_exp_details(cfg: Config) -> None:
    """Banner matching the reference (src/utils.py:287-303)."""
    print("======================================")
    print(f"    Dataset: {cfg.data}")
    print(f"    Global Rounds: {cfg.rounds}")
    print(f"    Aggregation Function: {cfg.aggr}")
    print(f"    Number of agents: {cfg.num_agents}")
    print(f"    Fraction of agents: {cfg.agent_frac}")
    print(f"    Batch size: {cfg.bs}")
    print(f"    Client_LR: {cfg.client_lr}")
    print(f"    Server_LR: {cfg.effective_server_lr}")
    print(f"    Client_Momentum: {cfg.client_moment}")
    print(f"    RobustLR_threshold: {cfg.robustLR_threshold}")
    print(f"    Noise Ratio: {cfg.noise}")
    print(f"    Number of corrupt agents: {cfg.num_corrupt}")
    print(f"    Poison Frac: {cfg.poison_frac}")
    print(f"    Clip: {cfg.clip}")
    print(f"    Seed: {cfg.seed}  Arch: {cfg.model_arch}  Dtype: {cfg.dtype}"
          f"  Mesh: {cfg.mesh}")
    print("======================================")
