"""`python -m defending_against_backdoors_with_robust_learning_rate_tpu`
— same CLI as `python federated.py` (reference src/runner.sh invocation
surface) and the installed `rlr-federated` console script."""

from defending_against_backdoors_with_robust_learning_rate_tpu.train import main

if __name__ == "__main__":
    main()
