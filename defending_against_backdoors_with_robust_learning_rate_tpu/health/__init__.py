"""In-program health sentinels + deterministic auto-recovery (ISSUE 14).

Two halves, mirroring the telemetry/service split the repo already uses:

- ``health/sentinel.py`` — the in-jit lane: per-round nonfinite counts,
  the committed-params finite bit and the cohort update-norm mass,
  computed INSIDE every compiled round program with ZERO added
  collectives (the sharded paths pack the scalars into the loss psum's
  lanes), plus the pure host-side EMA / z-score / spike math and the
  quarantine participation mask.
- ``health/monitor.py`` — the host-side policy: the unified divergence
  policy (``--health_policy abort|recover|record`` — ``--debug_nan``
  forces abort) every metrics boundary routes through, and the
  deterministic auto-recovery ladder the service driver runs under
  ``recover``: DISCARD -> ROLLBACK -> QUARANTINE -> HALT, every
  transition counted, journaled and crash-exact.
"""

from defending_against_backdoors_with_robust_learning_rate_tpu.health.sentinel import (  # noqa: F401
    boundary_keys, has_quarantine, health_keys, health_on, quarantine_ids,
    quarantine_mask)
from defending_against_backdoors_with_robust_learning_rate_tpu.health.monitor import (  # noqa: F401
    HealthIncident, HealthLadder, HealthRecovery, assess, check, ema_init,
    emit_rows, enforce, resolve_policy)
# NOTE: the `sentinel` NAME is deliberately not re-exported — it would
# shadow the health.sentinel SUBMODULE on the package object, breaking
# every `from ...health import sentinel as health_sentinel` importer.
