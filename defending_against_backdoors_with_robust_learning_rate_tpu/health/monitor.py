"""Host-side health policy: one divergence code path + the recovery ladder.

**Unified divergence policy.** Before this module, bad math had two
uncoordinated endpoints: ``--debug_nan`` aborted at the eval boundary and
``utils/guards.finite_warn`` printed a warning on the async drain path.
Both now route through ``assess``/``resolve_policy`` at the single metrics
emit site (train._emit_eval_body):

    abort    raise on a nonfinite boundary (``--debug_nan`` forces this);
    record   warn loudly, emit the Health/* rows, keep recording — the
             sweep default: a NaN cell is recorded-and-skipped by the
             queue, never a dead matrix;
    recover  same emission, plus the service driver runs the ladder.

**The deterministic auto-recovery ladder** (``serve`` under
``--health_policy recover``): at every eval boundary the driver fetches
the round's sentinel lanes (health/sentinel.py) and, on an incident,
walks DISCARD -> ROLLBACK -> QUARANTINE -> HALT:

    DISCARD      withdraw the unit's commit (params were retained — the
                 per-round families deliberately do not donate) and
                 re-dispatch the same round with a recovery nonce folded
                 into the round key: a transient numerics fault (one bad
                 batch draw, a bf16 edge) heals in place;
    ROLLBACK     tear the engine down and re-enter serve through the
                 crash-exact machinery: restore the newest digest-valid
                 checkpoint, truncate metrics.jsonl to its journaled
                 offset, replay — exactly what a kill -9 recovery does,
                 so a kill mid-rollback resumes the LADDER (this state
                 file), not the failure;
    QUARANTINE   feed the incident's suspect clients into the
                 participation mask (``--quarantine``, a traced program
                 constant — zero extra collectives, the churn protocol)
                 and re-enter from the checkpoint;
    HALT         raise loudly with the journal intact.

Every rung is counted and journaled: the ladder state lives in an
atomically-rewritten ``health_state.json`` (the chaos-state idiom), each
transition lands in ``status.json`` as a phase, and the per-rung counters
ride the run summary's ``service`` section.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.health import (
    sentinel)
from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    events as obs_events)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.checkpoint import (
    atomic_write_text)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.guards import (
    all_finite_device, finite_warn)

STATE_NAME = "health_state.json"
# ladder budgets per incident episode (a healthy boundary closes the
# episode): deterministic constants, not config — the ladder's value is
# that its walk is predictable enough to drill in CI
MAX_DISCARDS = 1
MAX_ROLLBACKS = 1
MAX_QUARANTINED = 32
# recovery nonce base folded into the round key on a DISCARD re-dispatch:
# far outside the round-id range, so recovery streams never collide with
# any round's own fold_in derivation
RECOVERY_NONCE = 1_000_003
# per-PROCESS ceiling on ladder re-entries (each ROLLBACK/QUARANTINE
# re-enters serve() recursively; episodes reset on healthy boundaries, so
# a long-lived service healing many incidents would otherwise creep
# toward the interpreter's recursion limit). A restart is free — the
# crash-exact resume + the ladder state file carry everything across it.
MAX_REENTRIES_PER_PROCESS = 50

RUNGS = ("discard", "rollback", "quarantine", "halt")
POLICIES = ("abort", "recover", "record")

TAGS = {
    "nonfinite": "Health/Nonfinite_Updates",
    "params_finite": "Health/Params_Finite",
    "update_norm": "Health/Update_Norm",
    "loss_z": "Health/Loss_Z",
    "norm_spike": "Health/Norm_Spike",
}


def check(cfg) -> None:
    """Validate the health flags loudly, before any build. Lives here
    (not in sentinel.py) because ``health_policy`` is a runtime field:
    sentinel.py is in the fingerprint audit's program-read scope
    (contracts.PROGRAM_READ_MODULES), where a runtime read is a
    violation."""
    if cfg.health not in sentinel.LEVELS:
        raise ValueError(f"--health must be one of {sentinel.LEVELS}, "
                         f"got {cfg.health!r}")
    if cfg.health_policy not in POLICIES:
        raise ValueError(f"--health_policy must be one of {POLICIES}, "
                         f"got {cfg.health_policy!r}")
    if cfg.quarantine and not sentinel.quarantine_ids(cfg):
        # a non-empty value that parses to ZERO ids ("," etc.) is an
        # operator mistake, not an empty quarantine — refuse it before
        # it half-arms the mask path
        raise ValueError(
            f"--quarantine {cfg.quarantine!r} contains no client ids; "
            f"pass a comma-separated id list or leave it empty")
    if cfg.quarantine:
        sentinel.quarantine_ids(cfg)   # validates the id list loudly


def resolve_policy(cfg) -> str:
    """The single source of the divergence policy: ``--debug_nan`` is the
    historical hard-abort switch and forces ``abort``; otherwise the
    ``--health_policy`` flag decides."""
    return "abort" if cfg.debug_nan else cfg.health_policy


class HealthIncident(FloatingPointError):
    """A numerics incident under the ``abort`` policy (or the ladder's
    HALT rung). FloatingPointError keeps the historical --debug_nan
    contract for callers that catch it."""


def assess(cfg, state, vals) -> Dict:
    """Judge one eval boundary's (host-fetched) values against the
    carried EMA state. Pure: returns a report dict with the Health/* row
    values, the incident verdict and the post-boundary EMA state —
    callers commit ``new_state`` LAST (the cum_poison_acc discipline:
    a supervised retry of the boundary must not double-fold the EMA).

    Works with or without the in-jit lane: when ``--health off`` only
    the boundary finite bit (vals['finite']) is judged and no rows are
    produced."""
    state = state or sentinel.ema_init()
    finite = bool(vals.get("finite", True))
    lane = "hlth_nonfinite" in vals
    report = {"rows": {}, "new_state": state, "healthy": True,
              "finite": finite, "why": ""}
    if not lane:
        report["healthy"] = finite
        if not finite:
            report["why"] = "nonfinite parameters"
        return report
    nonfinite = float(vals["hlth_nonfinite"])
    pfinite = float(vals["hlth_params_finite"])
    loss = float(vals["train_loss"])
    nsq = float(vals["hlth_update_normsq"])
    norm = math.sqrt(nsq) if (math.isfinite(nsq) and nsq >= 0) else nsq
    z = sentinel.loss_z(state, loss)
    spike = sentinel.norm_spike(state, norm, cfg.health_spike_factor)
    # the committed-delta norm lane exists only on the service ladder's
    # boundary check (HealthLadder.check) — it catches a magnitude fault
    # in the COMMIT at the boundary it happened, before the checkpoint;
    # the loss z-score alone would see it one boundary too late
    delta = float(vals.get("hlth_delta_norm", float("nan")))
    dspike = sentinel.delta_spike(state, delta, cfg.health_spike_factor)
    bad_params = not finite or pfinite < 1.0
    why = []
    if bad_params:
        why.append("nonfinite parameters")
    if nonfinite > 0:
        why.append(f"{int(nonfinite)} nonfinite client update(s)")
    if z > cfg.health_z_threshold:
        why.append(f"loss z-score {z:.1f} > {cfg.health_z_threshold}")
    if spike:
        why.append(f"update-norm spike (> {cfg.health_spike_factor}x EMA)")
    if dspike:
        why.append(f"committed-delta norm spike "
                   f"(> {cfg.health_spike_factor}x EMA)")
    # a finite-coordinate burst big enough to OVERFLOW the squared-norm
    # accumulation shows up as inf mass with zero nonfinite rows — the
    # spike comparisons above are isfinite-gated, so this must be its
    # own incident or the most catastrophic magnitude event would pass
    if not math.isfinite(norm):
        why.append("non-finite update-norm mass (magnitude overflow)")
    if not math.isnan(delta) and not math.isfinite(delta):
        why.append("non-finite committed-delta norm (magnitude overflow)")
    healthy = not why
    report.update(
        healthy=healthy, why="; ".join(why), finite=not bad_params,
        rows={"nonfinite": nonfinite, "params_finite": pfinite,
              "update_norm": norm, "loss_z": z,
              "norm_spike": 1.0 if spike else 0.0},
        # incident boundaries do not move the baseline they were judged
        # against (sentinel.ema_update docstring)
        new_state=(sentinel.ema_update(state, loss, norm, delta=delta)
                   if healthy else state))
    return report


def emit_rows(writer, report, step: int) -> None:
    """Health/* rows (deterministic — they join the crash-exact byte
    comparison, which is why the EMA state rides the round journal)."""
    for key, tag in TAGS.items():
        if key in report["rows"]:
            writer.scalar(tag, float(report["rows"][key]), step)


def enforce(cfg, report, where: str = "") -> bool:
    """The warn/abort half of the unified policy. Non-finiteness keeps
    its historical endpoint word-for-word (utils/guards.finite_warn —
    including the FloatingPointError the --debug_nan contract promises);
    the soft incidents (z-score, norm spike) warn, and abort only under
    the abort policy. Returns the healthy bit."""
    policy = resolve_policy(cfg)
    finite_warn(report["finite"], where=where,
                raise_error=policy == "abort")
    if not report["healthy"] and report["finite"]:
        # soft incident: its own loud line so `record` runs are greppable
        print(f"[health] WARNING: {report['why']}"
              f"{' at ' + where if where else ''}")
        if policy == "abort":
            raise HealthIncident(
                f"health incident{' at ' + where if where else ''}: "
                f"{report['why']}")
    return report["healthy"]


# defense-telemetry anomaly threshold DEFAULTS (ROADMAP PR-14
# follow-up): the same signatures the adaptation policy acts on
# (attack/adapt.py), here only OBSERVED — a low-severity ledger event,
# never a ladder trigger. The operative values live in config
# (``defense_flip_frac_hi`` / ``defense_low_margin_hi``,
# FIELD_PROVENANCE-tagged); these module constants are the argparse
# defaults' mirror so bare callers (tests) get the shipped calibration.
DEFENSE_FLIP_FRAC_HI = 0.5      # defense reversing most coordinates
DEFENSE_LOW_MARGIN_HI = 0.25    # electorate-splitting histogram mass


def defense_anomaly(defense: Optional[Dict],
                    flip_hi: Optional[float] = None,
                    low_margin_hi: Optional[float] = None) -> str:
    """Judge one boundary's drained Defense/* summary
    (obs/telemetry.host_summary) for the defense-side anomaly
    signatures; returns the reason string ('' = nothing anomalous).

    Thresholds default to the shipped calibration above; the service
    driver passes the config fields (``defense_flip_frac_hi`` /
    ``defense_low_margin_hi``) so deployments can recalibrate from the
    reputation plane's measured agreement quantiles without a code
    change (config.FIELD_PROVENANCE documents the derivation).

    Deliberately decoupled from ``assess``: a defense anomaly is the
    MECHANISM misbehaving (over-flipping, a splitting electorate), not
    bad numerics — it must be visible in the same event stream as the
    numerics incidents (the service driver emits it as a LOW-severity
    ``health/defense_anomaly`` ledger record) without ever feeding the
    recovery ladder."""
    flip_hi = DEFENSE_FLIP_FRAC_HI if flip_hi is None else flip_hi
    low_margin_hi = (DEFENSE_LOW_MARGIN_HI if low_margin_hi is None
                     else low_margin_hi)
    if not defense or "tel_flip_frac" not in defense:
        return ""
    why = []
    flip = float(defense["tel_flip_frac"])
    if flip >= flip_hi:
        why.append(f"flip fraction {flip:.2f} >= {flip_hi} "
                   f"(defense reversing most coordinates)")
    hist = defense.get("tel_margin_hist")
    if hist:
        from defending_against_backdoors_with_robust_learning_rate_tpu.attack.adapt import (
            low_margin_mass)
        mass = low_margin_mass(hist)
        if mass >= low_margin_hi:
            why.append(f"low-margin vote mass {mass:.2f} >= "
                       f"{low_margin_hi} (electorate splitting)")
    return "; ".join(why)


# --------------------------------------------------------------- the ladder


class HealthRecovery(RuntimeError):
    """Control-flow carrier for the rungs that rebuild the engine. The
    service driver catches it, closes the current engine/writer and
    re-enters serve through the crash-exact resume machinery."""

    def __init__(self, rung: str, rnd: int, quarantine: str = ""):
        super().__init__(f"health ladder: {rung} at round {rnd}")
        self.rung = rung
        self.rnd = rnd
        self.quarantine = quarantine


class HealthLadder:
    """The per-service ladder: carried EMA baseline, per-episode rung
    budget, cumulative counters and the quarantine list — all persisted
    through ``health_state.json`` so a kill at ANY rung resumes the
    ladder exactly where it stood."""

    def __init__(self, cfg, state_path: Optional[str] = None):
        from defending_against_backdoors_with_robust_learning_rate_tpu.utils.metrics import (
            run_name)
        self.cfg = cfg
        self.state_path = state_path
        # in-memory (deliberately unpersisted): recovery re-entries THIS
        # process has performed — the serve() recursion-depth bound
        self.reentries = 0
        # optional incident hook, on_rung(rung, rnd): the service driver
        # wires the flight-recorder snapshot + profile trigger here so a
        # rung leaves its evidence even with the event ledger off
        self.on_rung = None
        # the state file lives at the log_dir root (the status.json /
        # chaos_state.json convention, where external watchers look),
        # so it carries the run's identity: a DIFFERENT experiment
        # sharing the log_dir must start a fresh ladder, not inherit
        # this one's EMA baseline, spent budgets and quarantine list.
        # run_name deliberately ignores --quarantine, so a QUARANTINE
        # re-entry and a kill-resume both match their own state.
        self.run = run_name(cfg)
        self.state = {"run": self.run, "ema": sentinel.ema_init(),
                      "episode": {"discards": 0, "rollbacks": 0,
                                  "quarantines": 0, "open": False},
                      "counters": {r: 0 for r in RUNGS},
                      "quarantined": [], "incidents": 0}
        if state_path and os.path.exists(state_path):
            try:
                with open(state_path, encoding="utf-8") as f:
                    loaded = json.load(f)
                if loaded.get("run") == self.run:
                    self.state.update(loaded)
                else:
                    print(f"[health] {state_path} belongs to run "
                          f"{loaded.get('run')!r} — starting a fresh "
                          f"ladder for {self.run!r}")
            except (OSError, ValueError):
                pass
        # a pre-existing --quarantine (a prior QUARANTINE rung's re-entry)
        # is part of the ladder's record
        for cid in sentinel.quarantine_ids(cfg):
            if cid not in self.state["quarantined"]:
                self.state["quarantined"].append(cid)

    # ------------------------------------------------------------ persistence

    def _save(self) -> None:
        if self.state_path:
            atomic_write_text(self.state_path, json.dumps(self.state))

    @property
    def counters(self) -> Dict[str, int]:
        return dict(self.state["counters"])

    # ------------------------------------------------------------- judgement

    def check(self, cfg, eng, rnd: int, prev_params=None) -> Dict:
        """Synchronously judge round ``rnd``'s sentinel lanes (a small
        host fetch — the recover policy trades one tiny boundary sync
        for the ability to act BEFORE the bad commit reaches the
        checkpoint). ``prev_params`` (the params the round was dispatched
        from — the driver retains them for the DISCARD rung anyway) arms
        the committed-delta norm lane: sentinel.delta_spike catches a
        magnitude fault in the commit itself at THIS boundary, where the
        loss z-score would only see it at the next one, after the bad
        params had reached a checkpoint. Returns the assess() report."""
        info = eng._last_info
        vals = {"finite": bool(np.asarray(
            jax.device_get(all_finite_device(eng.model_params))))}
        if prev_params is not None:
            vals["hlth_delta_norm"] = delta_norm(prev_params,
                                                 eng.model_params)
        for key in sentinel.boundary_keys(cfg):
            if key in info:
                vals[key] = float(np.asarray(info[key]))
        if "train_loss" in info:
            vals["train_loss"] = float(np.asarray(info["train_loss"]))
        else:
            vals["train_loss"] = float("nan")
        return assess(cfg, self.state["ema"], vals)

    def note_healthy(self, report) -> None:
        """A healthy boundary: fold it into the EMA baseline and close
        any open incident episode (the rung budget resets; cumulative
        counters and the quarantine list persist)."""
        self.state["ema"] = report["new_state"]
        if self.state["episode"]["open"]:
            self.state["episode"] = {"discards": 0, "rollbacks": 0,
                                     "quarantines": 0, "open": False}
        self._save()

    def next_rung(self, cfg, quarantine_ok: bool = True) -> str:
        """The deterministic escalation: every rung's budget is a named
        constant, and a rung that cannot run (no checkpoint dir to roll
        back to, suspect budget exhausted, ``quarantine_ok=False`` on
        the host-sampled path whose program never sees the sampled
        client ids) is skipped — the walk always terminates at HALT."""
        ep = self.state["episode"]
        if ep["discards"] < MAX_DISCARDS:
            return "discard"
        if ep["rollbacks"] < MAX_ROLLBACKS and cfg.checkpoint_dir:
            return "rollback"
        # quarantine re-enters through the SAME checkpoint-restore
        # machinery as rollback — without a checkpoint dir the re-entry
        # would silently restart from round 0, so the rung is skipped
        # exactly like rollback
        if (quarantine_ok and cfg.checkpoint_dir
                and ep["quarantines"] < 1
                and len(self.state["quarantined"]) < MAX_QUARANTINED):
            return "quarantine"
        return "halt"

    def record(self, rung: str, rnd: int, sup=None) -> None:
        ep = self.state["episode"]
        ep["open"] = True
        if rung == "discard":
            ep["discards"] += 1
        elif rung == "rollback":
            ep["rollbacks"] += 1
        elif rung == "quarantine":
            ep["quarantines"] += 1
        self.state["counters"][rung] += 1
        self.state["incidents"] += 1
        self._save()
        # the rung as a typed ledger record, emitted AFTER the state
        # save: the ladder state is what guarantees exactly-once across
        # a kill-mid-recovery resume (the resumed process walks the
        # journaled ladder, it never re-records the rung)
        obs_events.emit("health/rung",
                        severity="error" if rung == "halt" else "warn",
                        round=rnd, rung=rung,
                        incidents=self.state["incidents"])
        if self.on_rung is not None:
            try:
                self.on_rung(rung, rnd)
            except Exception:
                pass  # observability must never take down the run
        if sup is not None:
            # a counted, journaled status.json phase per transition —
            # recovery is observable, not inferred from silence
            sup.phase(f"health_{rung}", health_round=rnd,
                      **{f"health_{r}s": c
                         for r, c in self.state["counters"].items()})

    def suspects(self, eng, rnd: int) -> List[int]:
        """The QUARANTINE rung's suspect set: the incident round's
        sampled clients whose update was nonfinite (hlth_agent_bad,
        single-device paths), degrading to the whole sampled cohort on
        the sharded paths (materializing per-slot bits there would cost
        the all_gather the zero-collective lane forbids)."""
        info = eng._last_info
        if "sampled" not in info:
            return []
        ids = np.asarray(info["sampled"]).reshape(-1)
        if "hlth_agent_bad" in info:
            bad = np.asarray(info["hlth_agent_bad"]).reshape(-1)
            if bad.any():
                ids = ids[bad.astype(bool)]
        merged = sorted(set(self.state["quarantined"])
                        | set(int(i) for i in ids))
        return merged[:MAX_QUARANTINED]

    def quarantine_spec(self, eng, rnd: int) -> str:
        ids = self.suspects(eng, rnd)
        self.state["quarantined"] = ids
        self._save()
        return ",".join(str(i) for i in ids)

    def summary(self) -> Dict:
        return {"incidents": self.state["incidents"],
                **{f"health_{r}s": c
                   for r, c in self.state["counters"].items()},
                "quarantined": list(self.state["quarantined"])}


def ema_init():
    return sentinel.ema_init()


def delta_norm(prev, params) -> float:
    """Host-fetched l2 norm of the committed delta (params - prev) over
    finite coordinates — the ladder's boundary-cadence magnitude lane
    (one tiny reduction per eval boundary, recover policy only)."""
    total = sum(
        jnp.sum(jnp.where(jnp.isfinite(d), d, 0.0) ** 2)
        for d in (jnp.asarray(b - a, dtype=jnp.float32)
                  for a, b in zip(jax.tree_util.tree_leaves(prev),
                                  jax.tree_util.tree_leaves(params))))
    return float(np.sqrt(np.asarray(jax.device_get(total))))


def poison_params(params):
    """Chaos ``nan@N``: write one NaN into the first parameter leaf —
    the deterministic stand-in for a bf16 NaN burst (service/chaos.py
    decides WHEN; this is the how)."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    idx = (0,) * leaves[0].ndim
    leaves[0] = leaves[0].at[idx].set(jnp.nan)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def spike_params(prev, params, factor: float):
    """Chaos ``spike@N:x``: scale the round's committed delta by x —
    a finite magnitude burst that trips the norm-spike sentinel without
    touching finiteness."""
    return jax.tree_util.tree_map(
        lambda p0, p1: p0 + factor * (p1 - p0), prev, params)
