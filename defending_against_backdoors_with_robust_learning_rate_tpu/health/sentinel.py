"""The in-jit health lane: numerics sentinels computed inside the round.

The round program already computes everything a numerics-health verdict
needs — the stacked per-agent updates, the committed params, the mean
loss — so the sentinel is a handful of reductions riding the existing
program, not a new dispatch:

- ``hlth_nonfinite``       f32 count of PARTICIPATING agents whose update
                           carries any NaN/inf coordinate (masked-out
                           rows — injected corrupt payloads the faults
                           path already rejects — do not count: they are
                           handled, not a health incident);
- ``hlth_params_finite``   the committed-params finite bit (1.0/0.0),
                           per ROUND — unlike the boundary-only
                           ``all_finite_device`` eval check, a chained
                           block carries it for every scanned round;
- ``hlth_update_normsq``   the cohort's summed squared update norm over
                           FINITE coordinates (a magnitude burst shows
                           here, a NaN burst in the nonfinite lane; the
                           host-side EMA turns it into the spike bit);
- ``hlth_agent_bad``       [m] per-slot nonfinite bits — the QUARANTINE
                           rung's suspect evidence. Single-device paths
                           only: the sharded body would need an
                           all_gather to materialize it, and the health
                           lane's contract is ZERO added collectives
                           (the sharded ladder falls back to the whole
                           sampled cohort as the suspect set).

Collective cost: zero everywhere. The vmap paths are collective-free by
construction; the sharded paths pack the two scalar lanes into the loss
psum the body already pays (a shape change from scalar to [3], not a
count change — the buffered mode's packed-lane idiom), pinned by the
``*_hlth`` CheckSpecs in analysis/contracts.py at 1/8/16-way.

The host-side half (EMA, z-score, spike bit) lives as pure functions
here so health/monitor.py, the service ladder and the tests share one
formula; state is a tiny JSON-able dict the driver journals alongside
each checkpoint, which is what keeps replayed ``Health/*`` rows
byte-identical across a crash-exact resume.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

PREFIX = "hlth_"
LEVELS = ("on", "off")
# EMA decay for the loss / update-norm baselines (host-side, boundary
# cadence). Deterministic Python-float arithmetic: the same stream of
# boundary values produces bit-identical Health/* rows on every replay.
EMA_DECAY = 0.9
# boundaries of warmup before the z-score / spike bit may fire (the first
# boundaries ARE the distribution being learned)
WARMUP_BOUNDARIES = 3
_EPS = 1e-12


def health_on(cfg) -> bool:
    return cfg.health == "on"


def has_quarantine(cfg) -> bool:
    # judged on the PARSED id set, not string truthiness: a value like
    # "," parses to zero ids and must not arm the mask path (whose
    # composition would crash on the None mask) — monitor.check
    # additionally rejects such a value loudly before any build
    return bool(cfg.quarantine) and bool(quarantine_ids(cfg))


def quarantine_ids(cfg):
    """The quarantined client ids as a sorted int tuple (program
    constants — the set is baked into the traced membership test)."""
    try:
        ids = sorted({int(tok) for tok in cfg.quarantine.split(",") if tok})
    except ValueError as e:
        raise ValueError(
            f"--quarantine must be a comma-separated client-id list, "
            f"got {cfg.quarantine!r}") from e
    if any(i < 0 for i in ids):
        raise ValueError(f"--quarantine ids must be >= 0, got {ids}")
    return tuple(ids)


def quarantine_mask(cfg, sampled):
    """[m] bool: True = this sampled slot's client is NOT quarantined.

    The quarantine set is a traced CONSTANT (program provenance, like
    churn_seed), so membership is one broadcast compare — elementwise,
    replicated, zero collectives. The mask joins the participation-mask
    protocol exactly like a churn absence: a quarantined client's update
    never reaches aggregation."""
    ids = quarantine_ids(cfg)
    if not ids:
        return None
    q = jnp.asarray(ids, dtype=sampled.dtype)
    return ~jnp.any(sampled[:, None] == q[None, :], axis=1)


def health_keys(cfg, sharded: bool = False):
    """The static hlth_* key set cfg's round program emits — chained
    scans and shard_map out_specs need it ahead of tracing (the
    telemetry_keys discipline)."""
    if not health_on(cfg):
        return ()
    keys = ("hlth_nonfinite", "hlth_params_finite", "hlth_update_normsq")
    if not sharded:
        keys = keys + ("hlth_agent_bad",)
    return keys


def boundary_keys(cfg):
    """The scalar subset the eval boundary fetches into ``vals`` (the
    [m] suspect vector stays in the info dict for the ladder — it is
    evidence, not a metrics row)."""
    return tuple(k for k in health_keys(cfg) if k != "hlth_agent_bad")


# --- in-jit pieces --------------------------------------------------------

def params_finite_bit(params):
    """1.0 iff every committed-params coordinate is finite (f32 scalar;
    replicated inputs -> replicated bit, no collective)."""
    ok = jnp.all(jnp.stack([jnp.isfinite(leaf).all()
                            for leaf in jax.tree_util.tree_leaves(params)]))
    return ok.astype(jnp.float32)


def _row_stats(updates, mask=None):
    """([rows] bad bits, [rows] finite-coordinate squared norms) over the
    stacked update leaves — the shared arithmetic of the vmap sentinel
    and the sharded local partials (their cross-path parity depends on
    accumulating leaves in the same order)."""
    leaves = jax.tree_util.tree_leaves(updates)
    rows = leaves[0].shape[0]
    bad = jnp.zeros((rows,), bool)
    nsq = jnp.zeros((rows,), jnp.float32)
    for u in leaves:
        uf = u.reshape(rows, -1).astype(jnp.float32)
        finite = jnp.isfinite(uf)
        bad = bad | ~jnp.all(finite, axis=1)
        safe = jnp.where(finite, uf, 0.0)
        nsq = nsq + jnp.sum(safe * safe, axis=1)
    if mask is not None:
        bad = bad & mask
        nsq = jnp.where(mask, nsq, 0.0)
    return bad, nsq


def sentinel(cfg, updates, new_params, mask=None, agent_bad: bool = True):
    """The vmap-path sentinel dict (single-device, cohort, host,
    megabatch, buffered — every path whose updates hold the full [m]
    cohort). Pure jnp reductions, zero collectives."""
    bad, nsq = _row_stats(updates, mask)
    out = {"hlth_nonfinite": jnp.sum(bad.astype(jnp.float32)),
           "hlth_update_normsq": jnp.sum(nsq),
           "hlth_params_finite": params_finite_bit(new_params)}
    if agent_bad:
        out["hlth_agent_bad"] = bad
    return out


def local_lanes(updates_local, mask_local=None):
    """[2] f32 (bad count, normsq) partials of THIS device's agent block —
    the sharded body stacks them into the loss psum's lanes (a shape
    change on an existing collective, never a new one)."""
    bad, nsq = _row_stats(updates_local, mask_local)
    return jnp.stack([jnp.sum(bad.astype(jnp.float32)), jnp.sum(nsq)])


def finish_sharded(bad_count, normsq, new_params):
    """Assemble the sharded sentinel dict from the psummed lanes + the
    replicated committed params (no hlth_agent_bad: materializing the
    [m] vector would cost the all_gather the lane's zero-collective
    contract forbids — the ladder's suspect set degrades to the whole
    sampled cohort, documented in health/monitor.py)."""
    return {"hlth_nonfinite": bad_count,
            "hlth_update_normsq": normsq,
            "hlth_params_finite": params_finite_bit(new_params)}


# --- host-side pure math (EMA / z-score / spike bit) ----------------------

def ema_init():
    """Fresh EMA state (JSON-able — it rides the round journal so a
    crash-exact resume replays identical Health/* rows). ``delta_ema``
    (the committed-delta norm baseline) is only ever fed by the service
    ladder's boundary check — the metrics-path EMA never folds it, so
    Health/* rows are identical whether or not a ladder is armed."""
    return {"n": 0, "loss_ema": 0.0, "loss_var": 0.0, "norm_ema": 0.0,
            "delta_ema": 0.0}


def loss_z(state, loss: float) -> float:
    """z-score of this boundary's train loss against the carried EMA
    baseline; 0.0 during warmup or when the loss is nonfinite (a
    nonfinite loss already trips the nonfinite lane — the z lane must
    stay a readable number)."""
    if state["n"] < WARMUP_BOUNDARIES or not math.isfinite(loss):
        return 0.0
    return (loss - state["loss_ema"]) / math.sqrt(state["loss_var"] + _EPS)


def norm_spike(state, norm: float, factor: float) -> bool:
    """True when the update norm exceeds ``factor`` x its EMA baseline
    (post-warmup, finite values only)."""
    return (state["n"] >= WARMUP_BOUNDARIES and math.isfinite(norm)
            and norm > factor * max(state["norm_ema"], _EPS))


def delta_spike(state, delta: float, factor: float) -> bool:
    """True when the COMMITTED-delta norm (this boundary's params minus
    the previous round's — the service ladder computes it host-side,
    health/monitor.HealthLadder.check) bursts past ``factor`` x its own
    EMA baseline. This is the detector that catches a magnitude fault in
    the commit itself AT the boundary it happened — the loss z-score
    only sees such damage one boundary later, after the bad params have
    reached a checkpoint the ROLLBACK rung would then restore."""
    return (state["n"] >= WARMUP_BOUNDARIES and math.isfinite(delta)
            and state.get("delta_ema", 0.0) > 0.0
            and delta > factor * max(state.get("delta_ema", 0.0), _EPS))


def ema_update(state, loss: float, norm: float,
               delta: float = float("nan")):
    """Fold one HEALTHY boundary into the EMA baselines (incident
    boundaries are deliberately not folded: a NaN or a spike must not
    move the baseline it was judged against). Returns a new dict.
    ``delta`` (the committed-delta norm) is only passed by the service
    ladder; the metrics path leaves it NaN so its baseline stays 0.0
    there."""
    s = dict(state)
    if math.isfinite(delta):
        s["delta_ema"] = (delta if s.get("delta_ema", 0.0) == 0.0
                          else EMA_DECAY * s.get("delta_ema", 0.0)
                          + (1.0 - EMA_DECAY) * delta)
    if math.isfinite(loss):
        if s["n"] == 0:
            s["loss_ema"], s["loss_var"] = loss, 0.0
        else:
            d = loss - s["loss_ema"]
            s["loss_ema"] = s["loss_ema"] + (1.0 - EMA_DECAY) * d
            s["loss_var"] = (EMA_DECAY * s["loss_var"]
                             + (1.0 - EMA_DECAY) * d * d)
    if math.isfinite(norm):
        s["norm_ema"] = (norm if s["n"] == 0
                         else EMA_DECAY * s["norm_ema"]
                         + (1.0 - EMA_DECAY) * norm)
    s["n"] = s["n"] + 1
    return s
