"""Shared observability constants — single-sourced, import-cheap.

``NON_TIMING_PREFIXES`` is THE exclusion list for crash-exact /
cross-layout metrics-row comparisons: rows whose tag starts with one of
these prefixes measure wall-clock time, service-life counters or
machine-local memory, and legitimately differ between two runs of the
same seed/config. Every byte-compare of ``metrics.jsonl`` streams —
tests/test_service.py, tests/test_health.py, tests/test_obs.py,
tests/test_async_metrics.py, the CI parity steps
(.github/workflows/ci.yml) and the verify-skill drill recipes — must
filter on this tuple instead of hand-duplicating it (the list drifted
once per PR between PR 7 and PR 14).

Stdlib-only on purpose: CI heredocs and the run-report tooling import it
on machines without jax.
"""

NON_TIMING_PREFIXES = (
    "Throughput/",   # rounds/sec — wall-clock by definition
    "Service/",      # retry/degradation counters — service-life, not math
    "Spans/",        # host span aggregates — wall-clock, mode-specific sets
    "Memory/",       # HBM/RSS watermarks — machine-local
    "Device/",       # profiler attribution — wall-clock, capture-dependent
    "_run/",         # the _run/start stream-boundary stamp
)
