"""Budgeted anomaly-triggered profiling: arm the round profiler when
the flight window (or an incident hook) says something just got slow.

Always-on profiling is too expensive for a resident fleet and manual
profiling always arrives after the anomaly is gone. The middle path:

- the service driver feeds every supervisor/health incident into
  ``ProfileTrigger.note_incident``; between units, ``step`` also scans
  the flight recorder's window for a span whose latest per-round
  duration is a ``Z_THRESHOLD``-sigma outlier vs its own history
  (``span_zscores``);
- either signal arms a fresh ``obs/attribution.RoundProfiler`` for
  ``DEFAULT_CAPTURE_ROUNDS`` steady rounds by slotting it into the
  engine's ``prof`` seat — the dispatch loop then drives it exactly
  like a user-requested ``--profile_rounds`` capture (which always
  wins the seat: the trigger never preempts an explicit request);
- when the window closes, ``attribute`` runs offline on the captured
  trace and the device split lands as typed ``obs/trigger_*`` ledger
  events (armed / capture / attribution) plus ``rlr_trigger_*``
  exporter gauges — evidence attached to the run, no human in the
  loop.

Hard budget: ``MAX_CAPTURES`` windows per process life — an unstable
run must not profile itself into the ground. Gated by
``--trigger_profile on|off`` (default OFF: z-arming is inherently
timing-dependent, and the attribution events would differ between
byte-identity drill twins; the ``obs/trigger_*`` prefix is per-life in
``obs/events`` for exactly that reason).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from . import attribution
from . import events as obs_events

DEFAULT_CAPTURE_ROUNDS = 6
MAX_CAPTURES = 2
Z_THRESHOLD = 4.0
MIN_WINDOW = 8   # prior samples a span needs before z-scores mean anything


def span_zscores(window: List[Dict[str, Any]],
                 min_points: int = MIN_WINDOW) -> Dict[str, float]:
    """Per-span z-score of the LATEST record's duration against that
    span's history in the flight window. The sigma floor (5% of the
    mean) keeps ultra-stable spans from flagging micro-jitter."""
    if len(window) < min_points + 1:
        return {}
    latest = window[-1].get("spans") or {}
    out: Dict[str, float] = {}
    for name, dur in latest.items():
        prior = [rec["spans"][name] for rec in window[:-1]
                 if isinstance(rec.get("spans"), dict)
                 and name in rec["spans"]]
        if len(prior) < min_points:
            continue
        mean = sum(prior) / len(prior)
        var = sum((p - mean) ** 2 for p in prior) / len(prior)
        sigma = max(var ** 0.5, 0.05 * abs(mean), 1e-6)
        out[name] = (dur - mean) / sigma
    return out


class ProfileTrigger:
    """Anomaly-armed, budgeted wrapper around the engine's profiler
    seat (module docstring). All methods are driver-thread only."""

    def __init__(self, eng, run_dir: str, exporter=None,
                 n_rounds: int = DEFAULT_CAPTURE_ROUNDS,
                 max_captures: int = MAX_CAPTURES,
                 z_threshold: float = Z_THRESHOLD,
                 make_profiler=attribution.RoundProfiler):
        self.eng = eng
        self.run_dir = run_dir
        self.exporter = exporter
        self.n_rounds = n_rounds
        self.max_captures = max_captures
        self.z_threshold = z_threshold
        self._make_profiler = make_profiler
        self.captures = 0
        self.prof = None                     # the window we armed, if any
        self._pending: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------------- signals

    def note_incident(self, kind: str, rnd: Optional[int]) -> None:
        """An incident hook fired (health rung, supervisor retry/give-up
        and friends); arm at the next unit boundary."""
        if self.captures < self.max_captures and self._pending is None:
            self._pending = {"cause": kind, "round": rnd}

    def _scan(self) -> Optional[Dict[str, Any]]:
        flight = getattr(self.eng, "flight", None)
        if flight is None:
            return None
        scores = span_zscores(flight.window())
        if not scores:
            return None
        name, z = max(scores.items(), key=lambda kv: kv[1])
        if z < self.z_threshold:
            return None
        return {"cause": f"zscore:{name}", "z": round(z, 2)}

    # ------------------------------------------------------------ lifecycle

    def step(self, rnd: int) -> None:
        """Per-unit driver hook: close a finished window, else consider
        arming a new one."""
        if self.prof is not None:
            if self.prof.done:
                self._finish(rnd)
            return
        if self.captures >= self.max_captures:
            return
        trip = self._pending or self._scan()
        self._pending = None
        if trip is not None:
            self._arm(rnd, trip)

    def _arm(self, rnd: int, trip: Dict[str, Any]) -> None:
        if self.eng.prof is not None:
            return   # an explicit --profile_rounds capture owns the seat
        trace_dir = os.path.join(
            self.run_dir, "trigger_profile", f"cap{self.captures}")
        try:
            prof = self._make_profiler(self.n_rounds, trace_dir)
        except Exception:
            return   # profiler backends may be absent; never down the run
        self.prof = prof
        self.eng.prof = prof       # the dispatch loop now drives it
        obs_events.emit("obs/trigger_armed", severity="warn", round=rnd,
                        cause=trip.get("cause"),
                        z=trip.get("z"), rounds=self.n_rounds,
                        capture=self.captures)
        flight = getattr(self.eng, "flight", None)
        if flight is not None:
            # the window that tripped the trigger IS the evidence
            flight.snapshot(f"trigger_armed:{trip.get('cause')}", rnd)

    def _finish(self, rnd: int) -> None:
        prof, self.prof = self.prof, None
        if self.eng.prof is prof:
            self.eng.prof = None
        self.captures += 1
        try:
            attr = prof.result()
        except Exception:
            attr = None
        obs_events.emit("obs/trigger_capture", round=rnd,
                        capture=self.captures - 1,
                        rounds=prof.captured,
                        attributed=bool(attr and attr.get("device_present")))
        if attr and attr.get("device_present"):
            per = attr.get("per_round", {})
            obs_events.emit(
                "obs/trigger_attribution", round=rnd,
                capture=self.captures - 1,
                compute_ms=per.get("compute_ms"),
                collective_ms=per.get("collective_ms"),
                gap_ms=per.get("gap_ms"),
                collective_frac=attr.get("collective_frac"))
            if self.exporter is not None:
                ex = self.exporter
                ex.set("trigger_compute_ms", per.get("compute_ms", 0.0),
                       help_text="Per-round device compute ms from the "
                                 "last triggered capture")
                ex.set("trigger_collective_frac",
                       attr.get("collective_frac", 0.0),
                       help_text="Collective share of device time from "
                                 "the last triggered capture")
                ex.set("trigger_gap_ms", per.get("gap_ms", 0.0),
                       help_text="Per-round device idle-gap ms from the "
                                 "last triggered capture")
        if self.exporter is not None:
            self.exporter.set("trigger_captures_total", self.captures,
                              mtype="counter",
                              help_text="Anomaly-triggered profile "
                                        "captures completed this run")
            self.exporter.flush()

    def finalize(self, rnd: int) -> None:
        """End-of-run hook: a window still open at exit is harvested if
        it captured anything (short runs arm near the end), else torn
        down without burning the budget's evidence trail."""
        if self.prof is None:
            return
        try:
            self.prof.close(getattr(self.eng, "params", None))
        except Exception:
            pass
        if self.prof.captured > 0:
            self._finish(rnd)
        else:
            if self.eng.prof is self.prof:
                self.eng.prof = None
            self.prof = None
