"""Cross-run perf trajectory: fold bench artifacts into one committed
series and judge regressions against a pinned tolerance.

Five ``BENCH_r*.json`` files sat on disk with no trajectory between
them; this module (driven by ``scripts/bench_trajectory.py``) folds each
bench artifact — either the session-runner record shape
(``{"n", "cmd", "rc", "tail", "parsed": {...}}``) or a bare bench.py
result object (``{"metric": "fl_rounds_per_sec", ...}``) — into
``trajectory.json``::

    {"version": 1, "tolerance": 0.15, "series": [
        {"label": "r01", "source": "BENCH_r01.json", "ok": false,
         "note": "bench rc 1"},
        {"label": "r03", "ok": true, "rounds_per_sec": 2.2268,
         "mfu": 0.1011, "group": "tpu|fmnist|f32", ...}, ...]}

Judgement extends the ``obs/report.py`` PASS/FAIL workflow to the time
axis: points are grouped by comparability (backend class, bench config,
dtype, reduced-shapes flag — a CPU-fallback number must never be judged
against a TPU flagship), and within a group each point is compared to
the best earlier point; a drop past ``tolerance`` is a REGRESSION. Exit
codes mirror the report gate: 0 all pass, 1 regression, 2 malformed
input. Stdlib-only.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_TOLERANCE = 0.15
VERSION = 1

# judged metrics and the point key each stores its value under; fleet
# artifacts (service/queue.py --scheduler writes fleet_bench.json) join
# the same series in their own comparability group — a fleet cells/hour
# number is never compared against a solo rounds/sec flagship
METRICS = {"fl_rounds_per_sec": "rounds_per_sec",
           "fleet_cells_per_hour": "cells_per_hour",
           "bank_build_clients_per_sec": "clients_per_sec"}


class MalformedArtifact(ValueError):
    """A file that is neither a session bench record nor a bench result
    object (exit code 2 — distinct from a *recorded* failed run, which
    folds as an ok:false point and is skipped by the judge)."""


def _group_key(parsed: Dict[str, Any]) -> str:
    device = str(parsed.get("device", ""))
    plat = "tpu" if "tpu" in device.lower() else "cpu"
    if parsed.get("reduced_shapes"):
        plat += "_reduced"
    config = parsed.get("bench_config", "fmnist")
    dtype = parsed.get("dtype", "f32")
    return f"{plat}|{config}|{dtype}"


def parse_artifact(path: str) -> Dict[str, Any]:
    """One bench artifact -> one trajectory point."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedArtifact(f"{path}: {e}") from e
    if not isinstance(data, dict):
        raise MalformedArtifact(f"{path}: expected a JSON object")
    source = os.path.basename(path)
    if "metric" in data:                       # bare bench.py result
        parsed: Optional[Dict[str, Any]] = data
        label = os.path.splitext(source)[0]
        rc = 0
    elif "cmd" in data or "rc" in data:        # session-runner record
        parsed = data.get("parsed")
        label = f"r{int(data.get('n', 0)):02d}"
        rc = int(data.get("rc", 0))
    else:
        raise MalformedArtifact(
            f"{path}: neither a bench result (no 'metric') nor a "
            f"session record (no 'cmd'/'rc')")
    if rc != 0 or not isinstance(parsed, dict) \
            or parsed.get("metric") not in METRICS \
            or "value" not in parsed:
        return {"label": label, "source": source, "ok": False,
                "note": (f"bench rc {rc}" if rc else "no parsed metric")}
    metric = parsed["metric"]
    group = _group_key(parsed)
    if metric == "fleet_cells_per_hour":
        group = f"fleet_{group}"
    elif metric == "bank_build_clients_per_sec":
        # build throughput joins its own group keyed by the pinned cell
        # (population + worker count) — a 4-worker 1M number must never
        # be judged against serial or a different population
        group = (f"bank_build_{group}|pop{parsed.get('population', 0)}"
                 f"|w{parsed.get('workers', 1)}")
    point = {
        "label": label, "source": source, "ok": True,
        "metric": metric,
        METRICS[metric]: float(parsed["value"]),
        "group": group,
        "device": parsed.get("device"),
    }
    for key in ("mfu", "tflops_per_sec", "tflop_per_round", "compile_s",
                "chain", "vs_baseline", "dtype", "bench_config",
                "reduced_shapes", "backend_note", "slot_occupancy",
                "cells", "scheduler_bins", "wall_s", "population",
                "workers", "shard_clients"):
        if key in parsed:
            point[key] = parsed[key]
    return point


def point_value(point: Dict[str, Any]) -> float:
    """The judged value of an ok point, whichever metric it carries
    (committed pre-fleet points have no 'metric' field and store
    rounds_per_sec — the historical schema stays readable)."""
    for key in METRICS.values():
        if key in point:
            return float(point[key])
    raise MalformedArtifact(
        f"point {point.get('label')!r} has no judged value "
        f"(expected one of {sorted(METRICS.values())})")


# --------------------------------------------------------------------------
# the committed series
# --------------------------------------------------------------------------

def load(path: str) -> Dict[str, Any]:
    if not os.path.exists(path):
        return {"version": VERSION, "tolerance": DEFAULT_TOLERANCE,
                "series": []}
    try:
        with open(path, encoding="utf-8") as f:
            traj = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedArtifact(f"{path}: {e}") from e
    if not isinstance(traj, dict) or not isinstance(
            traj.get("series"), list):
        raise MalformedArtifact(f"{path}: expected "
                                f"{{version, tolerance, series: []}}")
    return traj


def save(path: str, traj: Dict[str, Any]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(traj, f, indent=1, sort_keys=True)
        f.write("\n")


def _label_key(label: str):
    """Session labels sort numerically (r2 < r10 < r100 — a plain
    lexicographic sort would misorder the time axis from session 100
    on); anything else sorts after them, alphabetically."""
    if label.startswith("r") and label[1:].isdigit():
        return (0, int(label[1:]), label)
    return (1, 0, label)


def fold(traj: Dict[str, Any], points: List[Dict[str, Any]]
         ) -> Dict[str, Any]:
    """Merge points into the series (replace-by-label, then ordered by
    session number — the time axis judge() walks)."""
    by_label = {p["label"]: p for p in traj["series"]}
    for point in points:
        by_label[point["label"]] = point
    traj["series"] = [by_label[k] for k in sorted(by_label,
                                                  key=_label_key)]
    return traj


def judge(traj: Dict[str, Any]) -> Tuple[List[Dict[str, Any]], bool]:
    """[{label, group, value, best_prev, floor, pass, note}] for every
    ok point, plus the overall verdict. Each point is judged against the
    best EARLIER ok point of its comparability group; the first point of
    a group establishes it."""
    tol = float(traj.get("tolerance", DEFAULT_TOLERANCE))
    best: Dict[str, float] = {}
    results: List[Dict[str, Any]] = []
    for point in traj["series"]:
        if not point.get("ok"):
            results.append({"label": point["label"], "group": None,
                            "value": None, "pass": True,
                            "note": point.get("note",
                                              "recorded failure")})
            continue
        group = point["group"]
        value = point_value(point)
        prev = best.get(group)
        if prev is None:
            results.append({"label": point["label"], "group": group,
                            "value": value, "best_prev": None,
                            "floor": None, "pass": True,
                            "note": "group baseline"})
        else:
            floor = prev * (1.0 - tol)
            ok = value >= floor
            results.append({
                "label": point["label"], "group": group, "value": value,
                "best_prev": prev, "floor": round(floor, 6), "pass": ok,
                "note": "" if ok else
                f"regression: {value:.4f} < {floor:.4f} "
                f"(best {prev:.4f} - {100 * tol:.0f}%)"})
        best[group] = max(best.get(group, 0.0), value)
    return results, all(r["pass"] for r in results)
