"""Observability subsystem: round-trace spans, in-jit defense telemetry,
and the structured run heartbeat.

Three layers, built to be cheap enough to leave on:

- `obs.spans`      host-side span tracer emitting Chrome-trace/Perfetto
                   `trace.json` plus matching `jax.profiler` annotations;
                   per-span p50/p95/max aggregates land in metrics.jsonl
                   (`Spans/*`) and the bench JSON.
- `obs.telemetry`  defense telemetry computed INSIDE the jitted round fn
                   (vote-margin histogram, lr flip fraction, update-norm
                   percentiles, honest-vs-corrupt cosine) — device-resident
                   scalars that ride the async MetricsDrain, gated by
                   `--telemetry off|basic|full`. `off` leaves the traced
                   program untouched: training is bit-identical.
- `obs.heartbeat`  an atomically-rewritten `status.json` (phase, round,
                   last span, compile-in-flight flag, PID, HBM live/peak
                   watermarks) that `scripts/tpu_watch.sh` and the
                   session stall detector consume instead of parsing
                   stderr growth.
- `obs.attribution` device-time attribution from `jax.profiler` traces:
                   the `--profile_rounds` sampled capture window, the
                   shared Chrome-trace parser (compute vs collective vs
                   gap, per program family and per `jax.named_scope`),
                   and the `device.memory_stats()` watermarks — rows in
                   metrics.jsonl (`Device/*`, `Memory/*`), fields in the
                   bench JSON, and the input of `obs.report`.
- `obs.report`     the run-report generator (`python -m ...obs.report
                   <run_dir>`): report.md/report.json with the host-vs-
                   device span table, collective share per family and
                   memory watermarks, PASS/FAIL-gated against the pinned
                   `obs_baseline.json` budgets.

The fleet plane (ISSUE 15) — cross-run, service-level observability:

- `obs.events`     the structured event ledger: every lifecycle
                   transition (supervisor retries, recovery-ladder
                   rungs, adaptation moves, chaos injections, checkpoint
                   save/restore, AOT bank hit/miss, queue cells) as one
                   typed, seq-numbered record in `<run_dir>/events.jsonl`
                   — crash-exact (torn-tail truncation + exactly-once
                   episodic emission + replay dedupe).
- `obs.export`     stdlib Prometheus exporter: atomically-rewritten
                   textfile + optional HTTP `/metrics`
                   (`--metrics_textfile` / `--metrics_port`).
- `obs.console`    the fleet console (`python -m ...obs.console
                   <log_root> [--watch|--html]`): the live multi-run
                   table from heartbeats + ledgers.
- `obs.trajectory` the cross-run perf trajectory
                   (`scripts/bench_trajectory.py`): bench artifacts
                   folded into the committed `trajectory.json` series,
                   regressions judged against a pinned tolerance.
- `obs.constants`  `NON_TIMING_PREFIXES`, the single-sourced exclusion
                   list every crash-exact metrics byte-compare filters
                   on.

The forensics layer (ISSUE 18) — what happened, why, and what changed:

- `obs.flight`     the always-on incident flight recorder: a bounded
                   per-round ring of span durations, dispatch gaps,
                   drain depth, async buffer fill and HBM watermarks
                   streamed to `<run_dir>/flight.jsonl` with ledger-
                   grade crash-exact semantics, snapshotted atomically
                   to `flight.json` on any incident (health rung,
                   supervisor retry/wedge, chaos action, clean exit).
- `obs.trigger`    budgeted anomaly-triggered profiling: a span-p95
                   z-score over the flight window (or a monitor/
                   supervisor incident) arms `obs.attribution`'s
                   RoundProfiler for N steady rounds, max 2 captures
                   per run (`--trigger_profile on|off`), attaching the
                   device split as `obs/trigger_*` ledger events and
                   exporter gauges.
- `obs.explain`    cross-run regression forensics: diff two run dirs or
                   bench artifacts into a per-span/per-phase delta
                   table (compile vs steady vs drain vs eval vs
                   collective share) with a classified verdict —
                   `scripts/bench_trajectory.py --explain` and the
                   auto-explain on a trajectory gate FAIL.
"""

from defending_against_backdoors_with_robust_learning_rate_tpu.obs.heartbeat import (  # noqa: F401
    Heartbeat, NullHeartbeat, is_stale, read_status)
from defending_against_backdoors_with_robust_learning_rate_tpu.obs.spans import (  # noqa: F401
    SpanTracer)
