"""Run-report generator: one run dir -> ``report.md`` + ``report.json``.

    python -m defending_against_backdoors_with_robust_learning_rate_tpu.obs.report <run_dir>
        [--baseline PATH] [--write-baseline] [--headroom 4.0]
        [--trace_dir DIR] [--out DIR] [--backend cpu|tpu]

A training run leaves its observability in three places: ``Spans/*`` /
``Device/*`` / ``Memory/*`` rows in `metrics.jsonl`, the host-side
`trace.json`, and (under ``--profile_rounds``) a `profile/` dir of
jax.profiler captures. This CLI folds them into one judged artifact:

- a per-span table with host and device time side by side,
- the device compute/collective/gap split and named-scope attribution
  (re-parsed from the profile dir via `obs.attribution` when present),
- collective share per compiled program family,
- HBM live/peak watermarks,
- and a **PASS/FAIL budget table** against the pinned `obs_baseline.json`
  (tolerance-gated; refresh via ``--write-baseline``, mirroring the
  `analysis_baseline.json` workflow of the static-analysis gate).

Exit codes: 0 all budgets pass (or none pinned for this backend),
1 budget violation (or a pinned metric missing from the run — missing
observability is a regression too), 2 usage/IO error. Stdlib-only: runs
on machines without jax (the parse half of `obs.attribution` is
stdlib-only by design).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    attribution)

BASELINE_NAME = "obs_baseline.json"
DEFAULT_TOLERANCE = 1.5

# metrics --write-baseline pins (those present in the run): per-phase
# host latencies that catch a host-sync regression, the device split, and
# the memory watermark. Values are written with `--headroom` slack so CI
# machine jitter doesn't flake the gate.
DEFAULT_PIN_METRICS = (
    "Spans/round/dispatch/p50_ms",
    "Spans/metrics/emit/p50_ms",
    "Spans/eval/val_dispatch/p50_ms",
    "Device/Collective_Frac",
    "Device/Gap_Ms_Per_Round",
    "Memory/HBM_Peak_Bytes",
)

SPAN_STATS = ("count", "total_s", "p50_ms", "p95_ms", "max_ms")


def repo_root() -> str:
    pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg_dir)


# --------------------------------------------------------------------------
# inputs
# --------------------------------------------------------------------------

def read_metrics(jsonl_path: str) -> List[Dict[str, Any]]:
    """Records of the LAST run segment in metrics.jsonl (the deterministic
    run_name means reruns append to one file, separated by `_run/start`
    boundary records)."""
    records: List[Dict[str, Any]] = []
    with open(jsonl_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("tag") == "_run/start":
                records = []
                continue
            records.append(rec)
    return records


def flat_metrics(records: List[Dict[str, Any]]) -> Dict[str, float]:
    """tag -> last-written value (the run-final aggregates for Spans/*;
    the latest boundary for eval scalars)."""
    out: Dict[str, float] = {}
    for rec in records:
        tag, value = rec.get("tag"), rec.get("value")
        if isinstance(tag, str) and isinstance(value, (int, float)):
            out[tag] = float(value)
    return out


def span_table(metrics: Dict[str, float]) -> Dict[str, Dict[str, float]]:
    """{span_name: {stat: value}} from the Spans/<name>/<stat> rows."""
    spans: Dict[str, Dict[str, float]] = {}
    for tag, value in metrics.items():
        if not tag.startswith("Spans/"):
            continue
        name_stat = tag[len("Spans/"):]
        name, _, stat = name_stat.rpartition("/")
        if stat in SPAN_STATS and name:
            spans.setdefault(name, {})[stat] = value
    return spans


# --------------------------------------------------------------------------
# budgets (obs_baseline.json)
# --------------------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, Any]:
    if not os.path.exists(path):
        return {"tolerance": DEFAULT_TOLERANCE, "budgets": {}}
    with open(path) as f:
        return json.load(f)


def check_budgets(baseline: Dict[str, Any], backend: str,
                  metrics: Dict[str, float]) -> List[Dict[str, Any]]:
    """[{metric, value, max, limit, pass, note}] for this backend's pins.
    A pinned metric missing from the run FAILS: silently losing a span or
    the device split is exactly the regression this gate exists for."""
    tol = float(baseline.get("tolerance", DEFAULT_TOLERANCE))
    results: List[Dict[str, Any]] = []
    for metric, pin in sorted(
            baseline.get("budgets", {}).get(backend, {}).items()):
        cap = float(pin["max"])
        limit = cap * tol
        value = metrics.get(metric)
        if value is None:
            results.append({"metric": metric, "value": None, "max": cap,
                            "limit": limit, "pass": False,
                            "note": "metric missing from the run"})
        else:
            results.append({"metric": metric, "value": value, "max": cap,
                            "limit": round(limit, 6),
                            "pass": value <= limit, "note": ""})
    return results


def write_baseline(path: str, backend: str, metrics: Dict[str, float],
                   headroom: float,
                   pins: Tuple[str, ...] = DEFAULT_PIN_METRICS) -> str:
    """Refresh this backend's section with measured*headroom ceilings for
    every default pin the run actually produced (other backends' pins and
    the tolerance are preserved)."""
    baseline = load_baseline(path)
    baseline.setdefault("tolerance", DEFAULT_TOLERANCE)
    section = baseline.setdefault("budgets", {}).setdefault(backend, {})
    for metric in pins:
        if metric in metrics:
            section[metric] = {"max": round(metrics[metric] * headroom, 6)}
    with open(path, "w") as f:
        json.dump(baseline, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


# --------------------------------------------------------------------------
# rendering
# --------------------------------------------------------------------------

def _fmt(v: Optional[float], nd: int = 3) -> str:
    if v is None:
        return "—"
    if isinstance(v, float) and abs(v) >= 1e6:
        return f"{v:.3e}"
    s = f"{v:.{nd}f}"
    # strip trailing zeros only past a decimal point (at nd=0 there is
    # none, and "20" must not become "2")
    if "." in s:
        s = s.rstrip("0").rstrip(".")
    return s or "0"


def render_markdown(doc: Dict[str, Any]) -> str:
    lines: List[str] = []
    add = lines.append
    add(f"# Run report — `{doc['run_dir']}`")
    add("")
    add(f"Backend: **{doc['backend']}** · generated by "
        f"`python -m ...obs.report` · budgets: "
        f"{'PASS' if doc['pass'] else '**FAIL**'}")
    add("")
    tp = doc.get("throughput", {})
    if tp:
        add("## Throughput")
        add("")
        for tag, v in sorted(tp.items()):
            add(f"- `{tag}`: {_fmt(v)}")
        add("")

    add("## Spans — host vs device")
    add("")
    attr = doc.get("attribution") or {}
    per_round = attr.get("per_round") or {}
    add("| span | count | host p50 ms | host p95 ms | host total s "
        "| device ms/round |")
    add("|---|---:|---:|---:|---:|---:|")
    spans = doc.get("spans", {})
    for name in sorted(spans, key=lambda n: -spans[n].get("total_s", 0.0)):
        st = spans[name]
        # device time correlates to the dispatch phase: everything the
        # device executes per round was dispatched inside round/dispatch
        dev = (per_round.get("busy_ms")
               if name == "round/dispatch" else None)
        add(f"| `{name}` | {_fmt(st.get('count'), 0)} "
            f"| {_fmt(st.get('p50_ms'))} | {_fmt(st.get('p95_ms'))} "
            f"| {_fmt(st.get('total_s'))} | {_fmt(dev)} |")
    add("")

    add("## Device attribution")
    add("")
    if not attr:
        add("_No profiler capture found (run with `--profile_rounds N` "
            "to sample a device-trace window)._")
    elif not attr.get("device_present"):
        add(f"_No device track in the capture: "
            f"{attr.get('note', 'XLA:CPU')}_")
    else:
        add(f"- window {_fmt(attr['window_ms'])} ms over "
            f"{attr.get('rounds', '?')} rounds on "
            f"{', '.join(attr.get('devices', []))}")
        add(f"- busy {_fmt(attr['busy_ms'])} ms = compute "
            f"{_fmt(attr['compute_ms'])} + collective "
            f"{_fmt(attr['collective_ms'])} "
            f"({100 * attr['collective_frac']:.1f}%); gap "
            f"{_fmt(attr['gap_ms'])} ms")
        add("")
        add("| named scope | device ms | ms/round |")
        add("|---|---:|---:|")
        rounds = attr.get("rounds") or 0
        for scope, ms in sorted(attr.get("by_scope_ms", {}).items(),
                                key=lambda kv: -kv[1]):
            add(f"| `{scope}` | {_fmt(ms)} "
                f"| {_fmt(ms / rounds if rounds else None)} |")
        add("")
        add("### Collective share per program family")
        add("")
        add("| program | compute ms | collective ms | collective % |")
        add("|---|---:|---:|---:|")
        for mod, v in attr.get("by_program", {}).items():
            add(f"| `{mod}` | {_fmt(v['compute_ms'])} "
                f"| {_fmt(v['collective_ms'])} "
                f"| {100 * v['collective_frac']:.1f} |")
    add("")

    rep = doc.get("reputation", {})
    if rep:
        # defense-provenance section (obs/reputation.py): present only
        # when the run emitted Reputation/* rows — an off run's report
        # is byte-identical to the pre-plane format
        add("## Defense provenance")
        add("")
        add(f"- clients tracked: {_fmt(rep.get('Reputation/Clients_Tracked'), 0)}")
        add(f"- suspects past streak threshold: "
            f"{_fmt(rep.get('Reputation/Suspect_Count'), 0)}")
        add(f"- agreement (mean / min over sampled): "
            f"{_fmt(rep.get('Reputation/Mean_Agree'))} / "
            f"{_fmt(rep.get('Reputation/Min_Agree'))}")
        if "Reputation/Top_Suspect_Score" in rep:
            add(f"- top suspicion score: "
                f"{_fmt(rep['Reputation/Top_Suspect_Score'])}")
        if "Reputation/Suspicion_AUC" in rep:
            add(f"- suspicion ranking AUC vs known corrupt ids: "
                f"{_fmt(rep['Reputation/Suspicion_AUC'])}")
        tops = sorted((t, v) for t, v in rep.items()
                      if t.startswith("Reputation/Top_Suspects/"))
        if tops:
            add("")
            add("| rank | client id |")
            add("|---:|---:|")
            for t, v in tops:
                add(f"| {t.rsplit('/', 1)[1]} | {int(v)} |")
        add("")

    add("## Memory")
    add("")
    mem = doc.get("memory", {})
    if mem:
        for tag, v in sorted(mem.items()):
            add(f"- `{tag}`: {int(v):,} bytes")
    else:
        add("_No HBM watermarks recorded (device.memory_stats() is "
            "unavailable on this backend)._ ")
    add("")

    add("## Budgets")
    add("")
    results = doc.get("budget_results", [])
    if not results:
        add(f"_No budgets pinned for backend `{doc['backend']}` in "
            f"{BASELINE_NAME} (run `--write-baseline` on a good run)._ ")
    else:
        add("| metric | value | pinned max | limit (×tol) | verdict |")
        add("|---|---:|---:|---:|---|")
        for r in results:
            verdict = "PASS" if r["pass"] else "**FAIL**"
            note = f" ({r['note']})" if r.get("note") else ""
            add(f"| `{r['metric']}` | {_fmt(r['value'])} "
                f"| {_fmt(r['max'])} | {_fmt(r['limit'])} "
                f"| {verdict}{note} |")
    add("")
    if doc.get("explain"):
        # cross-run forensics (obs/explain.py, --explain_baseline): the
        # per-phase delta table against the named baseline run/artifact
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            explain as explain_mod)
        add(explain_mod.render_markdown_section(doc["explain"]))
    return "\n".join(lines)


# --------------------------------------------------------------------------
# driver
# --------------------------------------------------------------------------

def generate(run_dir: str, trace_dir: Optional[str] = None,
             baseline_path: Optional[str] = None,
             backend: str = "",
             explain_baseline: str = "") -> Dict[str, Any]:
    """Build the report document for one run dir (no files written).
    ``explain_baseline`` names a reference run dir or bench artifact to
    diff this run against (obs/explain.py forensics section)."""
    jsonl = os.path.join(run_dir, "metrics.jsonl")
    if not os.path.exists(jsonl):
        raise FileNotFoundError(f"no metrics.jsonl under {run_dir!r} — "
                                f"is this a run directory?")
    metrics = flat_metrics(read_metrics(jsonl))
    spans = span_table(metrics)

    trace_dir = trace_dir or os.path.join(run_dir, "profile")
    attr = (attribution.attribute(trace_dir)
            if os.path.isdir(trace_dir) else None)
    # Device/* rows may already be in metrics.jsonl (the driver parses its
    # own window); the offline re-parse wins when both exist — it is the
    # fresher view of the same trace, and the always-available mode
    if attr and attr.get("device_present"):
        metrics.update(attribution.scalar_rows(attr))

    if not backend:
        backend = (attr.get("backend") if attr else "") or \
            ("tpu" if attr and attr.get("device_present") else "cpu")

    doc: Dict[str, Any] = {
        "run_dir": os.path.abspath(run_dir),
        "backend": backend,
        "generated_at": time.time(),
        "throughput": {t: v for t, v in metrics.items()
                       if t.startswith("Throughput/")},
        "spans": spans,
        "attribution": attr,
        "memory": {t: v for t, v in metrics.items()
                   if t.startswith("Memory/")},
        # defense-provenance rows (obs/reputation.py) — empty (and the
        # report section absent) when the run had --reputation off
        "reputation": {t: v for t, v in metrics.items()
                       if t.startswith("Reputation/")},
        "metrics": metrics,
    }
    bl = load_baseline(baseline_path
                       or os.path.join(repo_root(), BASELINE_NAME))
    doc["budget_results"] = check_budgets(bl, backend, metrics)
    doc["pass"] = all(r["pass"] for r in doc["budget_results"])
    if explain_baseline:
        # local import: obs/explain.py imports this module's readers
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            explain as explain_mod)
        doc["explain"] = explain_mod.explain_paths(explain_baseline,
                                                   run_dir)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs.report",
        description="Render report.md/report.json for one run dir and "
                    "judge it against obs_baseline.json")
    ap.add_argument("run_dir", help="run directory (holds metrics.jsonl)")
    ap.add_argument("--trace_dir", default="",
                    help="profiler capture dir to attribute "
                         "(default <run_dir>/profile)")
    ap.add_argument("--baseline", default="",
                    help=f"budget file (default <repo>/{BASELINE_NAME})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="refresh this backend's pins from the measured "
                         "values instead of judging against them")
    ap.add_argument("--headroom", type=float, default=4.0,
                    help="--write-baseline slack factor over the "
                         "measured values")
    ap.add_argument("--backend", default="",
                    help="override the judged backend section "
                         "(default: inferred from the capture, else cpu)")
    ap.add_argument("--out", default="",
                    help="output dir for report.md/report.json "
                         "(default: the run dir)")
    ap.add_argument("--explain_baseline", default="",
                    help="reference run dir or bench artifact to diff "
                         "this run against (obs/explain.py: adds the "
                         "Regression forensics section)")
    args = ap.parse_args(argv)

    baseline_path = args.baseline or os.path.join(repo_root(),
                                                  BASELINE_NAME)
    try:
        doc = generate(args.run_dir, trace_dir=args.trace_dir or None,
                       baseline_path=baseline_path,
                       backend=args.backend,
                       explain_baseline=args.explain_baseline)
    except (OSError, ValueError) as e:
        print(f"[report] ERROR: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        path = write_baseline(baseline_path, doc["backend"],
                              doc["metrics"], args.headroom)
        print(f"[report] baseline written: {path}", file=sys.stderr)
        doc["budget_results"] = check_budgets(
            load_baseline(baseline_path), doc["backend"], doc["metrics"])
        doc["pass"] = all(r["pass"] for r in doc["budget_results"])

    out_dir = args.out or args.run_dir
    os.makedirs(out_dir, exist_ok=True)
    md_path = os.path.join(out_dir, "report.md")
    json_path = os.path.join(out_dir, "report.json")
    with open(md_path, "w") as f:
        f.write(render_markdown(doc))
    slim = {k: v for k, v in doc.items() if k != "metrics"}
    with open(json_path, "w") as f:
        json.dump(slim, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"[report] {md_path}")
    print(f"[report] {json_path}")
    failed = [r for r in doc["budget_results"] if not r["pass"]]
    for r in failed:
        print(f"[report] BUDGET FAIL: {r['metric']} = "
              f"{r['value'] if r['value'] is not None else 'missing'} "
              f"(limit {r['limit']})", file=sys.stderr)
    if doc["budget_results"]:
        print(f"[report] budgets: "
              f"{len(doc['budget_results']) - len(failed)}"
              f"/{len(doc['budget_results'])} pass", file=sys.stderr)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
