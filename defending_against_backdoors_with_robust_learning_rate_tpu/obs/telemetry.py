"""In-jit defense telemetry — cheap scalars computed INSIDE the round fn.

The RLR defense (PAPER.md) is a per-coordinate sign vote, yet the driver
only logs outcome scalars: you can see *that* poison accuracy fell, never
*why*. This module computes the mechanism's state each round, on device,
as part of the compiled round program:

- ``tel_upd_norm_p50/p95/max``  percentiles of the m per-agent update L2
  norms (attack payloads routinely separate by magnitude first);
- ``tel_flip_frac``             fraction of coordinates the RLR vote
  flipped to -server_lr (the defense's actual bite, per round);
- ``tel_margin_mean``           mean sign-vote margin |sum sign(u)|/m;
- ``tel_margin_hist``           [N_MARGIN_BUCKETS] fraction of coordinates
  per bucketized vote margin in [0, m] (a margin distribution collapsing
  toward 0 = the electorate is splitting — the adaptive-attack signature,
  arXiv:2303.03320);
- ``tel_cos_honest/corrupt``    mean cosine of honest (resp. corrupt)
  agent updates to the aggregate — the separability the defense relies on.

Ladder (``--telemetry``): ``off`` adds NOTHING to the traced program —
training is bit-identical to a build without this module; ``basic`` = the
norm percentiles + flip fraction; ``full`` adds the margin histogram and
cosine split. All outputs are device scalars that ride the existing
``MetricsDrain`` (no host syncs on the round loop's critical path) and
surface as ``Defense/*`` rows in metrics.jsonl.

Masked rounds (faults/): masked-out agents are zeroed before the stats,
so the margins/cosines describe the actual electorate; their norms read
as 0 in the percentile scan. Corrupt-vs-honest split needs the sampled
slots' corrupt flags: the device-resident path derives them in-jit, the
host-sampled per-round path takes them as an argument (see
``fl.rounds.host_takes_flags``); the host-sampled *chained* path has no
flag channel, so there the cosine split degrades to all-honest.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.fl.diagnostics import (
    per_agent_norms)

LEVELS = ("off", "basic", "full")
N_MARGIN_BUCKETS = 8
PREFIX = "tel_"
_EPS = 1e-12

# metrics.jsonl tag per telemetry key; tel_margin_hist expands to one
# Defense/Vote_Margin_Hist/<i> row per bucket (emit_scalars)
TAGS = {
    "tel_upd_norm_p50": "Defense/Update_Norm_P50",
    "tel_upd_norm_p95": "Defense/Update_Norm_P95",
    "tel_upd_norm_max": "Defense/Update_Norm_Max",
    "tel_flip_frac": "Defense/LR_Flip_Fraction",
    "tel_margin_mean": "Defense/Vote_Margin_Mean",
    "tel_margin_hist": "Defense/Vote_Margin_Hist",
    "tel_cos_honest": "Defense/Cosine_Honest_To_Agg",
    "tel_cos_corrupt": "Defense/Cosine_Corrupt_To_Agg",
    # per-staleness-bin split (fl/buffered.py, --agg_mode buffered +
    # --telemetry full on the vmap paths): one row per staleness bin
    "tel_stale_flip": "Defense/Stale_Flip_Fraction",
    "tel_stale_cos": "Defense/Stale_Cosine_To_Agg",
}


def check_level(level: str) -> str:
    if level not in LEVELS:
        raise ValueError(f"telemetry must be one of {LEVELS}, got {level!r}")
    return level


def telemetry_keys(cfg):
    """The static key set cfg's round program emits — the chained scans and
    shard_map out_specs need it ahead of tracing."""
    if cfg.telemetry == "off":
        return ()
    keys = ["tel_upd_norm_p50", "tel_upd_norm_p95", "tel_upd_norm_max"]
    if cfg.robustLR_threshold > 0:
        keys.append("tel_flip_frac")
    if cfg.telemetry == "full":
        keys += ["tel_margin_mean", "tel_margin_hist",
                 "tel_cos_honest", "tel_cos_corrupt"]
    return tuple(keys)


# --- pure pieces (shared by the vmap and shard_map paths) ----------------

def _norm_percentiles(norms):
    """Nearest-rank p50/p95/max of the [m] per-agent norms."""
    m = norms.shape[0]
    srt = jnp.sort(norms)
    return {"tel_upd_norm_p50": srt[(m - 1) // 2],
            "tel_upd_norm_p95": srt[min(m - 1, round(0.95 * (m - 1)))],
            "tel_upd_norm_max": srt[m - 1]}


def _flip_fraction(lr_tree):
    """Fraction of coordinates whose robust lr went negative."""
    neg, total = 0.0, 0
    for leaf in jax.tree_util.tree_leaves(lr_tree):
        neg = neg + jnp.sum((leaf < 0).astype(jnp.float32))
        total += leaf.size
    return neg / total


def _bucketize_margins(s, m: int, weights=None):
    """[B] coordinate counts of the vote margins s (values in [0, m]),
    plus their sum (for the mean): bucket i covers margins in
    [i*(m+1)/B, (i+1)*(m+1)/B). THE single source of the bucketing
    formula for every layout; `weights` ([len(s)] f32, optional) scales
    each coordinate's contribution — the bucketed aggregation path
    passes its real-coordinate mask so explicit padding (margin 0) never
    pollutes bucket 0 or the sum."""
    flat = s.reshape(-1)
    idx = jnp.clip((flat.astype(jnp.int32) * N_MARGIN_BUCKETS) // (m + 1),
                   0, N_MARGIN_BUCKETS - 1)
    counts = jnp.bincount(idx, weights=weights,
                          length=N_MARGIN_BUCKETS).astype(jnp.float32)
    flat = flat.astype(jnp.float32)
    msum = jnp.sum(flat if weights is None else flat * weights)
    return counts, msum


def _cosine_accumulators(updates_leaves, agg_leaves, mb: int):
    """([mb] dot(u_k, agg), [mb] ||u_k||^2) accumulated leaf-by-leaf —
    the shared cosine-split arithmetic of the sharded leaf and bucketed
    paths (their parity depends on accumulating in the same order)."""
    dots = jnp.zeros((mb,), jnp.float32)
    usq = jnp.zeros((mb,), jnp.float32)
    for u, a in zip(updates_leaves, agg_leaves, strict=True):
        uf = u.reshape(mb, -1).astype(jnp.float32)
        af = a.reshape(-1).astype(jnp.float32)
        dots = dots + uf @ af
        usq = usq + jnp.sum(uf * uf, axis=1)
    return dots, usq


def _finish_margins(counts, margin_sum, total_coords: int, m: int):
    return {"tel_margin_hist": counts / total_coords,
            "tel_margin_mean": margin_sum / (total_coords * m)}


def _finish_cosine(dots, usq, asq, corrupt, valid):
    """Mean cosine-to-aggregate over the honest and corrupt slots of the
    `valid` electorate (zero when a group is empty — NaN would poison the
    JSONL stream)."""
    cos = dots * jax.lax.rsqrt(usq * asq + _EPS)
    out = {}
    for key, sel in (("tel_cos_honest", valid & ~corrupt),
                     ("tel_cos_corrupt", valid & corrupt)):
        n = jnp.sum(sel.astype(jnp.float32))
        out[key] = jnp.where(n > 0,
                             jnp.sum(jnp.where(sel, cos, 0.0))
                             / jnp.maximum(n, 1.0), 0.0)
    return out


def _agg_sqnorm(agg):
    return sum(jnp.sum(jnp.square(a.astype(jnp.float32)))
               for a in jax.tree_util.tree_leaves(agg))


def _total_coords(updates) -> int:
    leaves = jax.tree_util.tree_leaves(updates)
    m = leaves[0].shape[0]
    return sum(u.size // m for u in leaves)


# --- single-device (vmap) path -------------------------------------------

def compute(cfg, updates, lr, agg, mask=None, corrupt_flags=None,
            sign_sums=None, vote_range=None):
    """Telemetry dict for the vmap round path. `updates` leaves are
    [m, ...]; `lr` is the robust-lr tree or None (RLR disabled); `agg` the
    aggregate tree; `mask` the [m] participation mask or None;
    `corrupt_flags` the [m] corrupt-slot flags or None (no split known).
    `sign_sums` (optional): an already-accumulated sign-sum tree whose
    margins the vote actually thresholds — the buffered-async path
    (fl/buffered.py) hands over its buffer accumulators so the margin
    histogram describes the BUFFERED electorate, not just this tick's;
    `vote_range` then widens the bucketization range to that
    electorate's maximum (fl/buffered.vote_range — default: m)."""
    with jax.named_scope("telemetry"):
        m = jax.tree_util.tree_leaves(updates)[0].shape[0]
        vr = vote_range or m
        if mask is not None:
            from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
                masking)
            updates = masking.zero_masked(updates, mask)
        out = _norm_percentiles(per_agent_norms(updates))
        if lr is not None:
            out["tel_flip_frac"] = _flip_fraction(lr)
        if cfg.telemetry != "full":
            return out
        counts = jnp.zeros((N_MARGIN_BUCKETS,), jnp.float32)
        margin_sum = jnp.float32(0.0)
        if sign_sums is not None:
            for s_leaf in jax.tree_util.tree_leaves(sign_sums):
                c, ms = _bucketize_margins(jnp.abs(s_leaf), vr)
                counts, margin_sum = counts + c, margin_sum + ms
        else:
            for u in jax.tree_util.tree_leaves(updates):
                uf = u.reshape(m, -1).astype(jnp.float32)
                s = jnp.abs(jnp.sum(jnp.sign(uf), axis=0))
                c, ms = _bucketize_margins(s, vr)
                counts, margin_sum = counts + c, margin_sum + ms
        dots, usq = _cosine_accumulators(
            jax.tree_util.tree_leaves(updates),
            jax.tree_util.tree_leaves(agg), m)
        out.update(_finish_margins(counts, margin_sum,
                                   _total_coords(updates), vr))
        corrupt = (jnp.zeros((m,), bool) if corrupt_flags is None
                   else corrupt_flags)
        valid = jnp.ones((m,), bool) if mask is None else mask
        out.update(_finish_cosine(dots, usq, _agg_sqnorm(agg),
                                  corrupt, valid))
        return out


# --- sharded (shard_map) path --------------------------------------------

def compute_sharded(cfg, updates_local, lr, agg, axis_name,
                    mask_local=None, mask_full=None, corrupt_full=None,
                    sign_sums=None, vote_range=None):
    """Telemetry dict inside the shard_mapped round body. `updates_local`
    leaves are this device's [m/d, ...] agent block; `lr`/`agg` are
    replicated trees. Collective cost: three tiny all_gathers under
    ``full`` (norms + the two cosine accumulators) and ZERO extra psums
    when the caller hands over `sign_sums` — the RLR vote's per-leaf psum
    results (raw or absolute; the margins take |s| either way). The
    pre-PR-5 version issued its own textually-identical psums and relied
    on XLA CSE, which the jaxpr contract checker measured never happens
    across channel-id'd all-reduces (the same finding the vote/aggregate
    sharing fixed in PR 4). Without `sign_sums` (RLR off) the psums are
    issued here and budgeted accordingly. `vote_range` widens the
    margin bucketization for the buffered electorate (see `compute`)."""
    with jax.named_scope("telemetry"):
        m = cfg.agents_per_round
        vr = vote_range or m
        if mask_local is not None:
            from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
                masking)
            updates_local = masking.zero_masked(updates_local, mask_local)
        norms = jax.lax.all_gather(per_agent_norms(updates_local),
                                   axis_name, axis=0, tiled=True)
        out = _norm_percentiles(norms)
        if lr is not None:
            out["tel_flip_frac"] = _flip_fraction(lr)  # replicated, no comm
        if cfg.telemetry != "full":
            return out
        mb = jax.tree_util.tree_leaves(updates_local)[0].shape[0]
        counts = jnp.zeros((N_MARGIN_BUCKETS,), jnp.float32)
        margin_sum = jnp.float32(0.0)
        sign_leaves = (None if sign_sums is None
                       else jax.tree_util.tree_leaves(sign_sums))
        for i, u in enumerate(jax.tree_util.tree_leaves(updates_local)):
            if sign_leaves is not None:
                # the vote's own psum result, re-read — no new collective
                s = jnp.abs(sign_leaves[i].reshape(-1))
            else:
                uf = u.reshape(mb, -1).astype(jnp.float32)
                s = jnp.abs(jax.lax.psum(jnp.sum(jnp.sign(uf), axis=0),
                                         axis_name))
            c, ms = _bucketize_margins(s, m)
            counts, margin_sum = counts + c, margin_sum + ms
        dots_l, usq_l = _cosine_accumulators(
            jax.tree_util.tree_leaves(updates_local),
            jax.tree_util.tree_leaves(agg), mb)
        out.update(_finish_margins(counts, margin_sum,
                                   _total_coords(updates_local), m))
        dots = jax.lax.all_gather(dots_l, axis_name, axis=0, tiled=True)
        usq = jax.lax.all_gather(usq_l, axis_name, axis=0, tiled=True)
        corrupt = (jnp.zeros((m,), bool) if corrupt_full is None
                   else corrupt_full)
        valid = jnp.ones((m,), bool) if mask_full is None else mask_full
        out.update(_finish_cosine(dots, usq, _agg_sqnorm(agg),
                                  corrupt, valid))
        return out


# --- bucketed (reduce-scatter) layout ------------------------------------

def shard_vote_stats(cfg, sign_shard, real_mask, lr_shard, m: int):
    """Per-device vote/flip statistics computed on the SCATTERED sign-sum
    shard of the bucketed aggregation layout (parallel/buckets.py), packed
    into one tiny f32 vector that rides the bucket path's result
    all_gather — summing the gathered rows across devices yields the
    global stats with ZERO extra collectives. Every entry is an
    integer-valued f32 count or an exact partial sum, so the cross-device
    sum is exact for counts. Layout (in order, entries present only when
    their series is emitted — telemetry_keys is the single source):

        [flip_neg]            robustLR on: real coords with lr < 0
        [counts x N_MARGIN_BUCKETS, margin_sum]   full level only

    `real_mask` excludes the layout's explicit padding coordinates
    (margin 0 there would otherwise pollute bucket 0 and the flip count).
    Returns None when nothing is needed (telemetry off, or basic with
    RLR disabled)."""
    stats = []
    if lr_shard is not None:
        stats.append(jnp.sum(jnp.where(real_mask & (lr_shard < 0),
                                       1.0, 0.0))[None])
    if cfg.telemetry == "full":
        counts, margin_sum = _bucketize_margins(
            jnp.abs(sign_shard), m,
            weights=real_mask.astype(jnp.float32))
        stats += [counts, margin_sum[None]]
    if not stats:
        return None
    return jnp.concatenate(stats)


def compute_sharded_bucket(cfg, updates_local, info, axis_name,
                           mask_local=None, mask_full=None,
                           corrupt_full=None):
    """Telemetry dict for the bucketed aggregation path. `info` is
    parallel/rounds._BucketInfo: the globally-summed `shard_vote_stats`
    vector, the real coordinate count, and (full level) the replicated
    post-noise aggregate tree reassembled from the SAME all_gather that
    carried the LR-scaled result. Collective cost: the norm all_gather
    (basic and up) plus the two cosine-accumulator all_gathers (full) —
    exactly the leaf path's budget; the flip fraction and vote-margin
    series that cost the leaf path its per-leaf sign psums (shared with
    the RLR vote) ride the scattered layout for free."""
    with jax.named_scope("telemetry"):
        m = cfg.agents_per_round
        if mask_local is not None:
            from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
                masking)
            updates_local = masking.zero_masked(updates_local, mask_local)
        norms = jax.lax.all_gather(per_agent_norms(updates_local),
                                   axis_name, axis=0, tiled=True)
        out = _norm_percentiles(norms)
        total = info.total_coords
        i = 0
        if cfg.robustLR_threshold > 0:
            out["tel_flip_frac"] = info.stats[0] / total
            i = 1
        if cfg.telemetry != "full":
            return out
        counts = info.stats[i:i + N_MARGIN_BUCKETS]
        margin_sum = info.stats[i + N_MARGIN_BUCKETS]
        out.update(_finish_margins(counts, margin_sum, total, m))
        mb = jax.tree_util.tree_leaves(updates_local)[0].shape[0]
        dots_l, usq_l = _cosine_accumulators(
            jax.tree_util.tree_leaves(updates_local),
            jax.tree_util.tree_leaves(info.agg), mb)
        dots = jax.lax.all_gather(dots_l, axis_name, axis=0, tiled=True)
        usq = jax.lax.all_gather(usq_l, axis_name, axis=0, tiled=True)
        corrupt = (jnp.zeros((m,), bool) if corrupt_full is None
                   else corrupt_full)
        valid = jnp.ones((m,), bool) if mask_full is None else mask_full
        out.update(_finish_cosine(dots, usq, _agg_sqnorm(info.agg),
                                  corrupt, valid))
        return out


# --- host side -----------------------------------------------------------

def tenant_rows(vals, e: int, allowed=None) -> dict:
    """One tenant's slice of [E]-stacked telemetry values (host-fetched,
    the multi-tenant pack fan-out — service/tenancy.py): every tel_*
    leaf indexed at ``e`` on its leading tenant axis. ``allowed``
    (optional iterable of tel_* keys — telemetry_keys of the TENANT's
    own config) filters series the pack computes but this tenant's solo
    twin would not emit (e.g. tel_flip_frac on an undefended tenant in a
    pack that builds the RLR vote), so per-tenant streams stay
    row-compatible with solo runs."""
    out = {}
    keep = None if allowed is None else set(allowed)
    for key in sorted(vals):
        if not key.startswith(PREFIX):
            continue
        if keep is not None and key not in keep:
            continue
        out[key] = vals[key][e]
    return out


def host_summary(vals) -> dict:
    """JSON-able snapshot of the telemetry values in `vals`
    (host-fetched): tel_* scalars as floats, tel_margin_hist as a float
    list. One source for everything downstream of the drain that wants
    the mechanism's state as data rather than metrics rows — the run
    summary's ``defense`` block (train.py, and through it every
    scenario-matrix JSONL cell, scripts/sweep_scenarios.py) and the
    online threshold-adaptation controller (attack/adapt.py)."""
    out = {}
    for key in sorted(vals):
        if not key.startswith(PREFIX):
            continue
        v = vals[key]
        if getattr(v, "ndim", 0) or isinstance(v, (list, tuple)):
            out[key] = [float(x) for x in v]
        else:
            out[key] = float(v)
    return out


def emit_scalars(writer, vals, step: int) -> None:
    """Write every telemetry value in `vals` (host-fetched) as Defense/*
    scalars. Shared by the sync and async metrics paths, so the jsonl
    stream is bit-identical between them."""
    for key in sorted(vals):
        if not key.startswith(PREFIX):
            continue
        tag = TAGS.get(key, f"Defense/{key[len(PREFIX):]}")
        v = vals[key]
        if getattr(v, "ndim", 0) or isinstance(v, (list, tuple)):
            # vector series (margin histogram, per-staleness split):
            # one row per bin, the margin-hist idiom
            for i, x in enumerate(v):
                writer.scalar(f"{tag}/{i}", float(x), step)
        else:
            writer.scalar(tag, float(v), step)
