"""The structured event ledger: one typed, seq-numbered ``events.jsonl``
stream per run.

Every lifecycle transition the service plane performs — supervisor
retry/backoff/degrade, recovery-ladder rungs, RLR-adaptation decisions,
queue cell/pack start-finish-fail-fallback, chaos injections, checkpoint
save/restore/digest-fallback, AOT bank hit/miss — was previously buried
in prints and status.json phases. The ledger makes each one a record::

    {"seq": 12, "event": "health/rung", "severity": "warn",
     "run": "<run_name>", "corr": "a1b2c3d4e5f6", "round": 4,
     "t": 1754280000.123, "rung": "rollback"}

Schema invariants:

- ``seq`` is strictly increasing per ledger file (resumes continue the
  numbering from the file on disk);
- ``corr`` is the run's correlation id — a pure function of the run name
  (``corr_id``), so every segment of one logical service run (adaptation
  re-entries, recovery-ladder re-entries, crash resumes in a NEW process)
  threads the same id, and a fleet console can group multi-segment
  streams without any shared mutable state;
- ``t`` is the only wall-clock field: ``strip_wallclock`` removes it for
  the byte-identity comparisons.

**Crash-exactness.** The metrics stream's splice machinery (truncate to
the journaled offset + deterministic replay) would be WRONG here: a
recovery-ladder rung recorded after the last checkpoint must survive the
resume — truncating it would erase exactly the evidence the ledger
exists to keep, and the rungs are never re-emitted (the ladder's
persisted state says they already happened). The ledger is therefore
append-only with three complementary guarantees:

1. **torn-tail truncation** — a SIGKILL mid-write leaves at most one
   partial line; opening the ledger truncates the file back to the last
   complete, parseable record (the splice analog, applied only to the
   torn tail);
2. **exactly-once episodic events** — retries, rungs, chaos injections
   and adaptation moves are gated by their subsystems' persisted state
   (chaos fire counts, health_state.json, the carried controller), so a
   crash-resumed process never re-emits them;
3. **replay dedupe** — events a crash-exact replay legitimately
   re-performs (``checkpoint/save``, ``health/defense_anomaly``) carry a
   per-event round high-water mark rebuilt from the file at open:
   re-emission for a round at or below the mark is suppressed.

Together these make a ``kill_recover@N`` drill's ledger byte-identical
(modulo ``t``) to its unkilled twin's: both walk the same ladder, both
re-enter through the same crash-exact machinery, and the kill adds no
record (a dying process writes no last word — the SIGKILL family is the
one chaos class deliberately NOT ledgered; the recovery it forces is).
A plain ``kill@N`` resume additionally records the new process's real
actions (``service/recover``, ``checkpoint/restore``, ``aot/*``) — facts
an uninterrupted twin genuinely lacks; ``PER_LIFE_PREFIXES`` names them
for comparisons that want the interruption-invariant stream.

Emission is decoupled from plumbing: ``install``/``emit`` hold one
process-wide active ledger (the service driver installs its run ledger;
everything else — supervisor, chaos, health ladder, checkpoint utils,
AOT bank — calls ``emit`` which no-ops when nothing is installed, so the
one-shot trainer and bare tests pay nothing).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

SEVERITIES = ("info", "warn", "error")
# "info" is the LOW severity: ledger-visible, never a ladder trigger
# (health/monitor.defense_anomaly emits at this level by contract).

# events a crash-exact replay legitimately re-performs: deduped by a
# monotone per-event round high-water mark (rounds only move forward
# past the resume point, so a scalar mark suffices)
REPLAY_DEDUPE_EVENTS = ("checkpoint/save", "health/defense_anomaly",
                        "rep/suspect")

# replay-deduped events whose high-water mark is PER SUBJECT, not per
# event name: rep/suspect announces one client each — two clients
# crossing at the same round are distinct records, while a crash-exact
# replay re-crossing the SAME client at the same round is the duplicate
# the mark exists to suppress (tenant scopes packed cells' id spaces)
REPLAY_DEDUPE_FIELDS = {"rep/suspect": ("tenant", "client")}

# records that document one PROCESS LIFE's real actions rather than the
# run's logical history: an interrupted-and-resumed run has more of them
# than its uninterrupted twin by construction. Comparisons that want the
# interruption-invariant stream filter these (and, because the extra
# records shift the numbering, also drop `seq`).
PER_LIFE_PREFIXES = ("service/recover", "checkpoint/restore", "aot/",
                     "obs/trigger_")

WALLCLOCK_FIELDS = ("t",)

# the SIGKILL chaos family is never ledgered (see module docstring)
_UNLEDGERED_CHAOS = ("kill", "kill_midbuf", "kill_recover")


def _dedupe_key(event: str, fields: Dict[str, Any]) -> str:
    """The replay-dedupe map key: the event name, extended with the
    event's subject fields (REPLAY_DEDUPE_FIELDS) when it announces a
    per-subject fact rather than a per-round one."""
    subs = REPLAY_DEDUPE_FIELDS.get(event)
    if not subs:
        return event
    return event + ":" + ":".join(str(fields.get(f)) for f in subs)


def corr_id(name: str) -> str:
    """The correlation id for a logical run: a pure function of its
    name, so every segment/process of the run derives the same id with
    no shared state (and twin drills stay byte-comparable)."""
    return hashlib.sha256(name.encode()).hexdigest()[:12]


class EventLedger:
    """Append-only ``events.jsonl`` writer with torn-tail recovery,
    resumed seq numbering and replay dedupe (module docstring).

    ``on_emit(record)`` is the heartbeat hook: the service driver wires
    it to ``status.json`` so readers can detect a wedged ledger
    (``ledger_seq`` + ``last_event``) without tailing the file. Like the
    heartbeat, IO failure disables the ledger rather than the run."""

    def __init__(self, path: str, run: str = "", corr: str = "",
                 on_emit: Optional[Callable[[Dict[str, Any]], None]] = None,
                 clock=time.time):
        self.path = path
        self.run = run
        self.corr = corr or corr_id(run)
        self.on_emit = on_emit
        self._clock = clock
        self._f = None
        self.seq = 0
        self._dedupe_hw: Dict[str, int] = {}
        # emit() is called from the driver thread AND the MetricsDrain
        # worker (the reputation plane's rep/suspect events ride the
        # drain-side emit body while checkpoint/save lands driver-side):
        # the seq counter, dedupe marks and file handle serialize here
        self._lock = threading.Lock()
        self.enabled = bool(path)
        if not self.enabled:
            return
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._recover_tail()
            self._f = open(path, "ab")
        except OSError:
            self.enabled = False

    # ------------------------------------------------------------ recovery

    def _recover_tail(self) -> None:
        """Truncate a torn tail back to the last complete, parseable
        line; resume the seq numbering and rebuild the replay-dedupe
        high-water marks from the surviving records."""
        try:
            with open(self.path, "rb") as f:
                data = f.read()
        except FileNotFoundError:
            return   # fresh ledger — nothing to recover
        good_end = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break   # torn tail: a kill landed mid-write
            try:
                rec = json.loads(line)
                self.seq = int(rec["seq"]) + 1
            except (ValueError, KeyError, TypeError):
                break   # corrupt line: everything after it is suspect
            event = rec.get("event")
            rnd = rec.get("round")
            if event in REPLAY_DEDUPE_EVENTS and isinstance(rnd, int):
                key = _dedupe_key(event, rec)
                self._dedupe_hw[key] = max(
                    self._dedupe_hw.get(key, -1), rnd)
            good_end += len(line)
        if good_end < len(data):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)

    # ------------------------------------------------------------ emission

    def emit(self, event: str, severity: str = "info",
             round: Optional[int] = None,  # noqa: A002 — schema field name
             **fields) -> Optional[Dict[str, Any]]:
        """Write one record; returns it (or None when suppressed or the
        ledger is disabled). Field order is fixed (schema head, then
        sorted extras) so identical event sequences produce identical
        bytes modulo the ``t`` stamp."""
        if severity not in SEVERITIES:
            raise ValueError(f"severity must be one of {SEVERITIES}, "
                             f"got {severity!r}")
        with self._lock:
            # checked under the lock: a concurrent write failure (or
            # close) may have disabled the ledger since the caller's view
            if not self.enabled:
                return None
            if event in REPLAY_DEDUPE_EVENTS and round is not None:
                key = _dedupe_key(event, fields)
                if round <= self._dedupe_hw.get(key, -1):
                    return None   # crash-exact replay re-performing the act
                self._dedupe_hw[key] = round
            rec: Dict[str, Any] = {
                "seq": self.seq, "event": event, "severity": severity,
                "run": self.run, "corr": self.corr, "round": round,
                "t": self._clock(),
            }
            for key in sorted(fields):
                rec[key] = fields[key]
            try:
                self._f.write((json.dumps(rec) + "\n").encode())
                self._f.flush()
            except (OSError, ValueError):
                self.enabled = False   # observability never takes down a run
                return None
            self.seq += 1
        # the heartbeat hook runs OUTSIDE the critical section: it does
        # its own IO (status.json) and must not serialize against — or
        # deadlock by re-entering — the emit path
        if self.on_emit is not None:
            self.on_emit(rec)
        return rec

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
            self.enabled = False


# --------------------------------------------------------------------------
# the process-wide active ledger (service-plane plumbing)
# --------------------------------------------------------------------------

_ACTIVE: Optional[EventLedger] = None


def install(ledger: Optional[EventLedger]) -> Optional[EventLedger]:
    """Make ``ledger`` the process-wide emission target; returns the
    previous one so callers can restore it (the queue's serve cells nest
    this way). ``install(None)`` clears."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, ledger
    return prev


def active() -> Optional[EventLedger]:
    return _ACTIVE


def emit(event: str, severity: str = "info",
         round: Optional[int] = None,  # noqa: A002 — schema field name
         **fields) -> Optional[Dict[str, Any]]:
    """Emit through the installed ledger; a no-op when none is installed
    (the one-shot trainer, bare engine tests, non-lead processes)."""
    if _ACTIVE is None:
        return None
    return _ACTIVE.emit(event, severity=severity, round=round, **fields)


def chaos_ledgered(action: str) -> bool:
    """Whether a chaos injection class is recorded in the ledger (the
    SIGKILL family is not — module docstring)."""
    return action not in _UNLEDGERED_CHAOS


# --------------------------------------------------------------------------
# readers (tests, CI drills, the fleet console)
# --------------------------------------------------------------------------

def read_events(path: str) -> List[Dict[str, Any]]:
    """Parse a ledger file; unparseable/torn lines terminate the read
    (they are what a fresh writer would truncate)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    break
    except OSError:
        return []
    return out


def strip_wallclock(records: List[Dict[str, Any]],
                    drop_per_life: bool = False) -> List[Dict[str, Any]]:
    """The comparison view: records minus the wall-clock fields.
    ``drop_per_life`` additionally removes the per-process-life records
    (and then ``seq``, which the removals shift) — the interruption-
    invariant stream a ``kill@N`` drill compares against its
    uninterrupted twin."""
    out = []
    for rec in records:
        if drop_per_life and str(rec.get("event", "")).startswith(
                PER_LIFE_PREFIXES):
            continue
        keep = {k: v for k, v in rec.items()
                if k not in WALLCLOCK_FIELDS
                and not (drop_per_life and k == "seq")}
        out.append(keep)
    return out
