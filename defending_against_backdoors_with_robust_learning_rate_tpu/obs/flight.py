"""Incident flight recorder: a bounded per-round ring of flight data,
streamed crash-exactly and snapshotted atomically on any incident.

The fleet plane can already *detect* that something went wrong (health
ladder rungs, supervisor degradation, the trajectory gate) — but by the
time anyone looks, the rounds AROUND the incident are gone. This module
keeps them:

- ``FlightRecorder`` records one compact record per dispatch unit —
  span durations, the dispatch gap, metrics-drain depth, the async
  buffer fill and HBM watermarks when the boundaries produced them —
  into an in-memory ring (default ``DEFAULT_WINDOW`` rounds) AND an
  append-only ``flight.jsonl`` stream next to ``metrics.jsonl``;
- ``snapshot(reason, round)`` atomically rewrites ``flight.json``
  (tmp + ``os.replace``, the heartbeat idiom) with the ring's contents
  — the service driver calls it on every warn/error ledger record
  (health rungs, supervisor retries/give-ups, chaos injections, eval/
  drain degradation) and on clean exit, so the LAST snapshot is always
  the evidence closest to the last incident.

**Crash-exact semantics**, mirroring ``obs/events.EventLedger``:

- torn-tail truncation: a SIGKILL mid-write leaves at most one partial
  line; opening the stream truncates back to the last complete record;
- resumed ``seq`` numbering and a round high-water mark: a crash-exact
  resume (or an in-process recovery re-entry) that replays rounds at or
  below the mark appends nothing — the ring still folds the replayed
  record in, so a post-resume snapshot shows fresh data;
- the correlation id (``obs/events.corr_id``) threads every segment of
  one logical run, exactly like the event ledger.

Together these make a ``kill_recover@N`` drill's flight stream
byte-identical to its unkilled twin's under ``strip_timing`` — the
non-timing projection (``seq``/``round``/``corr``/``slot``/unit size)
is deterministic; durations, gaps, drain depth and memory are honest
wall-clock/machine facts and are named in ``TIMING_FIELDS`` /
``VOLATILE_FIELDS`` for the comparisons that must exclude them.

Like every obs component: IO failure disables the recorder, never the
run. Stdlib-only — the console and offline forensics import this on
machines without jax.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

DEFAULT_WINDOW = 64
STREAM_NAME = "flight.jsonl"
SNAPSHOT_NAME = "flight.json"

# wall-clock / duration facts: differ between byte-identical twins
TIMING_FIELDS = ("gap_ms", "spans", "t")
# machine-local / pipeline-state facts: deterministic within one
# process life but not across a kill-resume (a resumed drain starts
# empty, a fresh allocator has fresh watermarks)
VOLATILE_FIELDS = ("drain_depth", "buffer_fill", "hbm_live_bytes",
                   "hbm_peak_bytes")


class FlightRecorder:
    """Per-round flight data: ring buffer + crash-exact stream +
    atomic incident snapshots (module docstring).

    The hot-path cost per round is a few dict updates and one buffered
    line write — ``observe_span`` is wired into the span tracer's
    completion hook and must stay allocation-light."""

    def __init__(self, path: str, run: str = "", corr: str = "",
                 slot: str = "", window: int = DEFAULT_WINDOW,
                 clock=time.time):
        self.path = path
        self.snapshot_path = os.path.join(
            os.path.dirname(path) or ".", SNAPSHOT_NAME)
        self.run = run
        self.corr = corr
        self.slot = slot
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=window)
        self._spans: Dict[str, float] = {}
        self._notes: Dict[str, Any] = {}
        self.seq = 0
        self.hw = -1          # highest round already streamed (dedupe)
        self._t_begin: Optional[float] = None
        self._t_last_end: Optional[float] = None
        self._f = None
        self.enabled = bool(path)
        if not self.enabled:
            return
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._recover_tail()
            self._f = open(path, "ab")
        except OSError:
            self.enabled = False

    # ------------------------------------------------------------ recovery

    def _recover_tail(self) -> None:
        """Truncate a torn tail back to the last complete, parseable
        line; resume seq numbering, rebuild the round high-water mark
        and reload the ring's tail from the surviving records (so a
        snapshot right after a resume still has a window)."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            data = f.read()
        good_end = 0
        for line in data.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break   # torn tail: a kill landed mid-write
            try:
                rec = json.loads(line)
                self.seq = int(rec["seq"]) + 1
            except (ValueError, KeyError, TypeError):
                break   # corrupt line: everything after it is suspect
            rnd = rec.get("round")
            if isinstance(rnd, int):
                self.hw = max(self.hw, rnd)
            self._ring.append(rec)
            good_end += len(line)
        if good_end < len(data):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)

    # ----------------------------------------------------------- recording

    def observe_span(self, name: str, dur_s: float) -> None:
        """Span-completion hook (chained onto the tracer's ``on_end``):
        accumulate this round's per-span milliseconds. Thread-safe —
        the metrics drain completes spans on its own thread."""
        if not self.enabled:
            return
        with self._lock:
            self._spans[name] = round(
                self._spans.get(name, 0.0) + dur_s * 1e3, 3)

    def note(self, **facts) -> None:
        """Stash boundary-sourced volatile facts (async buffer fill,
        HBM watermarks) for the next record — the values were already
        materialized on the host by the boundary's own machinery, so
        recording them costs no extra device sync."""
        if not self.enabled:
            return
        with self._lock:
            for key, value in facts.items():
                if value is not None:
                    self._notes[key] = value

    def begin_unit(self) -> None:
        """Mark the start of a dispatch unit (for the dispatch-gap
        clock)."""
        if self.enabled:
            with self._lock:
                self._t_begin = time.perf_counter()

    def end_unit(self, rnd: int, unit_rounds: int = 1,
                 drain_depth: Optional[int] = None
                 ) -> Optional[Dict[str, Any]]:
        """Close the round's record: fold it into the ring and append
        it to the stream — unless ``rnd`` is at or below the high-water
        mark (a crash-exact replay / recovery re-dispatch), where the
        ring is refreshed but nothing is written, so interrupted and
        uninterrupted twins leave byte-identical streams."""
        if not self.enabled:
            return None
        now = time.perf_counter()
        # one critical section end to end: the drain thread's
        # observe_span must never interleave with the seq/hw/stream
        # mutation (the torn-tail bug class this recorder exists to
        # catch must not live in the recorder itself)
        with self._lock:
            spans, self._spans = self._spans, {}
            notes, self._notes = self._notes, {}
            gap_ms = (round((self._t_begin - self._t_last_end) * 1e3, 3)
                      if self._t_begin is not None
                      and self._t_last_end is not None else None)
            self._t_last_end = now
            replay = rnd <= self.hw
            # fixed field order: the non-timing head first, then the
            # timing/volatile tail, then the wall stamp — the
            # strip_timing projection of identical round sequences is
            # byte-identical
            rec: Dict[str, Any] = {
                "seq": self.seq, "v": 1, "round": rnd, "corr": self.corr,
                "slot": self.slot, "rounds": unit_rounds,
                "gap_ms": gap_ms, "spans": spans,
                "drain_depth": drain_depth,
                "buffer_fill": notes.get("buffer_fill"),
                "hbm_live_bytes": notes.get("hbm_live_bytes"),
                "hbm_peak_bytes": notes.get("hbm_peak_bytes"),
                "t": self._clock(),
            }
            if replay:
                # refresh the ring's view of the replayed round (the
                # fresh record carries this life's real timings) without
                # touching the stream — and without consuming a seq
                rec["seq"] = next(
                    (r["seq"] for r in self._ring
                     if r.get("round") == rnd),
                    self.seq)
                kept = [r for r in self._ring if r.get("round") != rnd]
                self._ring.clear()
                self._ring.extend(kept)
                self._ring.append(rec)
                return None
            if self._f is not None:
                try:
                    self._f.write((json.dumps(rec) + "\n").encode())
                    self._f.flush()
                except (OSError, ValueError):
                    # observability never downs the run
                    self.enabled = False
                    return None
            self.seq += 1
            self.hw = rnd
            self._ring.append(rec)
        return rec

    # ----------------------------------------------------------- snapshots

    def window(self) -> List[Dict[str, Any]]:
        """The ring's current contents, oldest first (a copy)."""
        with self._lock:
            return list(self._ring)

    def snapshot(self, reason: str, rnd: Optional[int] = None,
                 **extra) -> Optional[str]:
        """Atomically rewrite ``flight.json`` with the ring (latest
        incident wins). Works after ``close()`` — the ring outlives the
        stream handle, so the driver can snapshot a recovery re-entry
        after the engine was torn down. Never raises."""
        if not self.path:
            return None
        with self._lock:
            win = list(self._ring)
            current = dict(self._spans)
        doc: Dict[str, Any] = {
            "v": 1, "run": self.run, "corr": self.corr,
            "slot": self.slot, "reason": reason, "round": rnd,
            "window_rounds": len(win), "t": self._clock(),
        }
        for key in sorted(extra):
            doc[key] = extra[key]
        if current:
            doc["current_spans"] = current
        doc["window"] = win
        tmp = f"{self.snapshot_path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f)
                f.write("\n")
            os.replace(tmp, self.snapshot_path)
        except OSError:
            return None
        return self.snapshot_path

    def close(self) -> None:
        """Close the stream handle; the ring (and ``snapshot``) stay
        usable — the driver snapshots the recovery re-entry AFTER the
        engine teardown closed the stream."""
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None


# --------------------------------------------------------------------------
# readers (tests, CI drills, offline forensics)
# --------------------------------------------------------------------------

def read_flight(path: str) -> List[Dict[str, Any]]:
    """Parse a flight stream; unparseable/torn lines terminate the read
    (they are what a fresh writer would truncate)."""
    out: List[Dict[str, Any]] = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    break
    except OSError:
        return []
    return out


def strip_timing(records: List[Dict[str, Any]],
                 drop_volatile: bool = True) -> List[Dict[str, Any]]:
    """The byte-comparison view: records minus the wall-clock/duration
    fields (and, by default, the machine-local volatile ones) — what a
    ``kill_recover@N`` drill's stream shares with its unkilled twin."""
    drop = set(TIMING_FIELDS) | (set(VOLATILE_FIELDS)
                                 if drop_volatile else set())
    return [{k: v for k, v in rec.items() if k not in drop}
            for rec in records]


def read_snapshot(path: str) -> Optional[Dict[str, Any]]:
    """The last incident snapshot, or None when absent/unreadable."""
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
