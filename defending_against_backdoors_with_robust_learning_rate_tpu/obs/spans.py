"""Zero-dependency host-side span tracer.

The driver's time goes into phases nobody could attribute without
hand-running `scripts/profile_round.py`: host gather, dispatch, eval,
drain waits, checkpoint writes. A `SpanTracer` wraps each phase in a
`with tracer.span("round/dispatch"):` block and produces

- a Chrome-trace / Perfetto `trace.json` (the `traceEvents` "X" complete-
  event schema — open it at https://ui.perfetto.dev or chrome://tracing),
- per-span aggregates (count, total, p50/p95/max milliseconds) for
  metrics.jsonl (`Spans/<name>/p50_ms`, ...) and the bench JSON,
- matching `jax.profiler.TraceAnnotation` annotations, so when a device
  trace is being captured (`--profile_dir`) the host spans line up with
  the XLA timeline and device time can be attributed to the same names.

Thread-safe: spans may open/close on the metrics-drain thread (the
`metrics/emit` span) concurrently with the round loop's spans; each
thread gets its own trace `tid`, and nesting depth is tracked per thread.
A disabled tracer's `span()` is a no-op context manager (one attribute
check, no locks), so the tracer can be threaded unconditionally.
"""

from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

# growth bound: a multi-day run must not accumulate events without limit.
# Past the cap, events are dropped (counted) but aggregates keep updating —
# percentile summaries stay honest while the trace covers the run's head.
MAX_EVENTS = 200_000
# per-name duration reservoir for the percentile aggregates; past the cap
# new durations still update count/total/max but stop entering the sample
MAX_DURATIONS_PER_NAME = 50_000


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an ascending-sorted non-empty list."""
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


class SpanTracer:
    def __init__(self, enabled: bool = True, clock=time.perf_counter,
                 annotate: bool = True, on_end=None):
        """`clock` is injectable for exactness tests; `annotate` wires the
        matching `jax.profiler.TraceAnnotation` (skipped when jax is
        unavailable — the tracer itself is zero-dep); `on_end(name, dur_s)`
        is an optional completion hook (the heartbeat's last-span field)."""
        self.enabled = enabled
        self._clock = clock
        self._on_end = on_end
        self._lock = threading.Lock()
        self._events: List[Dict[str, Any]] = []
        self._dropped = 0
        self._durations: Dict[str, List[float]] = {}
        self._totals: Dict[str, List[float]] = {}  # name -> [count, total, max]
        self._local = threading.local()
        self._t0 = clock()
        self._annotation = None
        if annotate:
            try:
                import jax.profiler
                self._annotation = jax.profiler.TraceAnnotation
            except Exception:
                self._annotation = None

    # --- recording -------------------------------------------------------
    @contextmanager
    def span(self, name: str, **args):
        if not self.enabled:
            yield
            return
        depth = getattr(self._local, "depth", 0)
        self._local.depth = depth + 1
        annotation = self._annotation(name) if self._annotation else None
        if annotation is not None:
            annotation.__enter__()
        start = self._clock()
        try:
            yield
        finally:
            dur = self._clock() - start
            if annotation is not None:
                annotation.__exit__(None, None, None)
            self._local.depth = depth
            self._record(name, start, dur, depth, args)
            if self._on_end is not None:
                try:
                    self._on_end(name, dur)
                except Exception:
                    pass  # observability must never take down the run

    def chain_on_end(self, hook) -> None:
        """Add a second completion hook after the existing one (the
        flight recorder chains onto the heartbeat's last-span hook;
        each hook is isolated — one failing never starves the other)."""
        with self._lock:
            prev = self._on_end

            def chained(name: str, dur_s: float) -> None:
                if prev is not None:
                    try:
                        prev(name, dur_s)
                    except Exception:
                        pass
                hook(name, dur_s)

            self._on_end = chained

    def _record(self, name: str, start: float, dur: float, depth: int,
                args: Dict[str, Any]) -> None:
        with self._lock:
            agg = self._totals.setdefault(name, [0, 0.0, 0.0])
            agg[0] += 1
            agg[1] += dur
            agg[2] = max(agg[2], dur)
            sample = self._durations.setdefault(name, [])
            if len(sample) < MAX_DURATIONS_PER_NAME:
                sample.append(dur)
            if len(self._events) >= MAX_EVENTS:
                self._dropped += 1
                return
            ev = {"name": name, "ph": "X", "cat": "host",
                  "ts": round((start - self._t0) * 1e6, 3),
                  "dur": round(dur * 1e6, 3),
                  "pid": os.getpid(),
                  "tid": threading.get_ident() & 0x7FFFFFFF,
                  "args": {"depth": depth, **args}}
            self._events.append(ev)

    # --- reporting -------------------------------------------------------
    def aggregates(self) -> Dict[str, Dict[str, float]]:
        """{name: {count, total_s, p50_ms, p95_ms, max_ms}} per span type."""
        with self._lock:
            out = {}
            for name, (count, total, mx) in sorted(self._totals.items()):
                sample = sorted(self._durations.get(name, ()))
                out[name] = {
                    "count": count,
                    "total_s": round(total, 4),
                    "p50_ms": round(_percentile(sample, 0.50) * 1e3, 3)
                    if sample else 0.0,
                    "p95_ms": round(_percentile(sample, 0.95) * 1e3, 3)
                    if sample else 0.0,
                    "max_ms": round(mx * 1e3, 3),
                }
            return out

    def span_names(self) -> List[str]:
        with self._lock:
            return sorted(self._totals)

    def write_trace(self, path: str) -> Optional[str]:
        """Write the Chrome-trace JSON (atomic: tmp + rename). Returns the
        path, or None when disabled / nothing recorded."""
        if not self.enabled:
            return None
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        if not events:
            return None
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"tracer": "rlr_fl.obs.spans",
                             "dropped_events": dropped}}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path

    def scalar_rows(self):
        """Flat (tag, value) rows for metrics.jsonl: Spans/<name>/<stat>."""
        rows = []
        for name, agg in self.aggregates().items():
            for stat in ("count", "total_s", "p50_ms", "p95_ms", "max_ms"):
                rows.append((f"Spans/{name}/{stat}", float(agg[stat])))
        return rows
