"""Prometheus exporter: textfile collector + optional HTTP scrape
endpoint, stdlib-only.

The service plane already computes its operational truth (heartbeat
phases, supervisor counters, ladder census, drained eval scalars, HBM
watermarks); this module publishes it in the one format every metrics
stack ingests::

    # HELP rlr_rounds_per_sec_ema EMA of observed rounds/sec
    # TYPE rlr_rounds_per_sec_ema gauge
    rlr_rounds_per_sec_ema{run="clip_val:0.0-..."} 1.234

Two transports, independently armed:

- **textfile** (``--metrics_textfile PATH``): the file is atomically
  rewritten (tmp + ``os.replace``, the heartbeat idiom) at every update,
  ready for node_exporter's textfile collector — zero open ports, works
  on an air-gapped TPU host;
- **HTTP** (``--metrics_port N``): a daemon-thread ``http.server``
  serving ``GET /metrics`` (port 0 binds an ephemeral port — the test
  hook; ``.port`` reports the bound one).

Provenance rides a ``<ns>_build_info`` gauge (value 1, labels carry the
run name / backend / jax version), the Prometheus convention for
runtime identity. Like every obs component, IO failure disables the
exporter rather than the run.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

DEFAULT_NAMESPACE = "rlr"
EMA_ALPHA = 0.3


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labelstr(labels: Optional[Dict[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class MetricsExporter:
    """A small gauge/counter registry with Prometheus text rendering.

    ``set`` registers/updates one series; ``observe_rounds`` derives the
    rounds/sec EMA from successive absolute round counts (negative
    deltas — a recovery rollback — are skipped rather than folded into
    the rate). ``flush`` rewrites the textfile; the HTTP endpoint
    renders on demand and needs no flush."""

    def __init__(self, port: Optional[int] = None, textfile: str = "",
                 info: Optional[Dict[str, str]] = None,
                 base_labels: Optional[Dict[str, str]] = None,
                 namespace: str = DEFAULT_NAMESPACE, clock=time.time):
        self.namespace = namespace
        self.textfile = textfile
        self.base_labels = dict(base_labels or {})
        self._clock = clock
        self._lock = threading.Lock()
        # name -> (help, type, {labelstr: value})
        self._series: Dict[str, Tuple[str, str, Dict[str, float]]] = {}
        self._ema = None
        self._last_obs: Optional[Tuple[float, float]] = None
        self.enabled = True
        self.set("build_info", 1.0, labels=dict(info or {}),
                 help_text="runtime provenance (value is always 1)")
        self.port: Optional[int] = None
        self._server = None
        self._thread = None
        if port is not None:
            try:
                self._server = ThreadingHTTPServer(
                    ("", port), _make_handler(self))
                self.port = self._server.server_address[1]
                self._thread = threading.Thread(
                    target=self._server.serve_forever,
                    name="metrics-exporter", daemon=True)
                self._thread.start()
            except OSError as e:
                print(f"[export] metrics port {port} unavailable "
                      f"({e}); HTTP exporter disabled, textfile "
                      f"(if armed) continues")
                self._server = None

    # ------------------------------------------------------------- registry

    def set(self, name: str, value, labels: Optional[Dict[str, str]] = None,
            mtype: str = "gauge", help_text: str = "") -> None:
        merged = {**self.base_labels, **(labels or {})}
        with self._lock:
            help_str, type_str, values = self._series.get(
                name, (help_text, mtype, {}))
            values[_labelstr(merged)] = float(value)
            # the registered TYPE/HELP are first-wins: a later value
            # update that omits mtype must not flip a counter to gauge
            self._series[name] = (help_str or help_text, type_str, values)

    def observe_rounds(self, rounds_total: float) -> None:
        """Fold an absolute round count into the rounds/sec EMA."""
        now = self._clock()
        # fold under the lock (set() re-acquires it afterwards): the
        # EMA read-modify-write must not interleave with another
        # observer's fold
        with self._lock:
            if self._last_obs is not None:
                last_t, last_r = self._last_obs
                dt, dr = now - last_t, rounds_total - last_r
                if dt > 0 and dr > 0:
                    rate = dr / dt
                    self._ema = (rate if self._ema is None
                                 else EMA_ALPHA * rate
                                 + (1 - EMA_ALPHA) * self._ema)
            self._last_obs = (now, rounds_total)
            ema = self._ema
        if ema is not None:
            self.set("rounds_per_sec_ema", ema,
                     help_text="EMA of observed rounds/sec")
        self.set("rounds_observed_total", rounds_total, mtype="counter",
                 help_text="latest absolute round count observed")

    # ------------------------------------------------------------ rendering

    def render(self) -> str:
        lines = []
        with self._lock:
            for name in sorted(self._series):
                help_str, mtype, values = self._series[name]
                full = f"{self.namespace}_{name}"
                if help_str:
                    lines.append(f"# HELP {full} {help_str}")
                lines.append(f"# TYPE {full} {mtype}")
                for labelstr, value in sorted(values.items()):
                    if value == int(value) and abs(value) < 1e15:
                        rendered = str(int(value))
                    else:
                        rendered = repr(value)
                    lines.append(f"{full}{labelstr} {rendered}")
        return "\n".join(lines) + "\n"

    def flush(self) -> None:
        """Atomically rewrite the textfile (no-op without one)."""
        if not (self.textfile and self.enabled):
            return
        tmp = f"{self.textfile}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(self.textfile) or ".",
                        exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                f.write(self.render())
            os.replace(tmp, self.textfile)
        except OSError:
            # observability never takes down the run
            with self._lock:
                self.enabled = False

    def close(self) -> None:
        self.flush()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


def _make_handler(exporter: MetricsExporter):
    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path not in ("/metrics", "/"):
                self.send_response(404)
                self.end_headers()
                return
            body = exporter.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            pass   # scrapes must not spam the service's stdout

    return Handler


# --------------------------------------------------------------------------
# parsing (tests + the fleet console read scrapes back)
# --------------------------------------------------------------------------

def parse_prometheus_text(text: str) -> Dict[str, Dict[str, float]]:
    """{metric_name: {labelstr: value}} from Prometheus exposition text.
    Raises ValueError on a malformed sample line — the scrape-validity
    check the CI job runs."""
    out: Dict[str, Dict[str, float]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_part, _, value_part = line.rpartition(" ")
        if not name_part:
            raise ValueError(f"malformed sample line: {line!r}")
        value = float(value_part)   # ValueError on garbage
        if "{" in name_part:
            name, _, rest = name_part.partition("{")
            if not rest.endswith("}"):
                raise ValueError(f"malformed labels: {line!r}")
            labelstr = "{" + rest
        else:
            name, labelstr = name_part, ""
        out.setdefault(name, {})[labelstr] = value
    return out


def read_textfile(path: str) -> Dict[str, Dict[str, float]]:
    with open(path, encoding="utf-8") as f:
        return parse_prometheus_text(f.read())


def summary_labels(path: str) -> Dict[str, str]:
    """The build_info label set of a textfile scrape (console helper);
    {} when absent/unreadable."""
    try:
        metrics = read_textfile(path)
    except (OSError, ValueError):
        return {}
    for name, series in metrics.items():
        if name.endswith("_build_info"):
            for labelstr in series:
                pairs = {}
                for part in labelstr.strip("{}").split(","):
                    if "=" in part:
                        k, _, v = part.partition("=")
                        pairs[k] = json.loads(v)   # unquote
                return pairs
    return {}
