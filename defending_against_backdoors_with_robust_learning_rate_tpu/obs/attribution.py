"""Device-time attribution from ``jax.profiler`` traces.

``Spans/*`` rows measure host wall-clock only: a `round/dispatch` span
says how long the host waited, never where the DEVICE spent the round —
compute, collective (all-reduce/all-gather), or idle gap. The op-level
truth has lived in an ad-hoc script (`scripts/trace_top_ops.py`) nobody
runs automatically. This module is the shared parser + capture layer that
turns profiler traces into judged numbers (FedJAX ships per-phase timing
as a core simulator feature, arXiv:2108.02117; Podracer makes device-
utilization accounting the primary scaling signal, arXiv:2104.06272):

- ``attribute(trace_dir)`` parses the gzipped Chrome-trace output of a
  `jax.profiler` capture into a per-program-family and per-named-scope
  split of device **compute vs collective vs gap** time, correlating XLA
  ops back to the ``jax.named_scope`` annotations the round fns plant
  (`sample_gather` / `local_train` / `aggregate_rlr` / `telemetry`).
  A trace with no device track (XLA:CPU runs ops on host threadpool
  lanes) degrades gracefully: ``device_present: false``, host side only.
- ``RoundProfiler`` is the driver's opt-in sampled capture window
  (``--profile_rounds N``): it opens ONE `jax.profiler` trace at the
  first steady dispatch unit (never the compile unit), closes it after N
  rounds, and polls ``device.memory_stats()`` per captured unit for the
  HBM live/peak watermarks.
- ``parse_top_ops`` is the op-level top-sinks report
  `scripts/trace_top_ops.py` now delegates to — one parser, two views.
- ``memory_watermarks()`` wraps ``device.memory_stats()`` (None on
  backends without allocator stats) into the ``hbm_live_bytes`` /
  ``hbm_peak_bytes`` fields the heartbeat and bench JSON carry.

The parse side is stdlib-only (gzip/json/re) so `obs/report.py` can run
on machines without jax; everything touching a backend imports jax
lazily inside the function.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import re
from typing import Any, Dict, List, Optional, Set, Tuple

# HLO op groups counted as collective (interconnect) time; everything
# else on a device op lane is compute. Matches the primitive families the
# jaxpr contracts budget (analysis/contracts.COLLECTIVE_PRIMITIVES).
COLLECTIVE_OP_GROUPS = frozenset({
    "all-reduce", "all-gather", "all-to-all", "collective-permute",
    "reduce-scatter", "collective-broadcast",
})

# the jax.named_scope annotations planted in fl/rounds.py and
# parallel/rounds.py (PR 3) — the correlation targets. Order is the
# report's display order; unmatched ops land in "unscoped".
KNOWN_SCOPES = ("sample_gather", "local_train", "aggregate_rlr",
                "telemetry")
UNSCOPED = "unscoped"

CAPTURE_META = "capture_meta.json"

GROUP_RE = re.compile(r"(\.(\d+|remat\d*|clone))+$")


def group_name(name: str) -> str:
    """fusion.123 -> fusion; convolution.4.remat -> convolution (group HLO
    instances of the same op kind, including remat/clone-suffixed copies)."""
    base = GROUP_RE.sub("", name)
    return base or name


def find_trace_file(trace_dir: str) -> Optional[str]:
    """Newest *.trace.json.gz under the dir (one per host per profiler
    run; multiple files mean multiple capture runs — parse the newest,
    merging across runs would mix programs)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not paths:
        return None
    return max(paths, key=os.path.getmtime)


def load_trace_events(path: str) -> List[Dict[str, Any]]:
    with gzip.open(path, "rt") as f:
        return json.load(f).get("traceEvents", [])


def read_capture_meta(trace_dir: str) -> Dict[str, Any]:
    try:
        with open(os.path.join(trace_dir, CAPTURE_META)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def write_capture_meta(trace_dir: str, meta: Dict[str, Any]) -> None:
    try:
        os.makedirs(trace_dir, exist_ok=True)
        with open(os.path.join(trace_dir, CAPTURE_META), "w") as f:
            json.dump(meta, f, indent=1)
    except OSError:
        pass  # observability must never take down the run


# --------------------------------------------------------------------------
# lane classification (shared by attribute() and parse_top_ops())
# --------------------------------------------------------------------------

def _trace_meta(events) -> Tuple[Dict, Dict]:
    """Chrome-trace metadata: pid -> process name, (pid, tid) -> thread
    name. Device lanes are the /device:TPU:* (or TPU:*) processes, host
    threads are everything else."""
    pnames: Dict[Any, str] = {}
    tnames: Dict[Tuple[Any, Any], str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            pnames[e["pid"]] = e.get("args", {}).get("name", "")
        elif e.get("name") == "thread_name":
            tnames[(e["pid"], e.get("tid"))] = \
                e.get("args", {}).get("name", "")
    return pnames, tnames


def _device_pids(pnames) -> Set:
    return {pid for pid, n in pnames.items()
            if "tpu" in n.lower() or "/device" in n.lower()}


def _op_lanes(dev_pids, tnames) -> Set:
    """A device process exports several stacked lanes (an 'XLA Modules'
    envelope spanning the whole executable above per-op 'XLA Ops' rows,
    and often a 'TensorFlow Ops' framework-attribution lane covering the
    SAME device time); summing across all of them double-counts. Prefer
    the exact 'XLA Ops' lane(s); fall back to the substring heuristic
    only when no lane carries that name."""
    xla_tids = {(p, t) for (p, t), n in tnames.items()
                if p in dev_pids and n.strip().lower() == "xla ops"}
    return xla_tids or {(p, t) for (p, t), n in tnames.items()
                        if p in dev_pids and "op" in n.lower()
                        and "module" not in n.lower()}


def _make_op_lane_filter(dev_pids, op_tids, tnames):
    def in_op_lane(e):
        if (e["pid"], e.get("tid")) in op_tids:
            return True
        # no op-level lane metadata: fall back to excluding known
        # envelope lanes by name
        if not op_tids:
            lane = tnames.get((e["pid"], e.get("tid")), "").lower()
            return "module" not in lane and "step" not in lane
        return False
    return in_op_lane


def scope_of(event: Dict[str, Any],
             known: Tuple[str, ...] = KNOWN_SCOPES) -> str:
    """Named-scope of a device op event. The profiler exports the HLO
    op_name metadata — which carries the jax.named_scope path, e.g.
    ``jit_step/local_train/fusion.1`` — in the event args (`long_name`
    on TPU 'XLA Ops' lanes, `tf_op` on framework lanes); scan every
    "/"-separated component against the planted scope names."""
    args = event.get("args", {}) or {}
    for field in ("long_name", "tf_op", "name"):
        path = args.get(field, "")
        if not path:
            continue
        for part in str(path).split("/"):
            # strip any trailing HLO instance suffix before matching
            if group_name(part) in known:
                return group_name(part)
    return UNSCOPED


# --------------------------------------------------------------------------
# attribution
# --------------------------------------------------------------------------

def attribute(trace_dir: str, rounds: Optional[int] = None,
              events: Optional[List[Dict[str, Any]]] = None
              ) -> Optional[Dict[str, Any]]:
    """Parse a profiler trace dir into the device-time attribution dict.

    Returns None when the dir holds no trace file at all. A trace with
    no device track (XLA:CPU) yields ``{"device_present": False, ...}``
    so callers/report can say "no device lanes" instead of crashing.
    `rounds` (or capture_meta.json's record) normalizes the per-round
    figures; without either, per-round fields are omitted. `events`
    skips the gunzip+json load when the caller already holds the newest
    trace file's events (full-shape XLA:CPU traces run to GBs)."""
    path = find_trace_file(trace_dir)
    if path is None:
        return None
    meta = read_capture_meta(trace_dir)
    if rounds is None:
        rounds = meta.get("rounds")
    if events is None:
        events = load_trace_events(path)
    pnames, tnames = _trace_meta(events)
    dev_pids = _device_pids(pnames)
    out: Dict[str, Any] = {
        "trace_file": path,
        "device_present": bool(dev_pids),
        "devices": sorted(pnames[p] for p in dev_pids),
        "rounds": rounds,
    }
    if meta.get("backend"):
        out["backend"] = meta["backend"]
    if not dev_pids:
        out["note"] = ("no device lanes in this trace (XLA:CPU runs ops "
                       "on host threadpool lanes; host spans in "
                       "trace.json are the attribution source there)")
        return out
    op_tids = _op_lanes(dev_pids, tnames)
    in_op_lane = _make_op_lane_filter(dev_pids, op_tids, tnames)

    busy = compute = collective = 0.0
    t_min, t_max = float("inf"), float("-inf")
    by_scope: Dict[str, float] = {}
    by_program: Dict[str, Dict[str, float]] = {}
    per_group: collections.Counter = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids \
                or not in_op_lane(e):
            continue
        dur = float(e.get("dur", 0.0))  # microseconds
        ts = float(e.get("ts", 0.0))
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
        name = e.get("name", "?")
        grp = group_name(name)
        per_group[grp] += dur
        busy += dur
        is_coll = grp in COLLECTIVE_OP_GROUPS
        if is_coll:
            collective += dur
        else:
            compute += dur
        scope = scope_of(e)
        by_scope[scope] = by_scope.get(scope, 0.0) + dur
        module = (e.get("args", {}) or {}).get("hlo_module", "?")
        prog = by_program.setdefault(
            module, {"compute_us": 0.0, "collective_us": 0.0})
        prog["collective_us" if is_coll else "compute_us"] += dur

    if busy == 0.0:
        out["device_present"] = False
        out["note"] = ("device lanes exist but no duration events "
                       "matched the op-level filter; lanes: "
                       f"{sorted(set(tnames.values()))}")
        return out
    window = t_max - t_min
    gap = max(window - busy, 0.0)
    out.update({
        "window_ms": round(window / 1e3, 3),
        "busy_ms": round(busy / 1e3, 3),
        "compute_ms": round(compute / 1e3, 3),
        "collective_ms": round(collective / 1e3, 3),
        "gap_ms": round(gap / 1e3, 3),
        "collective_frac": round(collective / busy, 4),
        "by_scope_ms": {k: round(v / 1e3, 3)
                        for k, v in sorted(by_scope.items())},
        "by_program": {
            mod: {
                "compute_ms": round(v["compute_us"] / 1e3, 3),
                "collective_ms": round(v["collective_us"] / 1e3, 3),
                "collective_frac": round(
                    v["collective_us"]
                    / max(v["compute_us"] + v["collective_us"], 1e-9), 4),
            } for mod, v in sorted(by_program.items())},
        "top_groups": [
            {"op": name, "ms": round(dur / 1e3, 1),
             "pct": round(100 * dur / busy, 1)}
            for name, dur in per_group.most_common(12)],
    })
    if rounds:
        out["per_round"] = {
            "busy_ms": round(busy / 1e3 / rounds, 3),
            "compute_ms": round(compute / 1e3 / rounds, 3),
            "collective_ms": round(collective / 1e3 / rounds, 3),
            "gap_ms": round(gap / 1e3 / rounds, 3),
        }
    return out


def scalar_rows(attr: Dict[str, Any]) -> List[Tuple[str, float]]:
    """Flat (tag, value) rows for metrics.jsonl: Device/*."""
    if not attr or not attr.get("device_present"):
        return []
    rows: List[Tuple[str, float]] = [
        ("Device/Collective_Frac", float(attr["collective_frac"]))]
    per_round = attr.get("per_round")
    if per_round:
        for key in ("busy_ms", "compute_ms", "collective_ms", "gap_ms"):
            tag = "Device/" + key.split("_")[0].capitalize() \
                + "_Ms_Per_Round"
            rows.append((tag, float(per_round[key])))
        rounds = attr.get("rounds") or 1
        for scope, ms in attr.get("by_scope_ms", {}).items():
            rows.append((f"Device/Scope/{scope}_Ms_Per_Round",
                         round(ms / rounds, 3)))
    return rows


# --------------------------------------------------------------------------
# op-level top-sinks view (scripts/trace_top_ops.py delegates here)
# --------------------------------------------------------------------------

def parse_top_ops(trace_dir: str, top: int, rounds: int,
                  events: Optional[List[Dict[str, Any]]] = None):
    """Print + return the op-level top time sinks of a trace dir — the
    historical `scripts/trace_top_ops.py` report, now a view over the
    shared lane classification above. `events` skips the load as in
    ``attribute`` (must be the newest trace file's events)."""
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True))
    if not paths:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    meta = read_capture_meta(trace_dir)
    if "rounds" in meta:
        rounds = meta["rounds"]
    else:
        print(f"[trace] no capture_meta.json — assuming --rounds={rounds} "
              f"for the ms/round figure")
    chosen = max(paths, key=os.path.getmtime)
    if len(paths) > 1:
        print(f"[trace] {len(paths)} trace files under {trace_dir}; "
              f"parsing the newest: {chosen}")
    if events is None:
        events = load_trace_events(chosen)
    pnames, tnames = _trace_meta(events)
    dev_pids = _device_pids(pnames)
    if not dev_pids:
        print("[trace] NO device lanes in this trace (profiler saw only "
              "host threads — the chip is behind the axon tunnel). "
              f"Processes seen: {sorted(set(pnames.values()))}")
        return None
    op_tids = _op_lanes(dev_pids, tnames)
    in_op_lane = _make_op_lane_filter(dev_pids, op_tids, tnames)

    per_op: collections.Counter = collections.Counter()
    per_group: collections.Counter = collections.Counter()
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids \
                or not in_op_lane(e):
            continue
        dur = float(e.get("dur", 0.0))  # microseconds
        name = e.get("name", "?")
        per_op[name] += dur
        per_group[group_name(name)] += dur
        total += dur
    if total == 0.0:
        print("[trace] device lanes exist but no duration events matched "
              f"the op-level filter; lanes: "
              f"{sorted(set(tnames.values()))}")
        return None
    lanes = (sorted(tnames[t] for t in op_tids)
             or "(fallback: all non-module lanes)")
    print(f"[trace] device processes: "
          f"{sorted(pnames[p] for p in dev_pids)}; op lanes: {lanes}")
    print(f"[trace] total device-op time in window: {total/1e3:.1f} ms "
          f"({rounds} rounds -> {total/1e3/max(rounds,1):.1f} ms/round)")
    print(f"\ntop {top} op groups (device time, % of captured op time):")
    rows = []
    for name, dur in per_group.most_common(top):
        print(f"  {name:<44s} {dur/1e3:8.1f} ms  {100*dur/total:5.1f}%")
        rows.append({"op": name, "ms": round(dur / 1e3, 1),
                     "pct": round(100 * dur / total, 1)})
    print(f"\ntop {top} individual ops:")
    for name, dur in per_op.most_common(top):
        print(f"  {name:<44s} {dur/1e3:8.1f} ms  {100*dur/total:5.1f}%")
    return {"total_ms": round(total / 1e3, 1), "rounds": rounds,
            "top_groups": rows}


# --------------------------------------------------------------------------
# memory watermarks
# --------------------------------------------------------------------------

# metrics.jsonl tag per heartbeat memory field
MEMORY_TAGS = {
    "hbm_live_bytes": "Memory/HBM_Live_Bytes",
    "hbm_peak_bytes": "Memory/HBM_Peak_Bytes",
    "host_peak_rss_bytes": "Memory/Host_Peak_RSS_Bytes",
}


def host_watermarks() -> Dict[str, int]:
    """Peak host RSS of this process (stdlib getrusage; ru_maxrss is KiB
    on Linux, bytes on macOS) — the population-axis memory judge: the
    constant-memory claim (ISSUE 7) pins this flat across a
    10k -> 100k -> 1M client ladder. Kept separate from
    ``memory_watermarks`` (device allocator stats) so backends without
    memory_stats still report host pressure."""
    try:
        import resource
        import sys
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        if sys.platform != "darwin":
            rss *= 1024
        return {"host_peak_rss_bytes": int(rss)}
    except Exception:
        return {}


def memory_rows(mem: Dict[str, int]) -> List[Tuple[str, float]]:
    """Flat (tag, value) rows for metrics.jsonl: Memory/*."""
    return [(MEMORY_TAGS.get(k, f"Memory/{k}"), float(v))
            for k, v in sorted(mem.items())]


def memory_watermarks(device=None) -> Dict[str, int]:
    """HBM live/peak bytes from ``device.memory_stats()``, or {} when the
    backend exposes none (XLA:CPU returns None). Keys match the heartbeat
    fields the session stall detectors read (``hbm_live_bytes`` /
    ``hbm_peak_bytes``)."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        return {}
    if not stats:
        return {}
    out: Dict[str, int] = {}
    if "bytes_in_use" in stats:
        out["hbm_live_bytes"] = int(stats["bytes_in_use"])
    if "peak_bytes_in_use" in stats:
        out["hbm_peak_bytes"] = int(stats["peak_bytes_in_use"])
    return out


# --------------------------------------------------------------------------
# sampled capture window (--profile_rounds)
# --------------------------------------------------------------------------

class RoundProfiler:
    """Driver-side sampled profiler window: capture N steady rounds.

    The window opens at the start of the first dispatch unit AFTER the
    compile unit (``maybe_start`` is a no-op until the caller says warmup
    is done) and closes once >= N rounds have been dispatched — blocking
    on the last unit's params first, so the device events of every
    captured round are actually in the trace. Each captured unit also
    polls the HBM watermarks. ``--profile_rounds 0`` (the default) never
    constructs a window: the run is bit-identical to a build without
    this class."""

    def __init__(self, n_rounds: int, trace_dir: str):
        self.n = int(n_rounds)
        self.dir = trace_dir
        self.active = False
        self.done = self.n <= 0
        self.captured = 0
        self.mem: Dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return self.n > 0

    def maybe_start(self) -> None:
        """Open the capture window (idempotent; call at the start of each
        steady dispatch unit)."""
        if self.done or self.active:
            return
        import jax
        os.makedirs(self.dir, exist_ok=True)
        jax.profiler.start_trace(self.dir)
        self.active = True
        print(f"[profile] capture window open -> {self.dir} "
              f"({self.n} rounds)")

    def after_unit(self, params, rounds_in_unit: int) -> None:
        """Account a dispatched unit; close the window when the budget is
        reached. `params` is the unit's output — blocked on before
        stop_trace so the captured rounds' device work is in the file."""
        if not self.active:
            return
        self.captured += int(rounds_in_unit)
        for key, val in memory_watermarks().items():
            self.mem[key] = max(self.mem.get(key, 0), val)
        if self.captured >= self.n:
            self._stop(params)

    def close(self, params=None) -> None:
        """Teardown for runs that end before the budget is reached.
        Swallows teardown errors: this runs on the driver's exception
        path too, and observability must never mask the real failure."""
        if self.active:
            try:
                self._stop(params)
            except Exception as e:
                print(f"[profile] capture teardown failed: "
                      f"{type(e).__name__}: {e}")
                self.active = False
                self.done = True

    def _stop(self, params) -> None:
        import jax
        if params is not None:
            jax.block_until_ready(params)
        jax.profiler.stop_trace()
        self.active = False
        self.done = True
        write_capture_meta(self.dir, {
            "rounds": self.captured,
            "backend": jax.default_backend(),
            "source": "train --profile_rounds",
        })
        print(f"[profile] captured {self.captured} steady rounds -> "
              f"{self.dir}")

    def result(self) -> Optional[Dict[str, Any]]:
        """Attribution of the captured window (None when nothing was
        captured)."""
        if self.captured == 0:
            return None
        return attribute(self.dir, rounds=self.captured)
