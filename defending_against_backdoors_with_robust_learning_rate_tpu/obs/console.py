"""Fleet console: the live multi-run table from heartbeats + ledgers.

    python -m defending_against_backdoors_with_robust_learning_rate_tpu.obs.console \
        <log_root> [--watch [--interval S]] [--html [--out PATH]]

A fleet is whatever lives under ``<log_root>``: every run directory (a
dir holding ``metrics.jsonl`` and/or ``events.jsonl``) joined to the
``status.json`` heartbeat, ``health_state.json`` and exporter textfile
of its log dir. One row per run::

    RUN            PHASE  ROUND      R/S    VAL  SEQ LAST EVENT        W/E  AGE
    clip_val:0...  done    8/8     1.234  0.969   12 checkpoint/save   1/0  3s

- PHASE/ROUND/AGE come from the heartbeat (AGE is staleness-aware: a
  compile-in-flight run gets the larger budget before it reads STALE —
  obs/heartbeat.is_stale);
- SEQ + LAST EVENT come from the heartbeat's ledger fields when present
  (the wedged-ledger detector: SEQ in status.json behind the ledger file
  means the emitter died mid-run), else from the ledger tail;
- W/E counts warn/error events in the ledger;
- R/S and VAL are the last Throughput/Rounds_Per_Sec and
  Validation/Accuracy rows of metrics.jsonl (tail-read, so a
  multi-gigabyte stream costs one seek).

``--watch`` redraws every ``--interval`` seconds; ``--html`` writes a
standalone table (default ``<log_root>/console.html``). Stdlib-only:
runs on machines without jax. Exit 0 always — the console observes, it
does not judge (the trajectory gate and obs.report do the judging).
"""

from __future__ import annotations

import argparse
import html
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    heartbeat as hb_mod)

TAIL_BYTES = 1 << 16
COLUMNS = ("run", "phase", "round", "rps", "val_acc", "suspects",
           "ledger_seq", "last_event", "incident", "warn_err", "age")
HEADERS = ("RUN", "PHASE", "ROUND", "R/S", "VAL", "SUSPECTS", "SEQ",
           "LAST EVENT", "INCIDENT", "W/E", "AGE")


def _tail_lines(path: str, max_bytes: int = TAIL_BYTES) -> List[str]:
    """The last complete lines of a file, reading at most ``max_bytes``
    from the end (a seek, not a scan — ledgers and metrics streams can
    be large)."""
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            f.seek(max(0, size - max_bytes))
            data = f.read()
    except OSError:
        return []
    lines = data.split(b"\n")
    if size > max_bytes:
        lines = lines[1:]   # first line is almost surely partial
    return [ln.decode("utf-8", "replace") for ln in lines if ln.strip()]


def _tail_records(path: str) -> List[Dict[str, Any]]:
    out = []
    for line in _tail_lines(path):
        try:
            out.append(json.loads(line))
        except ValueError:
            continue
    return out


def _last_metric(records: List[Dict[str, Any]], tag: str
                 ) -> Optional[float]:
    for rec in reversed(records):
        if rec.get("tag") == tag:
            return float(rec["value"])
    return None


def scan_fleet(log_root: str, now: Optional[float] = None
               ) -> List[Dict[str, Any]]:
    """One summary dict per run dir under ``log_root`` (sorted by most
    recent heartbeat/ledger activity, freshest first)."""
    now = time.time() if now is None else now
    root = os.path.abspath(log_root)
    found: List[tuple] = []
    runs_per_log_dir: Dict[str, int] = {}
    for base, dirs, files in os.walk(log_root):
        dirs.sort()
        if "metrics.jsonl" not in files and "events.jsonl" not in files:
            continue
        # a run dir's heartbeat lives at its PARENT log dir — except a
        # root-level ledger (the queue's), whose log dir is itself; a
        # parent outside log_root is never read (it is not this fleet's)
        log_dir = (os.path.dirname(base)
                   if os.path.abspath(base) != root else base)
        found.append((base, files, log_dir))
        runs_per_log_dir[log_dir] = runs_per_log_dir.get(log_dir, 0) + 1
    rows: List[Dict[str, Any]] = []
    for base, files, log_dir in found:
        status = hb_mod.read_status(os.path.join(log_dir, "status.json"))
        if status is not None and runs_per_log_dir[log_dir] > 1:
            # status.json carries no run identity: with several runs in
            # one log dir it describes only the LATEST writer — showing
            # it on every row would attribute a live run's phase (and
            # ledger seq) to long-finished siblings. Each row falls back
            # to its own ledger tail instead.
            status = None
        metrics = (_tail_records(os.path.join(base, "metrics.jsonl"))
                   if "metrics.jsonl" in files else [])
        events = (_tail_records(os.path.join(base, "events.jsonl"))
                  if "events.jsonl" in files else [])
        warn_err = [0, 0]
        for rec in events:
            if rec.get("severity") == "warn":
                warn_err[0] += 1
            elif rec.get("severity") == "error":
                warn_err[1] += 1
        last_event = (status or {}).get("last_event")
        if last_event is None and events:
            last = events[-1]
            last_event = {"event": last.get("event"),
                          "severity": last.get("severity"),
                          "round": last.get("round")}
        # the forensics column (ISSUE 18 satellite): the run's last
        # warn/error record from the ledger tail, plus whether a flight
        # snapshot (obs/flight.py flight.json) sits next to the stream
        last_incident = None
        for rec in reversed(events):
            if rec.get("severity") in ("warn", "error"):
                last_incident = {"event": rec.get("event"),
                                 "round": rec.get("round")}
                break
        flight_snapshot = os.path.exists(
            os.path.join(base, "flight.json"))
        ledger_seq = (status or {}).get("ledger_seq")
        if ledger_seq is None and events:
            ledger_seq = events[-1].get("seq")
        health = None
        try:
            with open(os.path.join(log_dir, "health_state.json"),
                      encoding="utf-8") as f:
                health = json.load(f)
        except (OSError, ValueError):
            pass
        updated = float((status or {}).get("updated_at", 0.0))
        rows.append({
            "run": os.path.basename(base),
            "run_dir": base,
            "log_dir": log_dir,
            "phase": (status or {}).get("phase", "?"),
            "round": (status or {}).get("round"),
            "rounds": (status or {}).get("rounds"),
            "stale": hb_mod.is_stale(status, now=now),
            "age_s": (now - updated) if updated else None,
            "rps": _last_metric(metrics, "Throughput/Rounds_Per_Sec"),
            "val_acc": _last_metric(metrics, "Validation/Accuracy"),
            # defense-provenance column (obs/reputation.py): how many
            # clients this run's suspicion ledger has past the streak
            # threshold — None (rendered "—") when the lane is off
            "suspects": _last_metric(metrics,
                                     "Reputation/Suspect_Count"),
            "ledger_seq": ledger_seq,
            "last_event": last_event,
            "last_incident": last_incident,
            "flight_snapshot": flight_snapshot,
            "warns": warn_err[0],
            "errors": warn_err[1],
            "health_incidents": (health or {}).get("incidents"),
        })
    rows.sort(key=lambda r: (r["age_s"] if r["age_s"] is not None
                             else float("inf"), r["run"]))
    return rows


# --------------------------------------------------------------------------
# rendering
# --------------------------------------------------------------------------

def _fmt_age(row: Dict[str, Any]) -> str:
    age = row.get("age_s")
    if age is None:
        return "—"
    text = (f"{age:.0f}s" if age < 120 else f"{age / 60:.0f}m"
            if age < 7200 else f"{age / 3600:.1f}h")
    return f"{text} STALE" if row.get("stale") else text


def _cells(row: Dict[str, Any]) -> List[str]:
    last = row.get("last_event") or {}
    ev = last.get("event") or "—"
    if last.get("round") is not None:
        ev += f"@{last['round']}"
    rnd = ("—" if row.get("round") is None
           else f"{row['round']}/{row.get('rounds') or '?'}")
    # last warn/error + a "+fl" marker when a flight snapshot is present
    inc = row.get("last_incident") or {}
    incident = inc.get("event") or "—"
    if inc.get("round") is not None:
        incident += f"@{inc['round']}"
    if row.get("flight_snapshot"):
        incident = (f"{incident} +fl" if incident != "—" else "+fl")
    return [
        row["run"],
        str(row.get("phase", "?")),
        rnd,
        "—" if row.get("rps") is None else f"{row['rps']:.3f}",
        "—" if row.get("val_acc") is None else f"{row['val_acc']:.3f}",
        ("—" if row.get("suspects") is None
         else str(int(row["suspects"]))),
        "—" if row.get("ledger_seq") is None else str(row["ledger_seq"]),
        ev,
        incident,
        f"{row.get('warns', 0)}/{row.get('errors', 0)}",
        _fmt_age(row),
    ]


def render_table(rows: List[Dict[str, Any]]) -> str:
    if not rows:
        return "(no runs found)\n"
    table = [list(HEADERS)] + [_cells(r) for r in rows]
    # RUN is left-justified and width-capped; everything else right-just
    widths = [min(44, max(len(t[i]) for t in table))
              for i in range(len(HEADERS))]
    lines = []
    for t in table:
        cells = [t[0][:widths[0]].ljust(widths[0])]
        cells += [t[i][:widths[i]].rjust(widths[i])
                  for i in range(1, len(HEADERS))]
        lines.append("  ".join(cells))
    return "\n".join(lines) + "\n"


def render_html(rows: List[Dict[str, Any]], log_root: str) -> str:
    head = "".join(f"<th>{h}</th>" for h in HEADERS)
    body = []
    for row in rows:
        cls = ("stale" if row.get("stale")
               else "err" if row.get("errors") else "")
        tds = "".join(f"<td>{html.escape(c)}</td>" for c in _cells(row))
        body.append(f'<tr class="{cls}">{tds}</tr>')
    return f"""<!doctype html>
<html><head><meta charset="utf-8">
<meta http-equiv="refresh" content="10">
<title>fleet console — {html.escape(log_root)}</title>
<style>
body {{ font: 13px/1.5 monospace; margin: 1.5em; }}
table {{ border-collapse: collapse; }}
th, td {{ padding: 2px 10px; text-align: right; }}
th:first-child, td:first-child {{ text-align: left; }}
th {{ border-bottom: 1px solid #888; }}
tr.stale td {{ color: #a40; }}
tr.err td {{ color: #c00; }}
</style></head><body>
<h3>fleet console — {html.escape(os.path.abspath(log_root))}</h3>
<p>{len(rows)} run(s) · generated {time.strftime('%Y-%m-%d %H:%M:%S')}</p>
<table><tr>{head}</tr>
{os.linesep.join(body)}
</table></body></html>
"""


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="obs.console",
        description="Live multi-run fleet table from heartbeats + event "
                    "ledgers under one log root")
    ap.add_argument("log_root", help="directory holding run log dirs")
    ap.add_argument("--watch", action="store_true",
                    help="redraw every --interval seconds until ^C")
    ap.add_argument("--interval", type=float, default=5.0)
    ap.add_argument("--html", action="store_true",
                    help="write a standalone HTML table instead of text")
    ap.add_argument("--out", default="",
                    help="HTML output path "
                         "(default <log_root>/console.html)")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.log_root):
        print(f"[console] not a directory: {args.log_root}",
              file=sys.stderr)
        return 2
    if args.html:
        rows = scan_fleet(args.log_root)
        out = args.out or os.path.join(args.log_root, "console.html")
        with open(out, "w", encoding="utf-8") as f:
            f.write(render_html(rows, args.log_root))
        print(f"[console] {out} ({len(rows)} run(s))")
        return 0
    while True:
        table = render_table(scan_fleet(args.log_root))
        if args.watch:
            sys.stdout.write("\x1b[2J\x1b[H")
        sys.stdout.write(table)
        sys.stdout.flush()
        if not args.watch:
            return 0
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
