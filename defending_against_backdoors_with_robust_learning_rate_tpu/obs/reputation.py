"""Defense provenance plane: per-client longitudinal suspicion ledger.

The RLR defense (PAPER.md) is a per-parameter sign VOTE, yet every
Defense/* series is aggregate-level (flip fraction, margin histogram) or
cheats with ground-truth corrupt flags (the cosine split). This module
answers the operator question those series cannot: WHICH clients is the
vote voting against, and are they the same ones round after round?

Two halves:

**In-jit** — every round program additionally emits two per-sampled-
client [m] scalars: ``rep_agree``, the fraction of parameter coordinates
where the client's update sign matches the committed sign vote, and
``rep_norm``, the client's update L2 norm (mask-aware: faulted/padded
slots carry the ``MASKED`` sentinel ``-1.0`` so one lane transports both
value and validity). Two signals because the sign vote is MAGNITUDE-
BLIND by construction: a sign-flipping client loses the vote (low
agreement), but a boosting client scales its update without changing a
single sign — and a coordinated boosted pair WINS contested coordinates,
so its agreement is indistinguishable-to-anticorrelated. The norm lane
is what sees it. Collective cost is ZERO everywhere — the
vmap/megabatch/cohort/host/buffered paths compute both as collective-
free [m] reductions (the tenant pack as [E, m]); the sharded leaf path
compares each device's local agent block against the REPLICATED
sign-sum tree the vote's own psums already produced and lets shard_map's
``P(AGENTS_AXIS)`` out_spec stitch the [m] rows; the bucketed path rides
the sign-sum shard on the payload all_gather the layout already pays (a
shape change on an existing collective, never a new one — and the norm
is local there too: each device's flat block holds its clients' FULL
flattened updates). Pinned by the ``*_rep`` CheckSpecs in
analysis/contracts.py at 1/8/16-way.

**Host** — ``ReputationTracker`` folds the drained [m] rows into
longitudinal per-client state keyed by REAL client ids. Each fold turns
a client's round into one ground-truth-free SUSPICION observation::

    susp = max(1 - agree,  1 - med_norm / norm)     # 0 when norm <= med

where ``med_norm`` is the median update norm of THAT round's sampled
row — a scale-free reference that tracks the natural norm decay of a
converging run, so the norm term reads "how many times louder than the
cohort is this client shouting" (a 5x boost scores 0.8) while the
agreement term reads "how often is it outvoted". The tracker keeps an
agreement EMA (the Mean/Min_Agree rows), a suspicion EMA (the ranking),
and a vote-loss streak (consecutive rounds with ``susp >= 0.5`` — the
client either lost the vote outright or out-shouted the cohort 2x).
Below ``rep_population_cap`` the state is a dense per-client dict; above
it (planet-scale cohort runs) it switches to a count-min sketch over
suspicion mass plus an exact top-k heavy-hitter ledger, so a 10M-client
run's RSS stays O(cohort + k). The state is a tiny JSON-able dict
journaled with each checkpoint (train.py), which is what keeps replayed
``Reputation/*`` rows byte-identical across a crash-exact resume.

The ranking is ground-truth-free by construction. The ONLY consumer of
corrupt flags here is the AUC row (``Reputation/Suspicion_AUC``), which
*evaluates* the ranking against ground truth; the ranking itself never
reads a flag. The tracker is observe-only: quarantine remains the
health ladder's decision (health/monitor.py), with this plane's
measured quantiles documented as the calibration source for the
ladder's defense-anomaly thresholds (``--defense_flip_frac_hi`` /
``--defense_low_margin_hi``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

PREFIX = "rep_"
MODES = ("auto", "on", "off")
# host-side EMA decay for the per-client agreement baseline (boundary
# cadence, deterministic Python-float arithmetic — byte-identical rows
# on every replay, the health/sentinel discipline)
EMA_DECAY = 0.9
# a round with per-round suspicion (max of disagreement and relative
# norm excess — see the module doc) at or above this is a LOSS for the
# client — feeds the streak counter. 0.5 means "outvoted on a majority
# of coordinates" on the agreement side and "2x the cohort's median
# update norm" on the magnitude side
LOSE_THRESHOLD = 0.5
# masked/padded slot sentinel: the [m] lane carries value AND validity
MASKED = -1.0
# count-min sketch geometry (population > rep_population_cap). 4 x 4096
# f64 cells ~= 256 KiB — constant regardless of population
SKETCH_DEPTH = 4
SKETCH_WIDTH = 4096
# fixed affine-mix salts per sketch row (NEVER derived from hash(): the
# sketch must be deterministic across interpreters and resumes)
_SKETCH_SALTS = ((0x9E3779B1, 0x85EBCA77), (0xC2B2AE3D, 0x27D4EB2F),
                 (0x165667B1, 0xD3A2646C), (0xFD7046C5, 0xB55A4F09))
# Top_Suspects rows emitted per boundary (metrics.jsonl width); the full
# ranked ledger (rep_topk wide) goes to the run summary, not the stream
N_SUSPECT_ROWS = 8
# typed ledger event on a streak-threshold crossing; replay-deduped
# (obs/events.REPLAY_DEDUPE_EVENTS names the same literal — events.py
# must not import this module)
SUSPECT_EVENT = "rep/suspect"

TAGS = {
    "clients": "Reputation/Clients_Tracked",
    "mean_agree": "Reputation/Mean_Agree",
    "min_agree": "Reputation/Min_Agree",
    "suspect_count": "Reputation/Suspect_Count",
    "top_score": "Reputation/Top_Suspect_Score",
    "top_suspects": "Reputation/Top_Suspects",
    "auc": "Reputation/Suspicion_AUC",
}


def wants_vote(cfg) -> bool:
    """A committed sign vote exists to agree with (the paper's RLR
    threshold vote, or sign aggregation — ops/aggregate.py)."""
    return cfg.robustLR_threshold > 0 or cfg.aggr == "sign"


def check(cfg) -> None:
    """Loud config validation (the health/monitor.check discipline)."""
    if cfg.reputation not in MODES:
        raise ValueError(
            f"--reputation must be one of {MODES}, got {cfg.reputation!r}")
    if cfg.reputation == "on" and not wants_vote(cfg):
        raise ValueError(
            "--reputation on needs a sign vote to measure agreement "
            "against (set robustLR_threshold > 0 or --aggr sign), or use "
            "--reputation auto to resolve off without one")
    if cfg.rep_topk < 1:
        raise ValueError(f"--rep_topk must be >= 1, got {cfg.rep_topk}")
    if cfg.rep_streak < 1:
        raise ValueError(f"--rep_streak must be >= 1, got {cfg.rep_streak}")


def reputation_on(cfg) -> bool:
    """Is the lane compiled into cfg's round program? ``on`` forces it
    (and gates the fused Pallas server step off, the telemetry
    precedent); ``auto`` resolves on exactly when a sign vote exists and
    the Pallas fused commit is NOT in use (the fused kernel owns the
    vote internals, so there is no sign-sum tree to ride)."""
    if cfg.reputation == "off" or not wants_vote(cfg):
        return False
    if cfg.reputation == "on":
        return True
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        _pallas_applicable)
    # normalize diagnostics: the engine builds a plain/diag program PAIR
    # per run (train.py plain_cfg) and gates pallas on the PLAIN variant,
    # so `auto` must resolve identically for both or snap rounds would
    # carry a lane their off-snap twins lack
    return not _pallas_applicable(cfg.replace(diagnostics=False))


def rep_keys(cfg):
    """The static rep_* key set cfg's round program emits — chained
    scans and shard_map out_specs need it ahead of tracing (the
    telemetry_keys discipline)."""
    return ("rep_agree", "rep_norm") if reputation_on(cfg) else ()


# --- in-jit pieces --------------------------------------------------------

def sign_sums_from(updates):
    """Per-coordinate signed vote sums derived from the (already
    masked/zeroed) stacked updates — the vmap paths' fallback when the
    aggregation call did not expose its own sign-sum tree. Elementwise
    reductions over the leading agent axis: zero collectives."""
    return jax.tree_util.tree_map(
        lambda u: jnp.sum(jnp.sign(u.astype(jnp.float32)), axis=0), updates)


def agree_rows(updates, sign_sums, mask=None):
    """[rows] rep_agree: per-slot fraction of coordinates whose update
    sign matches the committed vote sign (``sign(u) * sign(vote) > 0``;
    a zero on either side is a non-match — ties never count as
    agreement). ``updates`` leaves are [rows, ...]; ``sign_sums`` the
    RAW (signed) per-coordinate vote sums, replicated — the vote's own
    psum results on the sharded leaf path, a local reduction elsewhere.
    Masked slots read the ``MASKED`` sentinel. Pure elementwise jnp:
    zero collectives on every path."""
    with jax.named_scope("reputation"):
        u_leaves = jax.tree_util.tree_leaves(updates)
        s_leaves = jax.tree_util.tree_leaves(sign_sums)
        rows = u_leaves[0].shape[0]
        total = sum(u.size // rows for u in u_leaves)
        match = jnp.zeros((rows,), jnp.float32)
        for u, s in zip(u_leaves, s_leaves, strict=True):
            uf = u.reshape(rows, -1).astype(jnp.float32)
            sf = jnp.sign(s.reshape(-1).astype(jnp.float32))
            hit = (jnp.sign(uf) * sf[None, :]) > 0
            match = match + jnp.sum(hit.astype(jnp.float32), axis=1)
        agree = match / total
        if mask is not None:
            agree = jnp.where(mask, agree, MASKED)
        return agree


def agree_rows_flat(flat_updates, flat_sign, real_mask, total_coords):
    """The bucketed layout's variant: ``flat_updates`` is this device's
    [rows, P] padded flattened agent block, ``flat_sign`` the [P] signed
    vote vector reassembled from the payload all_gather the layout
    already pays, ``real_mask`` the [P] real-coordinate mask (explicit
    padding must never count as agreement or disagreement),
    ``total_coords`` the real coordinate count. Elementwise only."""
    with jax.named_scope("reputation"):
        sf = jnp.sign(flat_sign.astype(jnp.float32))
        hit = ((jnp.sign(flat_updates.astype(jnp.float32)) * sf[None, :]) > 0)
        hit = hit & real_mask[None, :]
        return jnp.sum(hit.astype(jnp.float32), axis=1) / total_coords


def norm_rows(updates, mask=None):
    """[rows] rep_norm: each slot's update L2 norm over every parameter
    coordinate — the magnitude signal the sign vote cannot carry
    (``sign(5u) == sign(u)``: a boosting attacker is invisible to
    agreement but 5x the cohort's norm). ``updates`` is a pytree of
    [rows, ...] leaves OR a single [rows, P] array (the bucketed flat
    block, whose padding coordinates are explicit zeros and so cost
    nothing). Masked slots read the ``MASKED`` sentinel. Pure local
    reductions: zero collectives on every path — on sharded layouts each
    device's block holds its clients' full coordinate set."""
    with jax.named_scope("reputation"):
        leaves = jax.tree_util.tree_leaves(updates)
        rows = leaves[0].shape[0]
        sq = jnp.zeros((rows,), jnp.float32)
        for u in leaves:
            uf = u.reshape(rows, -1).astype(jnp.float32)
            sq = sq + jnp.sum(uf * uf, axis=1)
        norm = jnp.sqrt(sq)
        if mask is not None:
            norm = jnp.where(mask, norm, MASKED)
        return norm


# --- host-side longitudinal tracker ---------------------------------------

def _sketch_cols(cid: int):
    """The client's cell column per sketch row — fixed affine+xorshift
    mixing, deterministic across interpreters (no built-in hash())."""
    cols = []
    for a, b in _SKETCH_SALTS:
        h = (a * (cid + 1) + b) & 0xFFFFFFFF
        h ^= h >> 15
        h = (h * 0x2C1B3C6D) & 0xFFFFFFFF
        h ^= h >> 12
        cols.append(h % SKETCH_WIDTH)
    return cols


def rank_auc(scores, labels):
    """Mann-Whitney AUC of ``scores`` (higher = more suspect) against
    boolean ``labels`` (True = actually corrupt), average ranks on ties.
    None when either class is empty. Pure deterministic Python — the
    row must be byte-identical on replay."""
    pairs = sorted(zip(scores, labels))
    n_pos = sum(1 for _, y in pairs if y)
    n_neg = len(pairs) - n_pos
    if n_pos == 0 or n_neg == 0:
        return None
    rank_sum, i = 0.0, 0
    while i < len(pairs):
        j = i
        while j < len(pairs) and pairs[j][0] == pairs[i][0]:
            j += 1
        avg_rank = (i + 1 + j) / 2.0  # average of ranks i+1..j
        rank_sum += avg_rank * sum(1 for k in range(i, j) if pairs[k][1])
        i = j
    return (rank_sum - n_pos * (n_pos + 1) / 2.0) / (n_pos * n_neg)


class ReputationTracker:
    """Longitudinal per-client suspicion state folded from drained [m]
    rep_agree + rep_norm rows, keyed by REAL client ids.

    Each fold scores every valid slot with the module-doc suspicion
    observation ``max(1 - agree, 1 - med_norm / norm)`` (``med_norm``
    the row's own median — scale-free, so converging-run norm decay
    cancels) and EMA-folds it per client; the agreement EMA rides along
    for the Mean/Min_Agree rows.

    Dense mode (population <= cap): one dict entry per ever-seen client
    — exact EMAs, exact streaks, full-population AUC. Sketch mode
    (population > cap): a count-min sketch accumulates each client's
    suspicion mass and fold count (O(1) memory in the population);
    an exact ledger tracks the ``topk`` current heavy hitters (EMAs +
    streak start at admission — pre-admission history is the sketch's
    estimate, the documented approximation tests bound). AUC rows are
    dense-mode only: ranking 10M clients would need the O(population)
    state the sketch exists to avoid.

    All state is JSON-able (``state_dict``/``load_state``) and rides the
    checkpoint journal, so a crash-exact resume replays byte-identical
    Reputation/* rows. Folds are deterministic: slots in row order,
    ties broken by client id. Observe-only — nothing here feeds the
    participation mask."""

    def __init__(self, population: int, cap: int, topk: int,
                 streak_thr: int, decay: float = EMA_DECAY):
        self.population = int(population)
        self.cap = int(cap)
        self.topk = int(topk)
        self.streak_thr = int(streak_thr)
        # construction-time Python scalar, never a device value
        self.decay = float(decay)  # static: ok(host-sync)
        self.sketch_mode = self.population > self.cap
        self.rounds_folded = 0
        # dense: {cid: [agree_ema, n, streak, susp_ema]}; ledger (sketch
        # mode): same shape, capped at topk entries
        self.clients = {}
        self.mass = ([[0.0] * SKETCH_WIDTH for _ in range(SKETCH_DEPTH)]
                     if self.sketch_mode else None)
        self.count = ([[0.0] * SKETCH_WIDTH for _ in range(SKETCH_DEPTH)]
                      if self.sketch_mode else None)
        self._pending_events = []

    @classmethod
    def for_config(cls, cfg, population: int):
        return cls(population, cfg.rep_population_cap, cfg.rep_topk,
                   cfg.rep_streak)

    # -- folding ----------------------------------------------------------

    def fold(self, round_id: int, ids, agrees, norms=None) -> None:
        """Fold one drained round row: ``ids`` the [m] sampled REAL
        client ids, ``agrees``/``norms`` the matching rep_agree and
        rep_norm values (MASKED sentinel slots — faulted/padded — are
        skipped: an absent client neither wins nor loses the vote).
        ``norms=None`` degrades to agreement-only suspicion (every norm
        deviation reads 0) — the oracle tests' single-signal mode."""
        vals = [(int(cid), float(a),
                 None if norms is None else float(r))
                for cid, a, r in zip(
                    ids, agrees,
                    agrees if norms is None else norms)
                if float(a) >= 0.0]
        # the row's own median norm: the scale-free magnitude reference
        # (sorted() on floats — deterministic, replay-identical)
        med = None
        if norms is not None and vals:
            ns = sorted(r for _, _, r in vals)
            mid = len(ns) // 2
            med = (ns[mid] if len(ns) % 2
                   else 0.5 * (ns[mid - 1] + ns[mid]))
        for cid, a, r in vals:
            dev = 0.0
            if med is not None and r > med:
                dev = 1.0 if med <= 0.0 else 1.0 - med / r
            self._fold_one(cid, a, max(1.0 - a, dev), int(round_id))
        self.rounds_folded += 1

    def _fold_one(self, cid: int, agree: float, susp: float,
                  round_id: int) -> None:
        if self.sketch_mode:
            est = self._sketch_add(cid, susp)
            if cid not in self.clients and not self._admit(cid, est):
                return
        ent = self.clients.get(cid)
        if ent is None:
            ent = [agree, 1, 1 if susp >= LOSE_THRESHOLD else 0, susp]
            self.clients[cid] = ent
        else:
            ent[0] = self.decay * ent[0] + (1.0 - self.decay) * agree
            ent[1] += 1
            ent[2] = ent[2] + 1 if susp >= LOSE_THRESHOLD else 0
            ent[3] = self.decay * ent[3] + (1.0 - self.decay) * susp
        if ent[2] == self.streak_thr:
            # exact crossing (== not >=: one event per streak, the
            # checkpoint/save dedupe idiom handles crash replays)
            self._pending_events.append({
                "client": cid, "streak": ent[2], "round": round_id,
                "score": round(ent[3], 6)})

    def _sketch_add(self, cid: int, susp: float) -> float:
        """Add one suspicion observation; return the count-min estimate
        of the client's MEAN suspicion so far."""
        est = float("inf")
        for row, col in enumerate(_sketch_cols(cid)):
            self.mass[row][col] += susp
            self.count[row][col] += 1.0
            est = min(est, self.mass[row][col]
                      / max(self.count[row][col], 1.0))
        return est

    def _admit(self, cid: int, est: float) -> bool:
        """Heavy-hitter ledger admission: always while below capacity;
        at capacity, only past the current minimum suspicion (evicting
        that member — deterministic tie-break by id)."""
        if len(self.clients) < self.topk:
            return True
        worst_id, worst = None, None
        for k, ent in self.clients.items():
            score = ent[3]
            if worst is None or score < worst or (score == worst
                                                  and k > worst_id):
                worst_id, worst = k, score
        if est <= worst:
            return False
        del self.clients[worst_id]
        return True

    # -- read side --------------------------------------------------------

    def suspicion(self, cid: int) -> float:
        """The client's suspicion score in [0, 1] (the suspicion EMA —
        module doc); sketch estimate for non-ledger clients in sketch
        mode, 0.0 for a never-seen client in dense mode."""
        ent = self.clients.get(cid)
        if ent is not None:
            return ent[3]
        if not self.sketch_mode:
            return 0.0
        est = float("inf")
        for row, col in enumerate(_sketch_cols(cid)):
            c = self.count[row][col]
            est = min(est, (self.mass[row][col] / c) if c else 0.0)
        return est

    def ranked(self):
        """[(cid, score)] best-suspect-first, ties broken by id —
        deterministic for the Top_Suspects rows and the summary."""
        return sorted(((cid, ent[3])
                       for cid, ent in self.clients.items()),
                      key=lambda t: (-t[1], t[0]))

    def suspect_count(self) -> int:
        return sum(1 for ent in self.clients.values()
                   if ent[2] >= self.streak_thr)

    def drain_events(self):
        """Streak-crossing events accumulated since the last drain —
        the caller emits them through obs/events (keeping ledger writes
        on the metrics thread's already-serialized emit path)."""
        out, self._pending_events = self._pending_events, []
        return out

    def boundary_rows(self, corrupt_pred=None):
        """Ordered [(tag, value)] Reputation/* rows for one eval
        boundary. ``corrupt_pred`` (cid -> bool, the GROUND TRUTH) adds
        the AUC row that evaluates the ranking — the ranking itself
        never read it. Dense mode ranks the whole seen population;
        sketch mode ranks the ledger (and skips AUC, see class doc)."""
        rows = [(TAGS["clients"], float(len(self.clients)))]
        if self.clients:
            emas = [ent[0] for ent in self.clients.values()]
            rows.append((TAGS["mean_agree"], sum(emas) / len(emas)))
            rows.append((TAGS["min_agree"], min(emas)))
        rows.append((TAGS["suspect_count"], float(self.suspect_count())))
        ranked = self.ranked()
        if ranked:
            rows.append((TAGS["top_score"], ranked[0][1]))
            for i, (cid, _) in enumerate(ranked[:N_SUSPECT_ROWS]):
                rows.append((f"{TAGS['top_suspects']}/{i}", float(cid)))
        if corrupt_pred is not None and not self.sketch_mode and ranked:
            auc = rank_auc([s for _, s in ranked],
                           [bool(corrupt_pred(c)) for c, _ in ranked])
            if auc is not None:
                rows.append((TAGS["auc"], auc))
        return rows

    def summary(self, corrupt_pred=None) -> dict:
        """JSON-able snapshot for the run summary's ``suspicion`` key
        (and through it every queue/sweep JSONL cell)."""
        ranked = self.ranked()
        out = {
            "clients": len(self.clients),
            "rounds": self.rounds_folded,
            "suspect_count": self.suspect_count(),
            "suspects": [cid for cid, _ in ranked[:self.topk]],
            "scores": [round(s, 6) for _, s in ranked[:self.topk]],
            "mode": "sketch" if self.sketch_mode else "dense",
        }
        if corrupt_pred is not None and not self.sketch_mode and ranked:
            auc = rank_auc([s for _, s in ranked],
                           [bool(corrupt_pred(c)) for c, _ in ranked])
            if auc is not None:
                out["auc"] = round(auc, 6)
        return out

    # -- journal ----------------------------------------------------------

    def state_dict(self) -> dict:
        """JSON-able state for the checkpoint journal (keys stringified:
        JSON objects cannot carry int keys). Sketch arrays ride along —
        256 KiB of f64 cells, constant in the population."""
        out = {"rounds": self.rounds_folded,
               "clients": {str(cid): ent
                           for cid, ent in self.clients.items()}}
        if self.sketch_mode:
            out["mass"] = self.mass
            out["count"] = self.count
        return out

    def load_state(self, state: dict) -> None:
        """Restore from a journal entry (crash-exact resume): replayed
        rounds re-fold the same drained rows on top of this state, so
        the replayed Reputation/* rows are byte-identical."""
        if not state:
            return
        self.rounds_folded = int(state.get("rounds", 0))
        self.clients = {
            int(cid): [float(e[0]), int(e[1]), int(e[2]), float(e[3])]
            for cid, e in state.get("clients", {}).items()}
        if self.sketch_mode and "mass" in state:
            self.mass = [[float(x) for x in row] for row in state["mass"]]
            self.count = [[float(x) for x in row] for row in state["count"]]


def emit_rows(writer, tracker, step: int, corrupt_pred=None) -> None:
    """Write one boundary's Reputation/* rows. Shared by the sync and
    async metrics paths AND the tenant fan-out, so every stream is
    bit-identical between them (the telemetry emit_scalars discipline)."""
    for tag, val in tracker.boundary_rows(corrupt_pred):
        writer.scalar(tag, float(val), step)
