"""Structured run heartbeat: an atomically-rewritten ``status.json``.

The session tooling's liveness heuristics (ADVICE.md) were wedge-prone by
construction: `tpu_session_r5.sh` inferred progress from stderr byte
growth and `tpu_watch.sh` from whether `jax.devices()` answered — both
proxies that confuse "quiet but computing" with "hung". The heartbeat
replaces the guesswork with structure: the driver (and bench.py) rewrite
one small JSON file —

    {"phase": "train", "round": 120, "rounds": 200,
     "last_span": "round/dispatch", "compile_in_flight": false,
     "pid": 4242, "started_at": ..., "updated_at": ...}

— via write-to-tmp + ``os.replace``, so a reader NEVER observes a partial
file. ``compile_in_flight`` is the wedge-safety flag the stall detectors
need most: a watchdog must not kill a process mid-compile (the documented
TPU-tunnel wedge cause), and the heartbeat says exactly when that is.

Writes are rate-limited (default: one per second) except on phase
changes, so per-round updates cost nothing measurable at hundreds of
rounds/sec. Consumption: ``read_status`` + ``is_stale`` here, and the
shell side reads mtime/fields with plain ``python -c`` one-liners
(scripts/tpu_watch.sh, scripts/tpu_session_r5.sh).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

DEFAULT_MIN_INTERVAL_S = 1.0
# a heartbeat older than this is stale — unless a compile is in flight,
# which legitimately produces no updates for minutes (stall detectors must
# use the larger compile budget then; see is_stale)
DEFAULT_STALE_S = 300.0
DEFAULT_COMPILE_STALE_S = 3600.0


class Heartbeat:
    def __init__(self, path: str, enabled: bool = True,
                 min_interval_s: float = DEFAULT_MIN_INTERVAL_S,
                 clock=time.time):
        self.path = path
        self.enabled = enabled and bool(path)
        self._clock = clock
        self._min_interval = min_interval_s
        self._last_write = 0.0
        self._state: Dict[str, Any] = {
            "phase": "starting", "round": 0, "rounds": 0,
            "last_span": "", "compile_in_flight": False,
            "pid": os.getpid(), "started_at": clock(), "updated_at": 0.0,
        }
        if self.enabled:
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            except OSError:
                # same contract as _write: observability must never take
                # down the run (read-only log dir on a borrowed machine)
                self.enabled = False
                return
            self._write()

    def update(self, phase: Optional[str] = None, force: bool = False,
               **fields) -> None:
        """Merge fields and rewrite the file. Rate-limited; a phase change
        or `force` always writes (phase is what the detectors key on)."""
        if not self.enabled:
            return
        changed_phase = phase is not None and phase != self._state["phase"]
        if phase is not None:
            self._state["phase"] = phase
        self._state.update(fields)
        now = self._clock()
        if (force or changed_phase
                or now - self._last_write >= self._min_interval):
            self._write(now)

    def span_hook(self, name: str, dur_s: float) -> None:
        """SpanTracer on_end hook: records the last completed span (rides
        the normal rate limit — span churn must not turn into fsync churn)."""
        self.update(last_span=name)

    def close(self, phase: str = "exited") -> None:
        if self.enabled:
            self.update(phase=phase, force=True)

    def _write(self, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        self._state["updated_at"] = now
        tmp = f"{self.path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                json.dump(self._state, f)
            os.replace(tmp, self.path)
            self._last_write = now
        except OSError:
            # observability must never take down the run (e.g. read-only
            # log dir on a borrowed machine): disable after first failure
            self.enabled = False


class NullHeartbeat:
    """No-op stand-in (non-lead processes of a multi-host job)."""

    def update(self, phase=None, force=False, **fields) -> None:
        pass

    def span_hook(self, name, dur_s) -> None:
        pass

    def close(self, phase="exited") -> None:
        pass


def read_status(path: str) -> Optional[Dict[str, Any]]:
    """Parse status.json; None when absent or (transiently) unreadable."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_stale(status: Optional[Dict[str, Any]], now: Optional[float] = None,
             stale_s: float = DEFAULT_STALE_S,
             compile_stale_s: float = DEFAULT_COMPILE_STALE_S) -> bool:
    """Stall verdict for a status record: no heartbeat within the budget.
    A compile-in-flight record gets the (much larger) compile budget —
    killing mid-compile is the documented tunnel-wedge cause, so the
    detector must be patient exactly then."""
    if status is None:
        return True
    now = time.time() if now is None else now
    budget = (compile_stale_s if status.get("compile_in_flight")
              else stale_s)
    return now - float(status.get("updated_at", 0.0)) > budget
