"""Cross-run regression forensics: diff two runs into a per-span /
per-phase delta table and a classified verdict.

The trajectory gate (``obs/trajectory.py``) says THAT a run regressed —
a bare ratio against the best earlier point. This module says WHERE:
it loads two sides (each a run directory holding ``metrics.jsonl``, or
a bench artifact — a bare ``bench.py`` result object or a session
``BENCH_r*.json`` record), normalizes every span's total host time to
ms per dispatched round, groups spans into phase families::

    compile     bench/probe, bench/data, bench/aot_acquire,
                bench/first_block  (+ the artifact's compile_s scalar)
    steady      round/*, prefetch/*, bench/steady_blocks,
                bench/profile_blocks
    eval        eval/*, metrics/*
    drain       drain/*
    checkpoint  ckpt/*

and classifies the verdict: which family grew the most, whether the
collective share moved, and whether the headline throughput drop
clears the trajectory tolerance. Consumed three ways: the
``scripts/bench_trajectory.py --explain`` CLI, the auto-explain a gate
FAIL prints, and the "Regression forensics" section of
``obs/report.py``'s markdown. Stdlib-only — runs on machines without
jax, like every offline obs tool.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from . import flight as obs_flight
from . import report as obs_report
from . import trajectory

FAMILIES = ("compile", "steady", "eval", "drain", "checkpoint", "other")

_COMPILE_SPANS = ("bench/probe", "bench/data", "bench/aot_acquire",
                  "bench/first_block")
_STEADY_SPANS = ("bench/steady_blocks", "bench/profile_blocks")

# a collective-share move this large reclassifies a steady regression:
# the rounds got slower because the devices talk more, not compute more
COLLECTIVE_SHIFT = 0.05


class MalformedInput(ValueError):
    """Neither a run dir with metrics.jsonl nor a recognizable bench
    artifact (CLI exit code 2, mirroring the trajectory gate)."""


def span_family(name: str) -> str:
    if name in _COMPILE_SPANS:
        return "compile"
    if name in _STEADY_SPANS:
        return "steady"
    if name.startswith(("eval/", "metrics/")):
        return "eval"
    if name.startswith("drain/"):
        return "drain"
    if name.startswith("ckpt/"):
        return "checkpoint"
    if name.startswith(("round/", "prefetch/")):
        return "steady"
    return "other"


# --------------------------------------------------------------------------
# sides
# --------------------------------------------------------------------------

def load_side(path: str) -> Dict[str, Any]:
    """Normalize one comparison side::

        {label, kind, value, units, spans, compile_s,
         collective_frac, incident}

    ``spans`` is the report-shaped ``{name: {count, total_s, ...}}``
    table; ``units`` is the dispatched-round count the totals are
    normalized by (None when the side doesn't record it); ``incident``
    is the run dir's last flight-snapshot reason, when one exists."""
    if os.path.isdir(path):
        jsonl = os.path.join(path, "metrics.jsonl")
        if not os.path.exists(jsonl):
            raise MalformedInput(
                f"{path}: a directory but no metrics.jsonl — "
                f"not a run dir")
        metrics = obs_report.flat_metrics(obs_report.read_metrics(jsonl))
        spans = obs_report.span_table(metrics)
        value = metrics.get("Throughput/Steady_Rounds_Per_Sec",
                            metrics.get("Throughput/Rounds_Per_Sec"))
        units = spans.get("round/dispatch", {}).get("count")
        snap = obs_flight.read_snapshot(
            os.path.join(path, obs_flight.SNAPSHOT_NAME))
        return {
            "label": os.path.basename(os.path.normpath(path)),
            "kind": "run_dir", "value": value, "units": units,
            "spans": spans, "compile_s": None,
            "collective_frac": metrics.get("Device/Collective_Frac"),
            "incident": snap.get("reason") if snap else None,
        }
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError) as e:
        raise MalformedInput(f"{path}: {e}") from e
    if not isinstance(data, dict):
        raise MalformedInput(f"{path}: expected a JSON object")
    label = os.path.splitext(os.path.basename(path))[0]
    if "parsed" in data and isinstance(data.get("parsed"), dict):
        label = f"r{int(data.get('n', 0)):02d}"   # session record
        data = data["parsed"]
    if "metric" not in data and "spans" not in data:
        raise MalformedInput(
            f"{path}: neither a bench result (no 'metric'/'spans') "
            f"nor a session record (no 'parsed')")
    spans = data.get("spans") or {}
    if not isinstance(spans, dict):
        raise MalformedInput(f"{path}: 'spans' is not a table")
    units: Optional[float] = None
    blocks, chain = data.get("blocks"), data.get("chain")
    if isinstance(blocks, (int, float)) and isinstance(chain,
                                                       (int, float)):
        units = float(blocks) * float(chain)
    attr = data.get("attribution") or {}
    return {
        "label": label, "kind": "artifact",
        "value": data.get("value"), "units": units, "spans": spans,
        "compile_s": data.get("compile_s"),
        "collective_frac": attr.get("collective_frac"),
        "incident": None,
    }


# --------------------------------------------------------------------------
# the diff
# --------------------------------------------------------------------------

def _per_unit_ms(side: Dict[str, Any], name: str) -> Optional[float]:
    st = side["spans"].get(name)
    if not st or "total_s" not in st:
        return None
    total_ms = st["total_s"] * 1e3
    units = side.get("units")
    return total_ms / units if units else total_ms


def _pct(base: Optional[float], cand: Optional[float]
         ) -> Optional[float]:
    if base is None or cand is None or base == 0:
        return None
    return round(100.0 * (cand - base) / base, 1)


def diff(base: Dict[str, Any], cand: Dict[str, Any],
         tolerance: float = trajectory.DEFAULT_TOLERANCE
         ) -> Dict[str, Any]:
    """The explain document: per-span and per-family deltas (base vs
    candidate, ms per dispatched round), the headline value delta, the
    collective-share move, and a classified verdict naming the phase
    that regressed. Sides with different unit normalization still
    compare fairly — each side is normalized by its OWN round count."""
    normalized = bool(base.get("units")) and bool(cand.get("units"))
    span_rows: List[Dict[str, Any]] = []
    for name in sorted(set(base["spans"]) | set(cand["spans"])):
        b, c = _per_unit_ms(base, name), _per_unit_ms(cand, name)
        span_rows.append({
            "span": name, "family": span_family(name),
            "base_ms": None if b is None else round(b, 3),
            "cand_ms": None if c is None else round(c, 3),
            "delta_ms": (None if b is None or c is None
                         else round(c - b, 3)),
            "delta_pct": _pct(b, c),
        })
    families: Dict[str, Dict[str, Any]] = {}
    for fam in FAMILIES:
        rows = [r for r in span_rows if r["family"] == fam]
        if not rows:
            continue
        b = sum(r["base_ms"] for r in rows
                if r["base_ms"] is not None)
        c = sum(r["cand_ms"] for r in rows
                if r["cand_ms"] is not None)
        families[fam] = {"base_ms": round(b, 3), "cand_ms": round(c, 3),
                         "delta_ms": round(c - b, 3),
                         "delta_pct": _pct(b, c)}

    value_pct = _pct(base.get("value"), cand.get("value"))
    compile_pct = _pct(base.get("compile_s"), cand.get("compile_s"))
    coll_b, coll_c = (base.get("collective_frac"),
                      cand.get("collective_frac"))
    coll_shift = (round(coll_c - coll_b, 4)
                  if coll_b is not None and coll_c is not None else None)

    # ---- verdict: did it regress, and which phase owns the delta ----
    if value_pct is not None:
        regressed = value_pct < -100.0 * tolerance
    else:
        regressed = any(
            f["delta_pct"] is not None
            and f["delta_pct"] > 100.0 * tolerance
            for f in families.values())
    phase: Optional[str] = None
    phase_note = ""
    grown = [(fam, f["delta_ms"]) for fam, f in families.items()
             if f["delta_ms"] > 0]
    if grown:
        phase, delta = max(grown, key=lambda kv: kv[1])
        f = families[phase]
        unit = "ms/round" if normalized else "ms total"
        phase_note = (f"{phase} grew {f['base_ms']} -> {f['cand_ms']} "
                      f"{unit} ({_fmt_pct(f['delta_pct'])})")
    if compile_pct is not None and compile_pct > 100.0 * tolerance \
            and (phase is None or phase != "compile"):
        # the compile_s scalar sees recompiles the span table may not
        phase = "compile"
        phase_note = (f"compile_s grew {base.get('compile_s')} -> "
                      f"{cand.get('compile_s')} s "
                      f"({_fmt_pct(compile_pct)})")
    if coll_shift is not None and coll_shift > COLLECTIVE_SHIFT:
        phase_note += (f"; collective share rose "
                       f"{coll_b:.2f} -> {coll_c:.2f}" if phase_note
                       else f"collective share rose "
                            f"{coll_b:.2f} -> {coll_c:.2f}")
        if phase in (None, "steady"):
            phase = phase or "steady"

    return {
        "base": {k: base.get(k) for k in
                 ("label", "kind", "value", "units", "compile_s",
                  "collective_frac", "incident")},
        "cand": {k: cand.get(k) for k in
                 ("label", "kind", "value", "units", "compile_s",
                  "collective_frac", "incident")},
        "tolerance": tolerance,
        "normalized": normalized,
        "value_delta_pct": value_pct,
        "compile_delta_pct": compile_pct,
        "collective_shift": coll_shift,
        "spans": span_rows,
        "families": families,
        "verdict": {"regressed": regressed, "phase": phase,
                    "note": phase_note},
    }


def explain_paths(base_path: str, cand_path: str,
                  tolerance: float = trajectory.DEFAULT_TOLERANCE
                  ) -> Dict[str, Any]:
    """load_side both sides and diff them (the CLI entry point)."""
    return diff(load_side(base_path), load_side(cand_path),
                tolerance=tolerance)


# --------------------------------------------------------------------------
# rendering
# --------------------------------------------------------------------------

def _fmt_pct(pct: Optional[float]) -> str:
    return "—" if pct is None else f"{pct:+.1f}%"


def _fmt(v: Optional[float]) -> str:
    return obs_report._fmt(v)


def render_text(doc: Dict[str, Any]) -> List[str]:
    """The CLI / gate-FAIL view: one ``[explain]`` line per fact, the
    verdict first — a FAIL should name its phase before the table."""
    v = doc["verdict"]
    lines = []
    if v["regressed"]:
        head = f"REGRESSED — phase: {v['phase'] or 'unclassified'}"
    else:
        head = "no regression past tolerance"
    lines.append(f"[explain] {doc['base']['label']} -> "
                 f"{doc['cand']['label']}: {head}")
    if v["note"]:
        lines.append(f"[explain]   {v['note']}")
    if doc["value_delta_pct"] is not None:
        lines.append(
            f"[explain]   value {_fmt(doc['base']['value'])} -> "
            f"{_fmt(doc['cand']['value'])} "
            f"({_fmt_pct(doc['value_delta_pct'])}, tolerance "
            f"-{100 * doc['tolerance']:.0f}%)")
    unit = "ms/round" if doc["normalized"] else "ms total"
    for fam, f in doc["families"].items():
        lines.append(f"[explain]   {fam:<10} {f['base_ms']:>10} -> "
                     f"{f['cand_ms']:>10} {unit}  "
                     f"({_fmt_pct(f['delta_pct'])})")
    for side in (doc["base"], doc["cand"]):
        if side.get("incident"):
            lines.append(f"[explain]   {side['label']}: last flight "
                         f"snapshot reason: {side['incident']}")
    return lines


def render_markdown_section(doc: Dict[str, Any]) -> str:
    """The ``## Regression forensics`` block obs/report.py appends when
    invoked with ``--explain_baseline``."""
    v = doc["verdict"]
    lines: List[str] = []
    add = lines.append
    add("## Regression forensics")
    add("")
    add(f"Baseline `{doc['base']['label']}` vs candidate "
        f"`{doc['cand']['label']}` — verdict: "
        + (f"**REGRESSED ({v['phase'] or 'unclassified'})**"
           if v["regressed"] else "PASS"))
    if v["note"]:
        add("")
        add(f"_{v['note']}_")
    add("")
    unit = "ms/round" if doc["normalized"] else "ms total"
    add(f"| phase | base {unit} | cand {unit} | delta |")
    add("|---|---:|---:|---:|")
    for fam, f in doc["families"].items():
        mark = "**" if v["regressed"] and fam == v["phase"] else ""
        add(f"| {mark}{fam}{mark} | {_fmt(f['base_ms'])} "
            f"| {_fmt(f['cand_ms'])} | {_fmt_pct(f['delta_pct'])} |")
    add("")
    add("| span | family | base | cand | delta |")
    add("|---|---|---:|---:|---:|")
    for r in sorted(doc["spans"],
                    key=lambda r: -(r["delta_ms"] or 0)):
        add(f"| `{r['span']}` | {r['family']} | {_fmt(r['base_ms'])} "
            f"| {_fmt(r['cand_ms'])} | {_fmt_pct(r['delta_pct'])} |")
    add("")
    return "\n".join(lines)
