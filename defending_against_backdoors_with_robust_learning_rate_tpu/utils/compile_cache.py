"""Compile persistence + ahead-of-time (AOT) executable banking.

The flagship configs run hundreds of FL rounds per experiment row, yet every
session used to pay the full XLA compile cost again (BENCH_r05.json: 164.3s
on the CPU fallback; ~60-70s per TPU program family). FedJAX
(arXiv:2108.02117) treats cached compilation of the round program as a
first-class requirement for FL-simulation throughput; this module is that
requirement, in two layers:

1. **Persistent XLA cache** (`enable_persistent_cache`): wires JAX's
   `jax_compilation_cache_dir` so every `jit` compilation — including ones
   this module never sees — warm-starts from disk across processes.
2. **Executable bank** (`AotBank`): `lower().compile()` each program family
   the run will use ahead of time and serialize the *executable itself*
   (`jax.experimental.serialize_executable`), keyed by a fingerprint of
   (config, jax version, backend, topology, arg shapes). A warm start
   deserializes the banked executable and skips XLA entirely — no trace,
   no lowering, no compile. This also de-risks the documented
   tunnel-wedge failure mode: `scripts/precompile.py` banks all families
   once, offline, before any watchdog arms, so session scripts never kill
   a first-time compile mid-flight again.

Program families (the manifest vocabulary; see `plan_programs`):

    round / round_diag      device-resident per-round fn (fl/rounds.py)
    chained                 device-resident lax.scan round block
    round_host[_diag]       host-sampled per-round fn
    chained_host            host-sampled chained block
    round_cohort[_diag] /   cohort-sampled population path (ISSUE 7):
    chained_cohort /        in-program seeded cohort over the client
    round_sharded_cohort    bank (data/bank.py + data/cohort.py)
    round_sharded /         shard_map variants (parallel/rounds.py) —
    chained_sharded         adopted at runtime, banked best-effort;
                            `--agg_layout bucket` (ISSUE 8) swaps their
                            aggregation plan to the bucketed
                            reduce-scatter program — same family names,
                            distinct fingerprints (agg_layout is a
                            program field), and the analysis passes plan
                            them per topology through
                            `plan_sharded_programs`
    *_mb                    `--train_layout megabatch` (ISSUE 10) twins
                            of every round/chained family above
                            (`family_suffix`): the local-training
                            compute layout folds the client axis into
                            the batch (fl/client.py), a DIFFERENT traced
                            program with its own name so the AOT
                            manifest, the analysis passes and the driver
                            log all say which layout ran. Eval families
                            never suffix (no client axis).
    eval_val / eval_poison  the two eval-set program instances

Every entry is a pair of files in `<root>/aot/`: `<family>-<fp>.jex`
(pickled serialized executable + arg pytree defs) and a `<family>-<fp>.json`
sidecar (the manifest record: fingerprint inputs, compile seconds, backend).
Per-entry files make concurrent writers safe without locking — the manifest
IS the directory. A changed config, jax version, backend, topology or arg
shape changes the fingerprint, so stale executables are never loaded; they
are simply dead files.

Failure policy: every load path degrades to the plain jit path with a log
line — a corrupt or version-skewed bank can cost a recompile, never a run.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

# config fields that do not change the compiled program (pure IO/driver
# knobs). `snap`/`rounds`/`seed`/`chain` only alter which/how many
# dispatches run; shapes (which DO change programs, e.g. the chained
# block's round_ids length) enter the fingerprint through the
# example-argument avals instead. This set is audited against
# config.FIELD_PROVENANCE by analysis/fingerprint_audit.py: every
# `runtime` field must be here, no `program` field may be — drift in
# either direction fails the static-analysis CI gate.
EXCLUDED_FIELDS = frozenset({
    "data_dir", "log_dir", "checkpoint_dir", "resume", "profile_dir",
    "tensorboard", "rounds", "snap", "seed", "chain", "host_prefetch",
    "compile_cache", "compile_cache_dir", "async_metrics",
    # obs/: spans + heartbeat are host-side IO; `telemetry` is NOT here —
    # it adds outputs to the traced program, so it must key the cache
    "spans", "heartbeat", "status_file",
    # fleet observability (ISSUE 15): ledger + exporter are host-side IO
    "events", "metrics_port", "metrics_textfile",
    # forensics (ISSUE 18): flight recorder + profile trigger are
    # host-side IO around the dispatch loop — neither shapes a program
    "flight", "trigger_profile",
    # fingerprint-drift fixes (ISSUE 4 audit): runtime-only fields that
    # used to split identical programs across cache keys. `platform`
    # (backend is fingerprinted directly), the multihost rendezvous
    # triplet (process/device counts are fingerprinted), `top_frac`
    # (host-side Sign/* set algebra), `rng_impl` (the RESOLVED impl keys
    # via jax_default_prng_impl — the unresolved 'auto' string must not
    # split from 'rbg' on TPU), `mesh` (sharded families are never
    # banked; eval/vmap programs are mesh-independent and should share),
    # `host_sampled` (family names already key the fingerprint).
    "platform", "coordinator", "num_processes", "process_id", "top_frac",
    "rng_impl", "mesh", "host_sampled",
    # sampled profiler window (obs/attribution.py): observation only
    "profile_rounds",
    # continuous-service driver knobs (service/): retry policy, streaming
    # budget, checkpoint retention and chaos injection are all host-side —
    # none shapes a traced program (churn_* fields by contrast DO and are
    # fingerprinted)
    "service_rounds", "service_retries", "service_backoff_s",
    "service_deadline_s", "service_keep_ckpts", "chaos",
    # health lane (ISSUE 14): the incident POLICY and its EMA judgement
    # knobs are host-side (health/monitor.py) and bank verification is
    # open-time IO — none shapes a traced program (`health` and
    # `quarantine` by contrast DO and are fingerprinted)
    "health_policy", "health_z_threshold", "health_spike_factor",
    "bank_verify",
    # population axis (ISSUE 7): `cohort_sampled` selects the cohort
    # program families (names key the fingerprint, like host_sampled);
    # bank storage location / IO shard layout / build parallelism never
    # shape a program (cohort_seed/cohort_size and the partitioner
    # fields by contrast DO shape programs or data and are
    # fingerprinted; the traffic_* fields are traced and stay in)
    "cohort_sampled", "bank_dir", "bank_shard_clients",
    "bank_build_workers",
    # online RLR-threshold adaptation (attack/adapt.py): a host-side
    # service policy — it ACTS by rebuilding programs with a different
    # robustLR_threshold (which is fingerprinted), never by changing a
    # trace itself. The attack/attack_* strategy fields by contrast ARE
    # traced (attack/registry.py update hook + schedule) and stay in the
    # fingerprint.
    "rlr_adapt", "rlr_adapt_every",
    # defense provenance plane (ISSUE 20): the host tracker's
    # representation knobs and the health ladder's promoted anomaly
    # thresholds are never read in a trace (`reputation` by contrast
    # selects whether the rep_* lanes are compiled in and stays in the
    # fingerprint, the `telemetry` rule)
    "rep_population_cap", "rep_topk", "rep_streak",
    "defense_flip_frac_hi", "defense_low_margin_hi",
    # NOT here: `agg_layout` (ISSUE 8). It selects the sharded
    # aggregation program (per-leaf psums vs bucketed reduce-scatter,
    # parallel/rounds.py reads it at trace time), so it must stay in the
    # fingerprint even though the sharded families are never banked —
    # the same rule as `telemetry`: a traced read makes it program
    # provenance, and the audit fails closed on excluding it.
})

# families built from cfg.replace(diagnostics=False) in the driver; their
# fingerprints normalize diagnostics off so a --diagnostics run still hits
# the same banked non-diag executables
_DIAG_FAMILIES = frozenset({"round_diag", "round_host_diag",
                            "round_sharded_diag"})

DEFAULT_CACHE_ROOT = os.path.join("~", ".cache", "rlr_fl")

# above this many stacked-array bytes the driver switches to host-side
# per-round shard gathering (the fedemnist path; train.py re-exports this)
DEVICE_RESIDENT_BYTES = 2 << 30


def cache_root(cfg=None) -> str:
    """Resolve the cache root: --compile_cache_dir, else $RLR_COMPILE_CACHE_DIR,
    else ~/.cache/rlr_fl (stable across runs — that is the point)."""
    root = ""
    if cfg is not None:
        root = getattr(cfg, "compile_cache_dir", "") or ""
    root = root or os.environ.get("RLR_COMPILE_CACHE_DIR", "")
    return os.path.expanduser(root or DEFAULT_CACHE_ROOT)


def _reset_jax_cache_state() -> None:
    """jax's persistent-cache module initializes AT MOST ONCE per process:
    after any compile with the dir unset, a later `jax_compilation_cache_dir`
    update is silently ignored. Reset to pristine so the next compile
    re-initializes against the current config."""
    try:
        from jax._src import compilation_cache as jax_cc
        jax_cc.reset_cache()
    except Exception:
        pass


def enable_persistent_cache(root: Optional[str] = None) -> str:
    """Point JAX's persistent compilation cache at `<root>/xla`.

    Thresholds are zeroed so every program family persists (the default
    1s/min-size gates would skip the small eval programs whose compiles
    still stall a TPU session through the tunnel). Safe to call more than
    once; returns the cache dir."""
    xla_dir = os.path.join(root or cache_root(), "xla")
    os.makedirs(xla_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", xla_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _reset_jax_cache_state()
    return xla_dir


def abstractify(tree):
    """Pytree of arrays -> matching ShapeDtypeStructs (already-abstract
    leaves pass through), for zero-materialization `lower()` calls."""
    return jax.tree_util.tree_map(
        lambda x: x if isinstance(x, jax.ShapeDtypeStruct)
        else jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)), tree)


def _arg_shapes(example_args) -> List[Tuple[str, str]]:
    return [(str(tuple(l.shape)), str(l.dtype))
            for l in jax.tree_util.tree_leaves(abstractify(example_args))]


def resolved_train_layout(cfg) -> str:
    """Single source of the local-training compute layout (ISSUE 10):
    `--train_layout megabatch` degrades to vmap under `--diagnostics`
    (per-client loss curves want the per-client axis; mixing layouts
    between snap and off-snap rounds would silently compare different
    programs — the engine prints the loud hint). The AOT fingerprint
    keys THIS resolved value, so a degraded megabatch config shares the
    vmap run's cache entries instead of splitting them."""
    layout = getattr(cfg, "train_layout", "vmap")
    if layout not in ("vmap", "megabatch"):
        raise ValueError(
            f"train_layout must be 'vmap' or 'megabatch', got {layout!r}")
    if layout == "megabatch" and cfg.diagnostics:
        return "vmap"
    return layout


def family_suffix(cfg) -> str:
    """Program-family name suffix for the aggregation mode + resolved
    training layout + tenancy: buffered-async families (`round_async`,
    ..., fl/buffered.py), megabatch families (`round_mb`, ...) and
    tenant-pack families (`round_mt`, ..., fl/tenancy.py) are DISTINCT
    programs with distinct names — and they compose (`round_mb_mt`) —
    so manifests, contracts and driver logs never conflate them."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
        buffered)
    sfx = "_async" if buffered.is_buffered(cfg) else ""
    if resolved_train_layout(cfg) == "megabatch":
        sfx += "_mb"
    if getattr(cfg, "tenants", 0) > 0:
        sfx += "_mt"
    return sfx


def carry_aval(cfg, params_aval, sharded: bool = False):
    """The round program's lead-argument aval: bare params (sync), or the
    (params, buffer-state) carry (buffered mode, fl/buffered.py). The
    ``sharded`` flag mirrors the per-bin telemetry layout decision — the
    vmap paths carry the per-staleness accumulators under full telemetry,
    the sharded paths degrade that split and carry none."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
        buffered)
    if not buffered.is_buffered(cfg):
        return params_aval
    return (params_aval,
            buffered.state_avals(cfg, params_aval, per_bin=not sharded))


def fingerprint(cfg, family: str, example_args) -> str:
    """Cache key for one program family: config fields that shape the
    program + jax version + backend + topology + PRNG impl + arg avals.
    Any mismatch is a different key — stale executables can't load."""
    fields = dataclasses.asdict(cfg)
    for name in EXCLUDED_FIELDS:
        fields.pop(name, None)
    if family not in _DIAG_FAMILIES:
        fields["diagnostics"] = False
    # the RESOLVED layout keys the cache (a diagnostics-degraded
    # megabatch config runs the vmap programs — same key, no split)
    fields["train_layout"] = resolved_train_layout(cfg)
    if fields.get("tenants", 0) > 0:
        # tenant packs (fl/tenancy.py): the per-tenant scalar knobs are
        # traced [E]-vector ARGUMENTS of the *_mt programs, so their
        # config values must not split the cache — normalize them to the
        # canonical rep. The one structural bit a knob carries (is the
        # RLR vote built at all) survives as threshold 0/1.
        fields.update(
            server_lr=1.0,
            robustLR_threshold=1 if fields["robustLR_threshold"] > 0 else 0,
            attack_boost=1.0, attack_start=0, attack_stop=0,
            attack_every=1)
    meta = {
        "family": family,
        "cfg": {k: repr(v) for k, v in sorted(fields.items())},
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "process_count": jax.process_count(),
        "prng_impl": str(jax.config.jax_default_prng_impl),
        # compilation-shaping global config: the test harness runs at
        # matmul precision 'highest' while production runs at default —
        # same Config, different compiled math; they must not collide
        "matmul_precision": str(jax.config.jax_default_matmul_precision),
        "x64": bool(jax.config.jax_enable_x64),
        "arg_shapes": _arg_shapes(example_args),
    }
    blob = json.dumps(meta, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:20]


def tenant_pack_key(cfg) -> str:
    """Shape/program-compatibility key for tenant-pack grouping (ISSUE
    13): two cells may share a tenant pack IFF their keys match. Derived
    from the SAME field algebra as the AOT fingerprint — the config minus
    the runtime knobs (EXCLUDED_FIELDS) minus the per-tenant scalar
    knobs (fl/tenancy.TENANT_KNOB_FIELDS, which become traced
    [E]-vectors) — rather than an ad-hoc key list, so a new
    program-shaping field can never silently mix programs inside one
    pack. One addition on top of the fingerprint fields: the dispatch
    schedule (rounds/snap/chain) — runtime fields for the fingerprint,
    but a pack advances every tenant in lockstep, so cells must agree
    on it. The RLR threshold needs no structural split: a pack with ANY
    defended tenant builds the vote (fl/tenancy.canonical_rep derives
    the bit from its members), and a threshold-0 tenant's vote
    degenerates to +server_lr on every coordinate — arithmetically the
    undefended update. `tenants` itself is dropped — pack width is the
    queue's choice, not the cell's."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.tenancy import (
        TENANT_KNOB_FIELDS)
    fields = dataclasses.asdict(cfg)
    for name in EXCLUDED_FIELDS:
        fields.pop(name, None)
    for name in TENANT_KNOB_FIELDS:
        fields.pop(name, None)
    fields.pop("tenants", None)
    fields["train_layout"] = resolved_train_layout(cfg)
    fields["_schedule"] = (cfg.rounds, cfg.snap, cfg.chain)
    meta = {"cfg": {k: repr(v) for k, v in sorted(fields.items())},
            "jax": jax.__version__,
            "backend": jax.default_backend()}
    blob = json.dumps(meta, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


class AotBank:
    """Serialized-executable store under `<root>/aot/`.

    `get_or_compile` is the single entry point: a fingerprint hit
    deserializes and returns the banked executable (no XLA); a miss
    compiles via `lower().compile()` and banks the result for the next
    process. Returns (compiled, cache_hit, seconds, entry)."""

    def __init__(self, root: Optional[str] = None):
        self.dir = os.path.join(root or cache_root(), "aot")
        os.makedirs(self.dir, exist_ok=True)

    def _base(self, family: str, fp: str) -> str:
        return os.path.join(self.dir, f"{family}-{fp}")

    def lookup(self, family: str, fp: str) -> Optional[Dict[str, Any]]:
        try:
            with open(self._base(family, fp) + ".json") as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def load(self, family: str, fp: str):
        """Deserialize a banked executable, or None (any failure = miss —
        logged, because a silently recompiling bank looks identical to a
        working one from the outside)."""
        from jax.experimental import serialize_executable
        try:
            with open(self._base(family, fp) + ".jex", "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            return serialize_executable.deserialize_and_load(
                payload, in_tree, out_tree)
        except Exception as e:
            print(f"[aot] {family}-{fp}: banked executable unloadable "
                  f"({type(e).__name__}: {e}); recompiling")
            return None

    # growth bound: fingerprint churn (config/jax-version changes) leaves
    # dead entries behind; keep the newest MAX_ENTRIES and reap the rest.
    # Sized above one full tier-1 suite's distinct program families (~64)
    # so a suite run never evicts entries a later test in the same run
    # (or the next run) would hit.
    MAX_ENTRIES = 128

    def _reap(self) -> None:
        entries = sorted(self.entries(), key=lambda e: e.get("created", 0.0))
        for e in entries[:-self.MAX_ENTRIES]:
            for ext in (".jex", ".json"):
                try:
                    os.remove(self._base(e["family"], e["fingerprint"])
                              + ext)
                except OSError:
                    pass

    def save(self, family: str, fp: str, compiled, compile_s: float,
             example_args) -> None:
        from jax.experimental import serialize_executable
        payload, in_tree, out_tree = serialize_executable.serialize(compiled)
        base = self._base(family, fp)
        _atomic_write(base + ".jex",
                      pickle.dumps((payload, in_tree, out_tree)))
        entry = {"family": family, "fingerprint": fp,
                 "jax": jax.__version__,
                 "backend": jax.default_backend(),
                 "device_count": jax.device_count(),
                 "process_count": jax.process_count(),
                 "compile_s": round(compile_s, 2),
                 "created": time.time(),
                 "arg_shapes": _arg_shapes(example_args),
                 "file": os.path.basename(base) + ".jex"}
        _atomic_write(base + ".json",
                      json.dumps(entry, indent=1).encode())
        self._reap()

    def get_or_compile(self, family: str, cfg, jit_obj, example_args):
        """(compiled, cache_hit, seconds, entry). `seconds` is the pure
        executable-acquisition time: deserialize on a hit, trace+lower+
        compile on a miss (first-call execution is NOT included).

        The miss path compiles with the persistent XLA cache DISABLED: an
        executable whose compile was served from that cache serializes to
        a payload missing its jitted symbol definitions on XLA:CPU
        ("Symbols not found" at deserialize) — the bank must hold
        self-contained executables. A verify-load after save catches any
        other unserializable case and deletes the broken artifacts."""
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            events as obs_events)
        fp = fingerprint(cfg, family, example_args)
        entry = self.lookup(family, fp)
        if entry is not None:
            t0 = time.perf_counter()
            compiled = self.load(family, fp)
            if compiled is not None:
                obs_events.emit("aot/hit", family=family)
                return compiled, True, time.perf_counter() - t0, entry
        xla_cache_dir = jax.config.jax_compilation_cache_dir
        t0 = time.perf_counter()
        try:
            if xla_cache_dir:
                jax.config.update("jax_compilation_cache_dir", None)
                _reset_jax_cache_state()
            compiled = jit_obj.lower(*abstractify(example_args)).compile()
        finally:
            if xla_cache_dir:
                jax.config.update("jax_compilation_cache_dir",
                                  xla_cache_dir)
                _reset_jax_cache_state()
        secs = time.perf_counter() - t0
        try:
            self.save(family, fp, compiled, secs, example_args)
            if self.load(family, fp) is None:
                raise RuntimeError("verify-load of the banked executable "
                                   "failed")
            entry = self.lookup(family, fp)
        except Exception as e:  # unserializable backend: still usable AOT
            for ext in (".jex", ".json"):
                try:
                    os.remove(self._base(family, fp) + ext)
                except OSError:
                    pass
            entry = {"family": family, "fingerprint": fp,
                     "compile_s": round(secs, 2),
                     "unserializable": f"{type(e).__name__}: {e}"}
        obs_events.emit("aot/miss", family=family)
        return compiled, False, secs, entry

    def entries(self) -> List[Dict[str, Any]]:
        out = []
        for name in sorted(os.listdir(self.dir)):
            if name.endswith(".json"):
                try:
                    with open(os.path.join(self.dir, name)) as f:
                        out.append(json.load(f))
                except (OSError, ValueError):
                    continue
        return out


def setup(cfg):
    """Driver/bench entry: enable the persistent XLA cache and return the
    executable bank, or None when --no_compile_cache (or --debug_nan —
    checkify-wrapped fns are not plain jits and AOT would bypass them)."""
    if not getattr(cfg, "compile_cache", True):
        return None
    root = cache_root(cfg)
    enable_persistent_cache(root)
    if getattr(cfg, "debug_nan", False):
        return None
    return AotBank(root)


def chain_budget(cfg, host_mode: bool = False, cohort: bool = False) -> int:
    """Rounds fused per dispatch — the driver's exact budget: capped at
    `snap` (minus the unchained diagnostic snap round), and 1 in
    host-sampled mode under faults OR an in-jit attack strategy
    (per-round corrupt flags ride each dispatch; train.py prints the
    reason). Cohort-sampled mode keeps its chain under both: the scanned
    round index re-derives the flags in-program
    (fl/rounds.make_cohort_step)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    n = max(1, min(cfg.chain, cfg.snap - (1 if cfg.diagnostics else 0)))
    if (host_mode and not cohort
            and (cfg.faults_enabled or attack_registry.in_jit(cfg))):
        return 1
    return n


def is_host_mode(cfg, fed, threshold: Optional[int] = None) -> bool:
    """Single source of the driver's host-sampled decision — the
    precompile planner and train.run must agree on which program families
    a config dispatches. `threshold` lets the driver pass its own
    (monkeypatchable) byte budget."""
    if threshold is None:
        threshold = DEVICE_RESIDENT_BYTES
    return (cfg.host_sampled == "on"
            or (cfg.host_sampled == "auto"
                and fed.train.images.nbytes > threshold))


# populations at or above this auto-select the cohort-sampled path: a
# dense [K, max_n, ...] stack at 4096+ clients is already the wrong
# layout, and the paper-scale configs (K <= 40, fedemnist 3383) stay on
# their historical bit-exact paths
COHORT_AUTO_MIN_POPULATION = 4096


def is_cohort_mode(cfg, fed=None, threshold: Optional[int] = None) -> bool:
    """Single source of the driver's cohort-sampled decision (ISSUE 7) —
    train.run, the precompile planner and the jaxpr contracts must agree
    on which program families a config dispatches.

    Without `fed` this is the cfg-only decision (explicit on/off, or the
    auto population threshold) — callable before any data is built, which
    is the point: a 1M-client population must never be materialized
    densely just to decide not to materialize it. With `fed`, a
    host-sampled run under churn ALSO routes to the cohort program
    (cohorts sampled in-program from the churn-present set over the dense
    host stacks) — retiring the host-sampled + churn refusal."""
    if cfg.cohort_sampled == "on":
        return True
    if cfg.cohort_sampled == "off":
        return False
    if cfg.num_agents >= COHORT_AUTO_MIN_POPULATION:
        # auto additionally requires the implied cohort to be samplable
        # AND genuinely smaller than the population: with --cohort_size
        # unset, m = floor(K * agent_frac) can be population-sized — the
        # chunked draw could now sample it, but a population-sized
        # "cohort" is just the dense layout with extra steps, and
        # auto-rerouting it would silently change previously-working
        # dense runs. Such configs stay dense, with a hint printed by
        # the engine; an explicit `on` still wins above.
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            cohort as cohort_mod)
        return (cfg.agents_per_round < cfg.num_agents
                and cohort_mod.cohort_feasible(cfg))
    if fed is not None and (cfg.churn_enabled or cfg.traffic_enabled) \
            and is_host_mode(cfg, fed, threshold):
        # churn/traffic-aware cohorting for host-sampled runs — both
        # presence draws need the sampled client ids, which the
        # host-sampled program never sees. Only when the cohort is
        # actually samplable; the driver refuses loudly otherwise (the
        # PR-6 behavior)
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            cohort as cohort_mod)
        return cohort_mod.cohort_feasible(cfg)
    return False


@dataclasses.dataclass
class ProgramSpec:
    """One program family of a run: the jit object to lower and the
    abstract example arguments that pin its (single) instantiation."""
    family: str
    jit_obj: Any
    example_args: Tuple


def plan_programs(cfg, model, norm, fed,
                  host_mode: Optional[bool] = None) -> List[ProgramSpec]:
    """Enumerate the program families train.run would dispatch for `cfg`
    on a single process (the precompile surface). Mirrors the driver's
    mode selection; the mesh>1 shard_map variants are adopted at runtime
    only (their executables embed the live mesh) and are not planned here.
    """
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.evaluate import (
        make_eval_fn, pad_eval_set)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        host_takes_flags, make_chained_cohort_round_fn,
        make_chained_round_fn, make_chained_round_fn_host,
        make_cohort_round_fn, make_round_fn, make_round_fn_host,
        step_takes_round)
    from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
        init_params)

    # normalize the layout ONCE so the plain/diag variants derived below
    # agree with the engine's diagnostics degrade (train.py prints the
    # hint; here the degrade must simply hold)
    cfg = cfg.replace(train_layout=resolved_train_layout(cfg))
    sfx = family_suffix(cfg)
    cohort_mode = is_cohort_mode(cfg, fed)
    if host_mode is None:
        host_mode = (not cohort_mode) and is_host_mode(cfg, fed)
    image_shape = fed.train.images.shape[2:]
    params_aval = jax.eval_shape(
        lambda k: init_params(model, image_shape, k), jax.random.PRNGKey(0))
    # buffered mode: round programs take the (params, buffer-state)
    # carry as their lead argument; eval programs keep bare params
    lead_aval = carry_aval(cfg, params_aval)
    key_aval = abstractify(jax.random.PRNGKey(0))
    data_avals = abstractify((fed.train.images, fed.train.labels,
                              fed.train.sizes))
    chain_n = chain_budget(cfg, host_mode, cohort=cohort_mode)
    ids_aval = jax.ShapeDtypeStruct((chain_n,), jnp.int32)
    plain = cfg.replace(diagnostics=False)
    m = cfg.agents_per_round
    specs: List[ProgramSpec] = []

    if getattr(cfg, "tenants", 0) > 0:
        # tenant-pack families (ISSUE 13, fl/tenancy.py): the experiment
        # axis rides every carried array as a leading [E] dimension; the
        # per-tenant scalar knobs are traced [E]-vector arguments. In
        # buffered mode the stacked lead is the WHOLE (params, buffer
        # state) carry (ISSUE 16 — round_async_mt and friends)
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
            tenancy)
        rep = tenancy.canonical_rep(plain)
        tenancy.check(rep)
        E = rep.tenants
        stackE = functools.partial(
            jax.tree_util.tree_map,
            lambda a: jax.ShapeDtypeStruct((E,) + a.shape, a.dtype))
        pE_aval = stackE(params_aval)
        carryE_aval = stackE(carry_aval(rep, params_aval))
        keysE_aval = jax.ShapeDtypeStruct((E,) + key_aval.shape,
                                          key_aval.dtype)
        rnd_aval = jax.ShapeDtypeStruct((), jnp.int32)
        kavals = tenancy.knob_avals(E)
        if cohort_mode:
            # cohort tenant pack (ISSUE 16 gap 3): shared [m] cohort
            # stacks broadcast across tenants — one bank gather per round
            # serves the whole pack. No chained variant: the engine
            # dispatches cohort packs per-round (the host gather is
            # per-round by construction).
            shard_avals = tuple(
                jax.ShapeDtypeStruct((m,) + a.shape[1:], a.dtype)
                for a in data_avals)
            specs.append(ProgramSpec(
                "round_cohort" + sfx,
                tenancy.make_tenant_cohort_round_fn(rep, model,
                                                    norm).jitted,
                (carryE_aval, keysE_aval, rnd_aval, kavals)
                + shard_avals))
        else:
            specs.append(ProgramSpec(
                "round" + sfx,
                tenancy.make_tenant_round_fn(rep, model, norm,
                                             *data_avals).jitted,
                (carryE_aval, keysE_aval, rnd_aval, kavals) + data_avals))
            if chain_n > 1:
                specs.append(ProgramSpec(
                    "chained" + sfx,
                    tenancy.make_tenant_chained_fn(rep, model, norm,
                                                   *data_avals).jitted,
                    (carryE_aval, keysE_aval, ids_aval, kavals)
                    + data_avals))
        eval_mt = tenancy.make_tenant_eval_fn(model, norm, cfg.n_classes)
        for family, (imgs, lbls) in (
                ("eval_val_mt", (fed.val_images, fed.val_labels)),
                ("eval_poison_mt", (fed.pval_images, fed.pval_labels))):
            eval_avals = abstractify(pad_eval_set(imgs, lbls, cfg.eval_bs))
            specs.append(ProgramSpec(family, eval_mt,
                                     (pE_aval,) + eval_avals))
        return specs

    if cohort_mode:
        # cohort-sampled families (ISSUE 7): data arrives as [m, ...]
        # cohort stacks like host mode, plus the traced round index the
        # in-program sampling consumes (data/cohort.py) — no flag
        # arguments, the program derives them from real client ids
        rnd_aval = jax.ShapeDtypeStruct((), jnp.int32)
        shard_avals = tuple(
            jax.ShapeDtypeStruct((m,) + a.shape[1:], a.dtype)
            for a in data_avals)
        specs.append(ProgramSpec(
            "round_cohort" + sfx,
            make_cohort_round_fn(plain, model, norm),
            (lead_aval, key_aval, rnd_aval) + shard_avals))
        if cfg.diagnostics:
            specs.append(ProgramSpec(
                "round_cohort_diag",
                make_cohort_round_fn(cfg, model, norm),
                (lead_aval, key_aval, rnd_aval) + shard_avals))
        if chain_n > 1:
            block_avals = tuple(
                jax.ShapeDtypeStruct((chain_n,) + a.shape, a.dtype)
                for a in shard_avals)
            specs.append(ProgramSpec(
                "chained_cohort" + sfx,
                make_chained_cohort_round_fn(plain, model, norm),
                (lead_aval, key_aval, ids_aval) + block_avals))
    elif host_mode:
        shard_avals = tuple(
            jax.ShapeDtypeStruct((m,) + a.shape[1:], a.dtype)
            for a in data_avals)
        flags = ((jax.ShapeDtypeStruct((m,), jnp.bool_),)
                 if host_takes_flags(cfg) else ())
        specs.append(ProgramSpec(
            "round_host" + sfx, make_round_fn_host(plain, model, norm),
            (params_aval, key_aval) + shard_avals + flags))
        if cfg.diagnostics:
            specs.append(ProgramSpec(
                "round_host_diag", make_round_fn_host(cfg, model, norm),
                (params_aval, key_aval) + shard_avals + flags))
        if chain_n > 1:
            block_avals = tuple(
                jax.ShapeDtypeStruct((chain_n,) + a.shape, a.dtype)
                for a in shard_avals)
            specs.append(ProgramSpec(
                "chained_host" + sfx,
                make_chained_round_fn_host(plain, model, norm),
                (params_aval, key_aval, ids_aval) + block_avals))
    else:
        # churn — and scheduled-attack — round programs take the round
        # index as a traced int32 scalar (service/churn.py,
        # attack/schedule.py: functions of time, not of the round key;
        # single source fl/rounds.step_takes_round)
        lead = ((jax.ShapeDtypeStruct((), jnp.int32),)
                if step_takes_round(cfg) else ())
        specs.append(ProgramSpec(
            "round" + sfx,
            make_round_fn(plain, model, norm, *data_avals).jitted,
            (lead_aval, key_aval) + lead + data_avals))
        if cfg.diagnostics:
            specs.append(ProgramSpec(
                "round_diag",
                make_round_fn(cfg, model, norm, *data_avals).jitted,
                (lead_aval, key_aval) + lead + data_avals))
        if chain_n > 1:
            specs.append(ProgramSpec(
                "chained" + sfx,
                make_chained_round_fn(plain, model, norm,
                                      *data_avals).jitted,
                (lead_aval, key_aval, ids_aval) + data_avals))

    eval_fn = make_eval_fn(model, norm, cfg.n_classes)
    for family, (imgs, lbls) in (
            ("eval_val", (fed.val_images, fed.val_labels)),
            ("eval_poison", (fed.pval_images, fed.pval_labels))):
        eval_avals = abstractify(pad_eval_set(imgs, lbls, cfg.eval_bs))
        specs.append(ProgramSpec(family, eval_fn,
                                 (params_aval,) + eval_avals))
    return specs


def plan_sharded_programs(cfg, model, norm, fed, mesh,
                          host_mode: bool = False) -> List[ProgramSpec]:
    """Enumerate the shard_map program families for an explicit `mesh`.

    The AOT bank never serves these (their executables embed the live
    mesh; train.run adopts them at runtime), but the static-analysis
    passes (analysis/jaxpr_lint.py) need the exact jit objects + avals the
    driver would dispatch, through the same planner vocabulary — this is
    the lowering hook that keeps the analysis surface and the dispatch
    surface from drifting."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
        make_sharded_chained_round_fn, make_sharded_cohort_round_fn,
        make_sharded_round_fn, make_sharded_round_fn_host)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        host_takes_flags, step_takes_round)
    from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
        init_params)

    # same layout normalization as plan_programs (the plain/diag variants
    # below must agree with the engine's diagnostics degrade)
    cfg = cfg.replace(train_layout=resolved_train_layout(cfg))
    sfx = family_suffix(cfg)
    image_shape = fed.train.images.shape[2:]
    params_aval = jax.eval_shape(
        lambda k: init_params(model, image_shape, k), jax.random.PRNGKey(0))
    # buffered mode: the sharded round programs take the (params,
    # buffer-state) carry — the sharded layout never carries the per-bin
    # telemetry accumulators (fl/buffered.init_state)
    lead_aval = carry_aval(cfg, params_aval, sharded=True)
    key_aval = abstractify(jax.random.PRNGKey(0))
    data_avals = abstractify((fed.train.images, fed.train.labels,
                              fed.train.sizes))
    chain_n = chain_budget(cfg, host_mode,
                           cohort=is_cohort_mode(cfg, fed))
    plain = cfg.replace(diagnostics=False)
    m = cfg.agents_per_round
    specs: List[ProgramSpec] = []
    if getattr(cfg, "tenants", 0) > 0:
        # sharded tenant pack (ISSUE 13): the tenant axis folds INSIDE
        # the shard (parallel/rounds.make_sharded_round_fn_mt) so the
        # leaf/bucket collective plans are unchanged — the *_mt
        # CheckSpecs pin that at 1/8/16-way
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
            tenancy)
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
            make_sharded_round_fn_mt)
        rep = tenancy.canonical_rep(plain)
        E = rep.tenants
        # buffered: the stacked lead is the whole (params, state) carry —
        # the sharded state shape (no per-bin accumulators), [E]-stacked
        carryE_aval = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct((E,) + a.shape, a.dtype),
            carry_aval(rep, params_aval, sharded=True))
        keysE_aval = jax.ShapeDtypeStruct((E,) + key_aval.shape,
                                          key_aval.dtype)
        rnd_aval = jax.ShapeDtypeStruct((), jnp.int32)
        kavals = tenancy.knob_avals(E)
        specs.append(ProgramSpec(
            "round_sharded" + sfx,
            make_sharded_round_fn_mt(rep, model, norm, mesh,
                                     *data_avals).jitted,
            (carryE_aval, keysE_aval, rnd_aval, kavals) + data_avals))
        return specs
    if is_cohort_mode(cfg, fed):
        shard_avals = tuple(
            jax.ShapeDtypeStruct((m,) + a.shape[1:], a.dtype)
            for a in data_avals)
        rnd_aval = jax.ShapeDtypeStruct((), jnp.int32)
        specs.append(ProgramSpec(
            "round_sharded_cohort" + sfx,
            make_sharded_cohort_round_fn(plain, model, norm, mesh),
            (lead_aval, key_aval, rnd_aval) + shard_avals))
        return specs
    if host_mode:
        shard_avals = tuple(
            jax.ShapeDtypeStruct((m,) + a.shape[1:], a.dtype)
            for a in data_avals)
        flags = ((jax.ShapeDtypeStruct((m,), jnp.bool_),)
                 if host_takes_flags(cfg) else ())
        specs.append(ProgramSpec(
            "round_sharded_host" + sfx,
            make_sharded_round_fn_host(plain, model, norm, mesh),
            (params_aval, key_aval) + shard_avals + flags))
        return specs
    lead = ((jax.ShapeDtypeStruct((), jnp.int32),)
            if step_takes_round(cfg) else ())
    specs.append(ProgramSpec(
        "round_sharded" + sfx,
        make_sharded_round_fn(plain, model, norm, mesh,
                              *data_avals).jitted,
        (lead_aval, key_aval) + lead + data_avals))
    if cfg.diagnostics:
        specs.append(ProgramSpec(
            "round_sharded_diag",
            make_sharded_round_fn(cfg, model, norm, mesh,
                                  *data_avals).jitted,
            (lead_aval, key_aval) + lead + data_avals))
    if chain_n > 1:
        ids_aval = jax.ShapeDtypeStruct((chain_n,), jnp.int32)
        specs.append(ProgramSpec(
            "chained_sharded" + sfx,
            make_sharded_chained_round_fn(plain, model, norm, mesh,
                                          *data_avals).jitted,
            (lead_aval, key_aval, ids_aval) + data_avals))
    return specs


def trace_program(jit_obj, example_args):
    """ClosedJaxpr of a planned program — trace only, no lowering, no
    XLA. The analysis passes count primitives on this."""
    args = abstractify(example_args)
    if hasattr(jit_obj, "trace"):
        return jit_obj.trace(*args).jaxpr
    return jax.make_jaxpr(jit_obj)(*args)


def lower_program(jit_obj, example_args):
    """Lowered (StableHLO-level) program for a planned family; call
    `.compile()` on the result for post-optimization HLO."""
    return jit_obj.lower(*abstractify(example_args))


def precompile(cfg, model, norm, fed, bank: AotBank,
               log=print) -> List[Dict[str, Any]]:
    """Bank every planned program family for `cfg`. Idempotent: already-
    banked families are verified loadable and skipped. Returns the manifest
    rows (one per family, with cache_hit + seconds)."""
    rows = []
    for spec in plan_programs(cfg, model, norm, fed):
        compiled, hit, secs, entry = bank.get_or_compile(
            spec.family, cfg, spec.jit_obj, spec.example_args)
        del compiled
        verb = "loaded" if hit else "compiled+banked"
        log(f"[precompile] {spec.family}: {verb} in {secs:.1f}s "
            f"(fp {entry['fingerprint']})")
        rows.append({**entry, "cache_hit": hit,
                     "seconds": round(secs, 2)})
    return rows
