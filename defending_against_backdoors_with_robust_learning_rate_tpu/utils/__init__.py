from defending_against_backdoors_with_robust_learning_rate_tpu.utils.metrics import (  # noqa: F401
    MetricsWriter,
)
