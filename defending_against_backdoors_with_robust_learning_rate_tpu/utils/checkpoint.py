"""Orbax checkpoint / resume.

The reference has NO checkpointing (SURVEY.md section 5.4: a killed 500-round
run restarts from scratch). The build adds it: (global params, round, PRNG
key, cumulative poison accuracy) saved every `snap` rounds, restored with
``--resume``."""

from __future__ import annotations

import os
import re
from typing import Any, Optional, Tuple

import jax
import numpy as np


def _ckptr():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def save(ckpt_dir: str, rnd: int, params, key, cum_poison_acc: float,
         cum_net_mov: float = 0.0) -> None:
    path = os.path.join(os.path.abspath(ckpt_dir), f"round_{rnd:06d}")
    state = {
        "params": jax.device_get(params),
        "round": np.asarray(rnd, np.int64),
        "key": np.asarray(jax.device_get(jax.random.key_data(key))),
        "cum_poison_acc": np.asarray(cum_poison_acc, np.float64),
        "cum_net_mov": np.asarray(cum_net_mov, np.float64),
    }
    ckptr = _ckptr()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()


def latest_round(ckpt_dir: str) -> Optional[int]:
    if not os.path.isdir(ckpt_dir):
        return None
    # only complete checkpoints: a kill mid-save leaves
    # round_NNNNNN.orbax-checkpoint-tmp-* directories behind
    rounds = [int(m.group(1)) for d in os.listdir(ckpt_dir)
              if (m := re.fullmatch(r"round_(\d+)", d))]
    return max(rounds) if rounds else None


def restore(ckpt_dir: str, params_like
            ) -> Optional[Tuple[int, Any, Any, float, float]]:
    """Returns (round, params, key, cum_poison_acc, cum_net_mov) or None."""
    rnd = latest_round(ckpt_dir)
    if rnd is None:
        return None
    path = os.path.join(os.path.abspath(ckpt_dir), f"round_{rnd:06d}")
    key_shape = jax.random.key_data(jax.random.PRNGKey(0)).shape
    target = {
        "params": jax.device_get(params_like),
        "round": np.asarray(0, np.int64),
        "key": np.zeros(key_shape, np.uint32),
        "cum_poison_acc": np.asarray(0.0, np.float64),
        "cum_net_mov": np.asarray(0.0, np.float64),
    }
    try:
        state = _ckptr().restore(path, target)
    except ValueError as e:
        # checkpoint written before cum_net_mov existed: retry with the
        # legacy target. A genuine structural mismatch (e.g. params shape
        # change) fails both attempts and re-raises the ORIGINAL error —
        # no dependence on orbax's error-message wording.
        legacy = dict(target)
        del legacy["cum_net_mov"]
        try:
            state = dict(_ckptr().restore(path, legacy))
        except ValueError:
            # surface the ORIGINAL error; the legacy retry is diagnostic
            # noise (B904: explicit cause, not implicit context chaining)
            raise e from None
        state["cum_net_mov"] = np.asarray(0.0, np.float64)
    key_data = np.asarray(state["key"])
    if key_data.shape != key_shape:
        # threefry key data is [2] uint32, rbg is [4]: a shape mismatch means
        # the checkpoint was written under a different PRNG bit generator —
        # resuming would silently change every stream (train.py apply_rng_impl
        # contract: a checkpoint resumes only under the impl that wrote it)
        raise ValueError(
            f"checkpoint {path} stores PRNG key data of shape "
            f"{key_data.shape} but the active --rng_impl expects {key_shape};"
            f" resume under the rng_impl that wrote the checkpoint")
    key = jax.random.wrap_key_data(key_data)
    return (int(state["round"]), state["params"], key,
            float(state["cum_poison_acc"]), float(state["cum_net_mov"]))
