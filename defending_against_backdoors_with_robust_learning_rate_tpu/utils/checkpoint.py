"""Orbax checkpoint / resume, hardened for the continuous-service driver.

The reference has NO checkpointing (SURVEY.md section 5.4: a killed 500-round
run restarts from scratch). The build adds it — (global params, round, PRNG
key, cumulative poison accuracy) saved every `snap` rounds, restored with
``--resume`` — and the service subsystem (service/driver.py) hardens it to
crash-exact recovery:

- **digest sidecars**: every completed checkpoint directory gets a
  ``round_NNNNNN.digest`` file (sha256 over the directory's file bytes,
  written atomically AFTER orbax finishes, so sidecar presence implies a
  complete checkpoint). ``restore`` verifies the digest before trusting a
  checkpoint and **falls back to the newest digest-valid one** instead of
  crashing on a truncated/corrupt latest file; a checkpoint written before
  digests existed restores on the legacy trust-the-directory path.
- **keep-K pruning**: ``save(keep_last=K)`` reaps the oldest checkpoints
  (and their sidecars) beyond K — the service driver checkpoints forever
  and must not grow the directory without bound.
- **round journal**: a small atomically-rewritten ``journal.json`` mapping
  each checkpointed round to the byte offset of ``metrics.jsonl`` at save
  time. On crash recovery the driver truncates the metrics stream back to
  the journaled offset of whichever checkpoint proved digest-valid, then
  replays — so an interrupted-and-resumed run reproduces the uninterrupted
  run's metrics file byte-for-byte (modulo wall-clock rows).

A ``kill -9`` at ANY point leaves one of: an orbax tmp dir (ignored by
``latest_round``), a complete dir without a sidecar (restored on the legacy
path), a complete dir + sidecar without a journal entry (the journal still
points at the previous checkpoint; the replay is deterministic), or a fully
recorded boundary. Every case resumes to bit-identical metrics rows —
tests/test_service.py drives each one via service/chaos.py.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import shutil
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

JOURNAL_NAME = "journal.json"


def _ckptr():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _round_path(ckpt_dir: str, rnd: int) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), f"round_{rnd:06d}")


def atomic_write_text(path: str, text: str) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)


# ----------------------------------------------------------------- digests ---

def dir_digest(path: str) -> str:
    """sha256 over a checkpoint directory's (sorted relative path, file
    bytes) — file-level, so corruption is detectable WITHOUT attempting an
    orbax restore (a restore failure can then be trusted to mean a
    structural mismatch, which must stay loud, not a disk problem)."""
    h = hashlib.sha256()
    for base, dirs, files in sorted(os.walk(path)):
        dirs.sort()
        for name in sorted(files):
            fp = os.path.join(base, name)
            h.update(os.path.relpath(fp, path).encode())
            with open(fp, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
    return h.hexdigest()


def digest_valid(ckpt_dir: str, rnd: int) -> Optional[bool]:
    """True/False = sidecar present and matching/violated; None = no
    sidecar (a pre-digest legacy checkpoint — unknown, trusted)."""
    path = _round_path(ckpt_dir, rnd)
    try:
        with open(path + ".digest", encoding="utf-8") as f:
            want = f.read().strip()
    except OSError:
        return None
    if not os.path.isdir(path):
        return False
    try:
        return dir_digest(path) == want
    except OSError:
        return False


# ------------------------------------------------------------- save/restore ---

def save(ckpt_dir: str, rnd: int, params, key, cum_poison_acc: float,
         cum_net_mov: float = 0.0, keep_last: int = 0) -> str:
    """Write the round checkpoint + digest sidecar; prune to ``keep_last``
    newest checkpoints when > 0. Returns the checkpoint path."""
    path = _round_path(ckpt_dir, rnd)
    state = {
        "params": jax.device_get(params),
        "round": np.asarray(rnd, np.int64),
        "key": np.asarray(jax.device_get(jax.random.key_data(key))),
        "cum_poison_acc": np.asarray(cum_poison_acc, np.float64),
        "cum_net_mov": np.asarray(cum_net_mov, np.float64),
    }
    ckptr = _ckptr()
    ckptr.save(path, state, force=True)
    ckptr.wait_until_finished()
    # sidecar LAST (atomic): its presence implies the directory is complete
    atomic_write_text(path + ".digest", dir_digest(path) + "\n")
    if keep_last > 0:
        prune(ckpt_dir, keep_last)
    return path


def saved_rounds(ckpt_dir: str) -> List[int]:
    """Complete checkpoint rounds on disk, ascending (orbax tmp dirs from
    a kill mid-save are excluded by the name filter)."""
    if not os.path.isdir(ckpt_dir):
        return []
    return sorted(int(m.group(1)) for d in os.listdir(ckpt_dir)
                  if (m := re.fullmatch(r"round_(\d+)", d)))


def latest_round(ckpt_dir: str) -> Optional[int]:
    rounds = saved_rounds(ckpt_dir)
    return rounds[-1] if rounds else None


def prune(ckpt_dir: str, keep_last: int) -> None:
    """Reap the oldest checkpoints (and sidecars) beyond ``keep_last``."""
    for rnd in saved_rounds(ckpt_dir)[:-keep_last]:
        path = _round_path(ckpt_dir, rnd)
        shutil.rmtree(path, ignore_errors=True)
        try:
            os.remove(path + ".digest")
        except OSError:
            pass


def _restore_state(path: str, params_like) -> Tuple[Dict[str, Any], Any]:
    """One checkpoint's state via orbax (with the legacy no-cum_net_mov
    fallback). Raises on structural mismatch — the caller has already
    ruled out disk corruption via the digest."""
    key_shape = jax.random.key_data(jax.random.PRNGKey(0)).shape
    target = {
        "params": jax.device_get(params_like),
        "round": np.asarray(0, np.int64),
        "key": np.zeros(key_shape, np.uint32),
        "cum_poison_acc": np.asarray(0.0, np.float64),
        "cum_net_mov": np.asarray(0.0, np.float64),
    }
    try:
        state = _ckptr().restore(path, target)
    except ValueError as e:
        # checkpoint written before cum_net_mov existed: retry with the
        # legacy target. A genuine structural mismatch (e.g. params shape
        # change) fails both attempts and re-raises the ORIGINAL error —
        # no dependence on orbax's error-message wording.
        legacy = dict(target)
        del legacy["cum_net_mov"]
        try:
            state = dict(_ckptr().restore(path, legacy))
        except ValueError:
            # surface the ORIGINAL error; the legacy retry is diagnostic
            # noise (B904: explicit cause, not implicit context chaining)
            raise e from None
        state["cum_net_mov"] = np.asarray(0.0, np.float64)
    key_data = np.asarray(state["key"])
    if key_data.shape != key_shape:
        # threefry key data is [2] uint32, rbg is [4]: a shape mismatch means
        # the checkpoint was written under a different PRNG bit generator —
        # resuming would silently change every stream (train.py apply_rng_impl
        # contract: a checkpoint resumes only under the impl that wrote it)
        raise ValueError(
            f"checkpoint {path} stores PRNG key data of shape "
            f"{key_data.shape} but the active --rng_impl expects {key_shape};"
            f" resume under the rng_impl that wrote the checkpoint")
    # Return the key in the SAME representation a fresh engine builds
    # (jax.random.PRNGKey). Under the default raw-key config that is a
    # uint32 vector, and unconditionally wrapping into a typed key<fry>
    # array here changed the program's key-argument aval — every resume,
    # recovery rung and rlr-adapt re-entry missed the AOT bank and
    # recompiled (the ledger-surfaced `aot/miss key<fry>` tax, ISSUE 16).
    fresh = jax.random.PRNGKey(0)
    if jax.dtypes.issubdtype(fresh.dtype, jax.dtypes.prng_key):
        return state, jax.random.wrap_key_data(key_data)
    return state, jnp.asarray(key_data)


def newest_valid_round(ckpt_dir: str) -> Optional[int]:
    """The round ``restore`` would resume from: newest checkpoint whose
    digest is not provably violated (legacy no-sidecar checkpoints are
    trusted)."""
    for rnd in reversed(saved_rounds(ckpt_dir)):
        if digest_valid(ckpt_dir, rnd) is not False:
            return rnd
    return None


def newest_resumable_round(ckpt_dir: str) -> Optional[int]:
    """The round crash-exact resume restores: newest digest-valid round
    that ALSO has a journal entry. A kill between ``save`` and
    ``journal_record`` leaves a newer digest-valid-but-unjournaled
    checkpoint; the journal still points at the previous one, and resuming
    THERE keeps the metrics splice exact — the orphan checkpoint is
    overwritten when its round is re-reached. A dir with checkpoints but
    no journal at all (pre-journal writer) falls back to
    ``newest_valid_round`` with no exactness claim. The service driver
    uses this BEFORE building the engine to truncate the metrics stream to
    the returned round's journaled offset."""
    journaled = {e["round"] for e in journal_read(ckpt_dir)}
    if not journaled:
        return newest_valid_round(ckpt_dir)
    for rnd in reversed(saved_rounds(ckpt_dir)):
        if rnd in journaled and digest_valid(ckpt_dir, rnd) is not False:
            return rnd
    return None


def restore(ckpt_dir: str, params_like, upto: Optional[int] = None,
            upto_validated: bool = False
            ) -> Optional[Tuple[int, Any, Any, float, float]]:
    """Returns (round, params, key, cum_poison_acc, cum_net_mov) from the
    newest digest-valid checkpoint, or None when no usable checkpoint
    exists.

    Fallback policy: a checkpoint whose digest sidecar MISMATCHES its
    directory (truncated/corrupted on disk) is skipped with a warning and
    the next-newest is tried — a crash must cost at most one snap
    interval, never the run. A checkpoint whose digest is VALID but whose
    restore raises (structural mismatch, cross-rng_impl resume) re-raises:
    that is an operator error, and silently resuming something older would
    hide it.

    ``upto`` pins the newest round considered (the service driver passes
    its journal-agreed resume round so restore cannot pick a newer
    unjournaled orphan; ``upto=0`` restores nothing — fresh start).
    ``upto_validated`` skips re-hashing round ``upto``'s directory when the
    caller just digest-validated it (newest_resumable_round reads every
    byte; doing it twice doubles recovery I/O for large models)."""
    rounds = saved_rounds(ckpt_dir)
    if upto is not None:
        rounds = [r for r in rounds if r <= upto]
    for rnd in reversed(rounds):
        valid = (True if upto_validated and rnd == upto
                 else digest_valid(ckpt_dir, rnd))
        if valid is False:
            print(f"[ckpt] round_{rnd:06d}: digest mismatch "
                  f"(truncated/corrupt checkpoint) — falling back to the "
                  f"previous one")
            # lazy import: this module is imported by stdlib-side tools
            # and must not pull the obs package at module-import time
            from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
                events as obs_events)
            obs_events.emit("checkpoint/digest_fallback",
                            severity="error", round=rnd)
            continue
        state, key = _restore_state(_round_path(ckpt_dir, rnd), params_like)
        return (int(state["round"]), state["params"], key,
                float(state["cum_poison_acc"]), float(state["cum_net_mov"]))
    return None


# ------------------------------------------------------------ round journal ---

def journal_path(ckpt_dir: str) -> str:
    return os.path.join(os.path.abspath(ckpt_dir), JOURNAL_NAME)


def journal_read(ckpt_dir: str) -> List[Dict[str, Any]]:
    """The journal's entries (ascending rounds); [] when absent or
    unreadable (a torn write is impossible — writes go through
    tmp + os.replace — but a hand-edited file must not take down the
    driver)."""
    try:
        with open(journal_path(ckpt_dir), encoding="utf-8") as f:
            data = json.load(f)
        return sorted(data.get("entries", []), key=lambda e: e["round"])
    except (OSError, ValueError, KeyError, TypeError):
        return []


def journal_record(ckpt_dir: str, rnd: int, metrics_offset: int,
                   keep_last: int = 0, **extra) -> None:
    """Append/replace the entry for ``rnd`` (atomic rewrite). Entries for
    rounds whose checkpoints were pruned are dropped alongside, bounded by
    ``keep_last`` like the checkpoints themselves."""
    entries = [e for e in journal_read(ckpt_dir) if e["round"] != rnd]
    entries.append({"round": int(rnd),
                    "metrics_offset": int(metrics_offset),
                    "wall_time": time.time(), **extra})
    entries.sort(key=lambda e: e["round"])
    if keep_last > 0:
        entries = entries[-keep_last:]
    os.makedirs(os.path.abspath(ckpt_dir), exist_ok=True)
    atomic_write_text(journal_path(ckpt_dir),
                       json.dumps({"version": 1, "entries": entries},
                                  indent=1) + "\n")


def journal_offset_for(ckpt_dir: str, rnd: int) -> int:
    """metrics.jsonl byte offset journaled for checkpoint round ``rnd``;
    0 when unjournaled (fresh start — truncate everything and replay)."""
    for e in journal_read(ckpt_dir):
        if e["round"] == rnd:
            return int(e["metrics_offset"])
    return 0
