"""Numerical-health guards — the sanitizer subsystem (SURVEY.md section 5.2).

The reference has no race detection or sanitizers to port (single-threaded,
single process); the JAX-native equivalent of a sanitizer pass is
`jax.experimental.checkify`: float checks (NaN/inf) instrumented into the
compiled round program itself. Behind ``--debug_nan``:

    round_fn = guard_round_fn(round_fn)   # checkify.checkify(..., float_checks)
    params, info = round_fn(params, key)  # raises on the first NaN/inf
                                          # produced anywhere in the round

This is strictly a debug mode — the instrumentation costs a few percent and
is off by default. Complementing it, `assert_finite_params` is a cheap
post-round host-side sanity check the driver can run every snap round at
negligible cost (one all-reduce over the params).

Since ISSUE 14 these guards are ENDPOINTS of the unified divergence
policy, not independent policies: every boundary routes through
``health/monitor.assess``/``enforce`` (``--health_policy
abort|recover|record``; ``--debug_nan`` forces abort), and ``enforce``
calls ``finite_warn`` so the historical message and the
FloatingPointError contract stay word-for-word. Call ``finite_warn``
directly only from paths that cannot carry the health lane (e.g. the
multihost pack check) — a second, uncoordinated warn/abort site is the
drift this module's unification removed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import checkify


def guard_round_fn(round_fn):
    """Wrap a round(params, key) -> (params, info) fn with float checks.

    The wrapped fn raises `checkify.JaxRuntimeError` naming the failed check
    on the first NaN/inf produced inside the compiled round."""
    checked = checkify.checkify(round_fn, errors=checkify.float_checks)

    def wrapped(*args):
        err, out = checked(*args)
        checkify.check_error(err)
        return out

    return wrapped


@jax.jit
def _all_finite(params):
    return jnp.all(jnp.stack(
        [jnp.isfinite(l).all()
         for l in jax.tree_util.tree_leaves(params)]))


def all_finite_device(params):
    """Device-side half of the post-round guard: the compiled finite
    reduction WITHOUT the host sync. The async metrics drain
    (utils/metrics.MetricsDrain) fetches the scalar in its batched
    device_get and routes it through `finite_warn` off the round loop's
    critical path."""
    return _all_finite(params)


def finite_warn(finite, where: str = "", raise_error: bool = True) -> bool:
    """Host-side half: act on an already-fetched finite flag. Raises when
    `raise_error`, else prints a loud warning and returns the flag (so
    sweeps record their NaN metrics instead of aborting)."""
    finite = bool(finite)
    if not finite:
        msg = (f"non-finite parameters detected"
               f"{' at ' + where if where else ''}"
               f" — rerun with --debug_nan to locate the producing op")
        if raise_error:
            raise FloatingPointError(msg)
        print(f"[guards] WARNING: {msg}")
    return finite


def assert_finite_params(params, where: str = "",
                         raise_error: bool = True) -> bool:
    """Host-side post-round guard: one compiled reduction + one device sync.

    Returns True when all params are finite. On divergence: raises when
    `raise_error`, else prints a loud warning and returns False."""
    return finite_warn(_all_finite(params), where, raise_error)
