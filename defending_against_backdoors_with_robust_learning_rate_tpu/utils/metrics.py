"""Metrics / observability.

Reference: TensorBoard SummaryWriter with a hyperparameter-derived run name
(src/federated.py:27-31) and seven scalar series (src/federated.py:81-91).
Scalar names are preserved exactly — curve parity against the reference's
TensorBoard output is the acceptance test (SURVEY.md section 5.5):

    Validation/Loss, Validation/Accuracy,
    Poison/Base_Class_Accuracy, Poison/Poison_Accuracy, Poison/Poison_Loss,
    Poison/Cumulative_Poison_Accuracy_Mean

Additions: a JSONL sink (always on — greppable, no TB dependency) and
rounds/sec throughput scalars (SURVEY.md section 5.1: the reference has no
profiling; BASELINE's metric is FL rounds/sec)."""

from __future__ import annotations

import json
import os
import time
from typing import Optional


def run_name(cfg) -> str:
    """Hyperparam-derived run dir name (src/federated.py:27-31, minus the
    duplicated num_corrupt quirk, SURVEY.md 2.3.9, and minus the
    reference's time.ctime() prefix: the name is a pure function of the
    config, so two runs of the same --seed land in the same directory and
    their metrics.jsonl streams can be diffed directly)."""
    faults = ""
    if cfg.faults_enabled:
        faults = (f"-flt:d{cfg.dropout_rate}"
                  f"s{cfg.straggler_rate}c{cfg.corrupt_rate}")
    return (f"clip_val:{cfg.clip}"
            f"-noise_std:{cfg.noise}-aggr:{cfg.aggr}"
            f"-s_lr:{cfg.effective_server_lr}-num_cor:{cfg.num_corrupt}"
            f"-thrs_robustLR:{cfg.robustLR_threshold}"
            f"-pttrn:{cfg.pattern_type}-seed:{cfg.seed}{faults}")


class NullWriter:
    """No-op writer — non-lead processes of a multi-host job use this so
    only process 0 touches the log directory."""

    def scalar(self, tag: str, value, step: int) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class MetricsWriter:
    """JSONL always; TensorBoard when available and enabled."""

    def __init__(self, log_dir: str, name: Optional[str] = None,
                 tensorboard: bool = True):
        os.makedirs(log_dir, exist_ok=True)
        self.dir = os.path.join(log_dir, name) if name else log_dir
        os.makedirs(self.dir, exist_ok=True)
        self._jsonl = open(os.path.join(self.dir, "metrics.jsonl"), "a")
        self._tb = None
        if tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._tb = SummaryWriter(self.dir)
            except Exception:
                self._tb = None
        # deterministic run_name means reruns of one config share this file
        # (resume appends by design); a boundary record lets readers split
        # the stream into runs instead of seeing duplicate (tag, step) rows
        self._jsonl.write(json.dumps(
            {"tag": "_run/start", "value": time.time(), "step": -1}) + "\n")

    def scalar(self, tag: str, value, step: int) -> None:
        self._jsonl.write(json.dumps(
            {"tag": tag, "value": float(value), "step": int(step)}) + "\n")
        if self._tb is not None:
            self._tb.add_scalar(tag, float(value), step)

    def flush(self) -> None:
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()
