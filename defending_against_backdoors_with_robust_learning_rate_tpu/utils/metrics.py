"""Metrics / observability.

Reference: TensorBoard SummaryWriter with a hyperparameter-derived run name
(src/federated.py:27-31) and seven scalar series (src/federated.py:81-91).
Scalar names are preserved exactly — curve parity against the reference's
TensorBoard output is the acceptance test (SURVEY.md section 5.5):

    Validation/Loss, Validation/Accuracy,
    Poison/Base_Class_Accuracy, Poison/Poison_Accuracy, Poison/Poison_Loss,
    Poison/Cumulative_Poison_Accuracy_Mean

Additions: a JSONL sink (always on — greppable, no TB dependency) and
rounds/sec throughput scalars (SURVEY.md section 5.1: the reference has no
profiling; BASELINE's metric is FL rounds/sec)."""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional


def run_name(cfg) -> str:
    """Hyperparam-derived run dir name (src/federated.py:27-31, minus the
    duplicated num_corrupt quirk, SURVEY.md 2.3.9, and minus the
    reference's time.ctime() prefix: the name is a pure function of the
    config, so two runs of the same --seed land in the same directory and
    their metrics.jsonl streams can be diffed directly)."""
    faults = ""
    if cfg.faults_enabled:
        # every fault knob that changes the experiment must be in the name:
        # two sweep cells differing only in threshold mode / spare-corrupt
        # used to collide into one run dir and interleave their
        # metrics.jsonl streams. corrupt_mode / straggler_epochs ride the
        # cell at non-default values only (the coverage pass's
        # run-name-blind rule caught both; default-valued names keep
        # every historical run dir)
        faults = (f"-flt:d{cfg.dropout_rate}"
                  f"s{cfg.straggler_rate}c{cfg.corrupt_rate}"
                  + (f"m{cfg.corrupt_mode}"
                     if cfg.corrupt_mode != "nan" else "")
                  + (f"e{cfg.straggler_epochs}"
                     if cfg.straggler_epochs != 1 else "")
                  + f"-thrm:{cfg.rlr_threshold_mode}"
                  + ("-spare" if cfg.faults_spare_corrupt else ""))
    churn = ""
    if cfg.churn_enabled:
        # same collision rule as the fault knobs: two cells differing only
        # in the churn process must not share a run dir
        churn = (f"-chrn:a{cfg.churn_available}p{cfg.churn_period}"
                 f"s{cfg.churn_seed}")
    traffic = ""
    if cfg.traffic_enabled:
        # diurnal-traffic cell (ISSUE 17): same collision rule; "flat"
        # stays cell-free so every historical run dir is preserved
        # the latency sigma shapes the buffered-mode staleness draw
        # (data/traffic.py) — it rides the cell only in buffered mode,
        # where it changes the experiment (run-name-blind rule; sync
        # traffic names stay historical)
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
            buffered as _buffered)
        traffic = (f"-tfc:{cfg.traffic}p{cfg.traffic_peak_frac}"
                   f"t{cfg.traffic_trough_frac}d{cfg.traffic_day_rounds}"
                   + (f"l{cfg.traffic_latency_sigma}"
                      if _buffered.is_buffered(cfg) else "")
                   + f"s{cfg.traffic_seed}")
    cohort = ""
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    if compile_cache.is_cohort_mode(cfg) or cfg.churn_enabled:
        # population-axis cells (ISSUE 7): two runs differing only in
        # population / cohort size / partitioner must not share a run
        # dir. Churn runs get the cell too: a host-sampled run under
        # churn reroutes to the cohort program at engine construction
        # (train.py — a data-size decision run_name cannot see), and its
        # results then depend on cohort_seed/cohort_size.
        part = cfg.partitioner
        # the partition-shaping params ride the cell too — two runs
        # differing only in the bank's content must not share a dir
        if part == "dirichlet":
            part += f":a{cfg.dirichlet_alpha}n{cfg.samples_per_client}"
        elif part == "pathological":
            part += (f":c{cfg.classes_per_client}"
                     f"n{cfg.samples_per_client}")
        cohort = (f"-coh:K{cfg.num_agents}m{cfg.agents_per_round}"
                  f"-{part}-cs{cfg.cohort_seed}")
    atk = ""
    if cfg.attack != "static":
        # attack-registry cell (ISSUE 11): scenario-matrix cells
        # differing only in strategy / boost / schedule must not collide
        # into one run dir (the rlr_threshold_mode bug class PR 3 fixed).
        # `static` stays cell-free so every pre-registry baseline keeps
        # its historical run dir.
        from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
            schedule as attack_schedule)
        # poison_frac rides the cell too: it is the attack's data
        # intensity, and scenario cells differing only in it (e.g. the
        # signflip vs signflip_clean vocabulary pair) must not share a
        # run dir. Base (static) names never carried it and stay as-is.
        atk = f"-atk:{cfg.attack}b{cfg.attack_boost}p{cfg.poison_frac}"
        if not attack_schedule.is_trivial(cfg):
            atk += (f"s{cfg.attack_start}e{cfg.attack_every}"
                    + (f"t{cfg.attack_stop}" if cfg.attack_stop else ""))
    agm = ""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
        buffered)
    if buffered.is_buffered(cfg):
        # buffered-aggregation cell: two runs differing only in commit
        # threshold / staleness weighting / latency range must not share
        # a run dir (the sweep-cell collision class PR 3 fixed); sync
        # runs stay cell-free so every historical dir is preserved
        agm = (f"-agm:bufK{buffered.buffer_k(cfg)}"
               f"a{cfg.async_staleness_exp}S{cfg.async_max_staleness}")
    qrt = ""
    if cfg.quarantine:
        # static quarantine list (ISSUE 14): excluding clients from the
        # aggregate changes the experiment's results, so two cells
        # differing only in the exclusion list must not share a run dir
        # (run-name-blind rule; the empty default stays cell-free so
        # every historical dir is preserved)
        qrt = f"-qrt:{str(cfg.quarantine).replace(',', '.')}"
    layout = ""
    if compile_cache.resolved_train_layout(cfg) == "megabatch":
        # training-layout cell (ISSUE 10): megabatch results are only
        # ulp-equal to vmap's, so the two layouts must not share a run
        # dir (their metrics streams would interleave). The RESOLVED
        # layout names the dir — a diagnostics-degraded megabatch run
        # lands in (and is comparable to) the vmap dir it actually ran.
        layout = "-tl:mb"
    return (f"clip_val:{cfg.clip}"
            f"-noise_std:{cfg.noise}-aggr:{cfg.aggr}"
            f"-s_lr:{cfg.effective_server_lr}-num_cor:{cfg.num_corrupt}"
            f"-thrs_robustLR:{cfg.robustLR_threshold}"
            f"-pttrn:{cfg.pattern_type}-seed:{cfg.seed}"
            f"{faults}{churn}{traffic}{cohort}{atk}{agm}{qrt}{layout}")


class NullWriter:
    """No-op writer — non-lead processes of a multi-host job use this so
    only process 0 touches the log directory."""

    def scalar(self, tag: str, value, step: int) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class MetricsDrain:
    """Async host-sync pipeline: the round loop queues callbacks with their
    *device* values and moves on; a background thread fetches the values
    (one batched `jax.device_get` across everything queued at that moment —
    a Podracer-style host loop free of synchronous readbacks) and runs the
    callbacks in strict FIFO order, so the metrics stream is bit-identical
    to the synchronous path (tests/test_async_metrics.py pins this).

    Error policy: a callback exception stops the drain and is re-raised on
    the submitting thread at the NEXT submit() — i.e. at the next dispatch
    unit, not only at the next (possibly much later) flush()/close() —
    whichever of submit/flush/close comes first. After the error is
    delivered once, later submissions are silently dropped — metrics can
    lag, never corrupt silently.

    ``flush(timeout=...)`` raises TimeoutError when the drain makes no
    progress within the budget — the service supervisor's wedge signal
    (service/supervisor.py classifies it and degrades to sync metrics).
    ``close()`` interrupted by KeyboardInterrupt still flushes cleanly:
    the worker is told to stop, drains everything already queued, and the
    interrupt then propagates — a ^C never loses recorded rows."""

    def __init__(self, tracer=None):
        self._items = collections.deque()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending = 0
        self._stop = False
        self._error = None
        self._dead = False      # drain thread exited on error: reject work
        self._thread = None
        # optional obs.spans.SpanTracer: attributes the batched device_get
        # (the host sync this pipeline hides) on the drain thread's track
        self._tracer = tracer

    @property
    def dead(self) -> bool:
        """True once the drain thread has exited on an error: callbacks no
        longer execute and submits are dropped. The service driver checks
        this after every supervised eval unit — a dead drain means the
        boundary's rows were lost, so it degrades to synchronous metrics
        and replays the boundary inline instead of serving on with a
        silently dark pipeline."""
        with self._lock:
            return self._dead

    @property
    def pending(self) -> int:
        """Queued-but-undrained callback count — the backpressure gauge
        the flight recorder samples per round (a growing depth is the
        earliest sign a boundary is outrunning the host sync)."""
        with self._lock:
            return self._pending

    def _raise_pending_locked(self) -> None:
        """Deliver the drain thread's error exactly once (caller holds the
        lock)."""
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def submit(self, fn, device_vals, *host_args) -> None:
        """Queue fn(fetched_device_vals, *host_args) for the drain thread.
        `device_vals` may be any pytree of jax arrays (or host scalars).
        A pending drain-thread error is re-raised HERE — the main loop
        learns about a failed metrics callback at its next dispatch, not
        only at the next checkpoint flush."""
        with self._cond:
            self._raise_pending_locked()
            if self._dead:
                return
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="metrics-drain", daemon=True)
                self._thread.start()
            self._items.append((fn, device_vals, host_args))
            self._pending += 1
            self._cond.notify_all()

    def _loop(self):
        import jax
        while True:
            with self._cond:
                while not self._items and not self._stop:
                    self._cond.wait()
                if self._stop and not self._items:
                    return
                batch = list(self._items)
                self._items.clear()
            try:
                # ONE transfer for everything queued right now: the whole
                # batch's device scalars come back in a single device_get
                if self._tracer is not None:
                    with self._tracer.span("drain/device_get",
                                           batch=len(batch)):
                        fetched = jax.device_get([d for _, d, _ in batch])
                else:
                    fetched = jax.device_get([d for _, d, _ in batch])
                for (fn, _, host_args), vals in zip(batch, fetched, strict=True):
                    fn(vals, *host_args)
            except BaseException as e:  # noqa: BLE001 — re-raised at flush
                with self._cond:
                    self._error = e
                    self._dead = True
                    self._pending = 0
                    self._items.clear()
                    self._cond.notify_all()
                return
            with self._cond:
                self._pending -= len(batch)
                self._cond.notify_all()

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until every queued callback has run; re-raise the first
        drain-thread error on this (the submitting) thread. With a
        ``timeout`` (seconds), raise TimeoutError when callbacks are still
        pending past it — the wedged-drain signal the service supervisor
        consumes."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while self._pending > 0 and self._error is None:
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"metrics drain stalled: {self._pending} "
                        f"callback(s) still pending after {timeout:.1f}s")
                self._cond.wait(remaining)
            self._raise_pending_locked()

    def _stop_and_join(self, join_timeout: float = 30.0) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            self._thread = None

    def close(self, raise_errors: bool = True,
              timeout: Optional[float] = None) -> None:
        try:
            self.flush(timeout=timeout)
        except KeyboardInterrupt:
            # ^C mid-flush: flush cleanly anyway. The worker's stop
            # protocol drains everything already queued before exiting
            # (_loop returns only when stop is set AND the queue is
            # empty), so recorded rows still land; then the interrupt
            # propagates — regardless of raise_errors, a user interrupt
            # is never swallowed.
            self._stop_and_join(join_timeout=5.0)
            raise
        except BaseException:
            self._stop_and_join()
            if raise_errors:
                raise
            return
        self._stop_and_join()


class MetricsWriter:
    """JSONL always; TensorBoard when available and enabled."""

    def __init__(self, log_dir: str, name: Optional[str] = None,
                 tensorboard: bool = True, boundary: bool = True):
        os.makedirs(log_dir, exist_ok=True)
        self.dir = os.path.join(log_dir, name) if name else log_dir
        os.makedirs(self.dir, exist_ok=True)
        self.jsonl_path = os.path.join(self.dir, "metrics.jsonl")
        self._jsonl = open(self.jsonl_path, "a")
        self._tb = None
        if tensorboard:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._tb = SummaryWriter(self.dir)
            except Exception:
                self._tb = None
        # deterministic run_name means reruns of one config share this file
        # (resume appends by design); a boundary record lets readers split
        # the stream into runs instead of seeing duplicate (tag, step) rows.
        # `boundary=False` is the crash-exact resume path (service/driver):
        # the stream was truncated to a journaled offset and the continued
        # rows must splice in with NO extra record, so the recovered file
        # is byte-identical to an uninterrupted run's.
        if boundary:
            self._jsonl.write(json.dumps(
                {"tag": "_run/start", "value": time.time(), "step": -1})
                + "\n")

    def offset(self) -> int:
        """Current byte offset of metrics.jsonl (flushed) — what the round
        journal records at checkpoint boundaries (utils/checkpoint.py)."""
        self._jsonl.flush()
        return self._jsonl.tell()

    def scalar(self, tag: str, value, step: int) -> None:
        self._jsonl.write(json.dumps(
            {"tag": tag, "value": float(value), "step": int(step)}) + "\n")
        if self._tb is not None:
            self._tb.add_scalar(tag, float(value), step)

    def flush(self) -> None:
        self._jsonl.flush()
        if self._tb is not None:
            self._tb.flush()

    def close(self) -> None:
        self.flush()
        self._jsonl.close()
        if self._tb is not None:
            self._tb.close()
