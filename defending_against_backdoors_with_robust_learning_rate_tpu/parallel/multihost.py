"""Multi-host (DCN) support — the scale-out path for v5e-256-class meshes.

The reference has no distributed backend at all (SURVEY.md 2.2: no
torch.distributed/NCCL/MPI; its only "multi-GPU" story is backgrounding
independent processes, src/runner.sh:12-18). Here multi-host is first-class:

- one process per host, rendezvoused with `jax.distributed.initialize`
  (driven by --coordinator/--num_processes/--process_id flags, or the
  standard cloud env auto-detection when the flags are absent);
- ONE global 1-D `agents` mesh over all hosts' devices, ordered by
  `mesh_utils.create_hybrid_device_mesh` so that neighboring mesh positions
  are ICI neighbors and the DCN (inter-host) hops are minimized — the
  psum/all_gather/all_to_all collectives in parallel/rounds.py then ride
  ICI within a slice and DCN only at slice boundaries;
- process-local numpy arrays are promoted to global jax.Arrays (replicated
  for params/datasets — every host loads the identical seeded data — and
  agents-sharded for per-agent stacks);
- the aggregation collective PLAN matters most here: per-leaf psums
  (`--agg_layout leaf`, 2L+2 on the flagship) are latency-bound over DCN,
  while the bucketed plan (`--agg_layout bucket`, parallel/buckets.py)
  runs one reduce-scatter + one all-gather per round at bandwidth — the
  multi-process driver adopts whichever the config selects (the sharded
  round builders read `cfg.agg_layout`), and `agg_plan_note` prints which
  plan a mesh is about to run so pod bring-up logs show the collective
  shape next to the topology.

Single-process runs degrade transparently: every helper is a no-op or the
trivial local construction, so the same driver code serves a laptop CPU, a
single TPU chip, a v5e-8 slice, and a multi-host pod.
"""

from __future__ import annotations


import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
    AGENTS_AXIS)


def maybe_initialize(coordinator: str = "", num_processes: int = 0,
                     process_id: int = -1) -> None:
    """Rendezvous this process into the multi-host job.

    With explicit flags, passes them through; with no flags on a cloud TPU
    pod, `jax.distributed.initialize()` auto-detects from the environment.
    Safe to skip entirely for single-process runs (the default)."""
    if num_processes > 1 or coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator or None,
            num_processes=num_processes or None,
            process_id=process_id if process_id >= 0 else None)


def is_lead() -> bool:
    """True on the process that owns logging/metrics/checkpoint writes."""
    return jax.process_index() == 0


def global_agents_mesh(n_devices: int = 0) -> Mesh:
    """A 1-D `agents` mesh over the job's GLOBAL device list.

    Multi-host: hybrid ICI/DCN ordering via mesh_utils, so the agent axis
    walks each host's slice contiguously before crossing DCN. The mesh MUST
    span every process (each host can only run SPMD programs whose mesh
    includes its addressable devices), so a partial n_devices is rejected
    rather than silently excluding hosts. Single-host: parallel/mesh
    construction."""
    if jax.process_count() == 1:
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
            make_mesh)
        return make_mesh(n_devices)
    total = jax.device_count()
    if n_devices not in (0, total):
        raise ValueError(
            f"multi-host mesh must span all {total} global devices, got "
            f"n_devices={n_devices}; pick num_agents/agent_frac so the "
            f"per-round participant count is divisible by {total}")
    from jax.experimental import mesh_utils
    # process_is_granule=True: one DCN granule per *process*. The default
    # granule is the slice, and on any slice spanning multiple hosts
    # (v5e-16 .. v5e-256) slice_count != process_count, which would make
    # this construction raise. Per-process granules are valid on every
    # topology and still order ICI neighbors contiguously within a host.
    devices = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(jax.local_device_count(),),
        dcn_mesh_shape=(jax.process_count(),),
        process_is_granule=True).reshape(-1)
    return Mesh(devices, (AGENTS_AXIS,))


def require_pod_divisible(m: int, what: str) -> int:
    """Global-mesh precondition: the mesh must span every host's devices
    (each host can only run SPMD programs whose mesh includes its
    addressable devices), so the per-round participant count has to divide
    over the full pod. Returns the pod's device count."""
    n = jax.device_count()
    if m % n != 0:
        raise ValueError(
            f"agents_per_round={m} must be divisible by the pod's {n} "
            f"devices for a {what} run; adjust --num_agents/--agent_frac")
    return n


def agg_plan_note(cfg, params, mesh: Mesh) -> str:
    """One bring-up log line for the aggregation collective plan this
    mesh will run each round — the leaf/bucket decision is where a pod
    run's interconnect time is won or lost, so it belongs next to the
    `[mesh]` topology line in the driver log."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        _pallas_applicable)
    n_leaves = len(jax.tree_util.tree_leaves(params))
    d = int(mesh.devices.size)
    if _pallas_applicable(cfg):
        # pallas wins the plan precedence in the shard body — the note
        # must describe the program that actually runs
        return ("fused pallas server step: per-device partial sums + "
                "per-leaf psums (--agg_layout is not consulted)")
    if cfg.agg_layout == "bucket" and cfg.aggr in ("avg", "sign"):
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
            buckets)
        layout = buckets.layout_for_leaves(params, d)
        n = layout.n_buckets + 1 + (2 if cfg.aggr == "avg" else 1)
        return (f"bucketed aggregation: {layout.n_buckets} bucket(s) x "
                f"{layout.bucket:,} coords ({layout.total:,} real), "
                f"{n} collectives/round (reduce-scatter"
                f" x{layout.n_buckets} + all-gather + scalar psums)")
    if cfg.aggr in ("avg", "sign"):
        per_leaf = 2 if (cfg.aggr == "avg"
                         and cfg.robustLR_threshold > 0) else 1
        return (f"leaf aggregation: {per_leaf} psum(s) x {n_leaves} "
                f"leaves + scalars per round (--agg_layout bucket for "
                f"the pod shape)")
    if cfg.aggr == "rfa":
        return ("leaf aggregation: rfa's replicated Weiszfeld iterate "
                "(two psums per iteration, no transpose)")
    return (f"leaf aggregation: {cfg.aggr} rides the all_to_all "
            f"transpose plan over {n_leaves} leaves")


def take_agents_sharded(mesh: Mesh, base: np.ndarray, ids: np.ndarray):
    """`base[ids]` as a global jax.Array sharded over the `agents` axis,
    WITHOUT materializing the full [m, ...] stack on any host.

    Every process holds the full `base` (replicated seeded data) and the
    identical `ids`; `jax.make_array_from_callback` asks each process only
    for its addressable shards, so each host fancy-index-copies just its
    m/P rows. Correct for any mesh device order (hybrid ICI/DCN
    included)."""
    sharding = NamedSharding(mesh, P(AGENTS_AXIS))
    shape = (len(ids),) + base.shape[1:]
    return jax.make_array_from_callback(
        shape, sharding, lambda idx: base[ids[idx[0]]])


def take_agents_sharded_block(mesh: Mesh, base: np.ndarray,
                              ids_blk: np.ndarray):
    """`base[ids_blk]` for a [chain, m] id block as a global
    [chain, m, ...] jax.Array sharded on the m axis (P(None, agents)) —
    the chained-host payload (fl/rounds.make_chained_host). Same
    no-full-stack property as `take_agents_sharded`: each process
    fancy-index-copies only its addressable [chain, m/P, ...] block."""
    sharding = NamedSharding(mesh, P(None, AGENTS_AXIS))
    shape = ids_blk.shape + base.shape[1:]
    return jax.make_array_from_callback(
        shape, sharding, lambda idx: base[ids_blk[idx[0], idx[1]]])


def put_replicated(mesh: Mesh, x):
    """Promote (a pytree of) process-local arrays, identical on every host
    (seeded data / init), to fully-replicated global jax.Arrays."""
    sharding = NamedSharding(mesh, P())

    def one(a):
        a = np.asarray(a)
        if jax.process_count() == 1:
            return jax.device_put(a, sharding)
        from jax.experimental import multihost_utils
        return multihost_utils.host_local_array_to_global_array(
            a, mesh, P())
    return jax.tree_util.tree_map(one, x)


