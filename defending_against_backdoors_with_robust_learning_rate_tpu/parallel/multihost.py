"""Multi-host (DCN) support — the scale-out path for v5e-256-class meshes.

The reference has no distributed backend at all (SURVEY.md 2.2: no
torch.distributed/NCCL/MPI; its only "multi-GPU" story is backgrounding
independent processes, src/runner.sh:12-18). Here multi-host is first-class:

- one process per host, rendezvoused with `jax.distributed.initialize`
  (driven by --coordinator/--num_processes/--process_id flags, or the
  standard cloud env auto-detection when the flags are absent);
- ONE global 1-D `agents` mesh over all hosts' devices, ordered by
  `mesh_utils.create_hybrid_device_mesh` so that neighboring mesh positions
  are ICI neighbors and the DCN (inter-host) hops are minimized — the
  psum/all_gather/all_to_all collectives in parallel/rounds.py then ride
  ICI within a slice and DCN only at slice boundaries;
- process-local numpy arrays are promoted to global jax.Arrays (replicated
  for params/datasets — every host loads the identical seeded data — and
  agents-sharded for per-agent stacks).

Single-process runs degrade transparently: every helper is a no-op or the
trivial local construction, so the same driver code serves a laptop CPU, a
single TPU chip, a v5e-8 slice, and a multi-host pod.
"""

from __future__ import annotations


import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
    AGENTS_AXIS)


def maybe_initialize(coordinator: str = "", num_processes: int = 0,
                     process_id: int = -1) -> None:
    """Rendezvous this process into the multi-host job.

    With explicit flags, passes them through; with no flags on a cloud TPU
    pod, `jax.distributed.initialize()` auto-detects from the environment.
    Safe to skip entirely for single-process runs (the default)."""
    if num_processes > 1 or coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator or None,
            num_processes=num_processes or None,
            process_id=process_id if process_id >= 0 else None)


def is_lead() -> bool:
    """True on the process that owns logging/metrics/checkpoint writes."""
    return jax.process_index() == 0


def global_agents_mesh(n_devices: int = 0) -> Mesh:
    """A 1-D `agents` mesh over the job's GLOBAL device list.

    Multi-host: hybrid ICI/DCN ordering via mesh_utils, so the agent axis
    walks each host's slice contiguously before crossing DCN. The mesh MUST
    span every process (each host can only run SPMD programs whose mesh
    includes its addressable devices), so a partial n_devices is rejected
    rather than silently excluding hosts. Single-host: parallel/mesh
    construction."""
    if jax.process_count() == 1:
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
            make_mesh)
        return make_mesh(n_devices)
    total = jax.device_count()
    if n_devices not in (0, total):
        raise ValueError(
            f"multi-host mesh must span all {total} global devices, got "
            f"n_devices={n_devices}; pick num_agents/agent_frac so the "
            f"per-round participant count is divisible by {total}")
    from jax.experimental import mesh_utils
    # process_is_granule=True: one DCN granule per *process*. The default
    # granule is the slice, and on any slice spanning multiple hosts
    # (v5e-16 .. v5e-256) slice_count != process_count, which would make
    # this construction raise. Per-process granules are valid on every
    # topology and still order ICI neighbors contiguously within a host.
    devices = mesh_utils.create_hybrid_device_mesh(
        mesh_shape=(jax.local_device_count(),),
        dcn_mesh_shape=(jax.process_count(),),
        process_is_granule=True).reshape(-1)
    return Mesh(devices, (AGENTS_AXIS,))


def require_pod_divisible(m: int, what: str) -> int:
    """Global-mesh precondition: the mesh must span every host's devices
    (each host can only run SPMD programs whose mesh includes its
    addressable devices), so the per-round participant count has to divide
    over the full pod. Returns the pod's device count."""
    n = jax.device_count()
    if m % n != 0:
        raise ValueError(
            f"agents_per_round={m} must be divisible by the pod's {n} "
            f"devices for a {what} run; adjust --num_agents/--agent_frac")
    return n


def take_agents_sharded(mesh: Mesh, base: np.ndarray, ids: np.ndarray):
    """`base[ids]` as a global jax.Array sharded over the `agents` axis,
    WITHOUT materializing the full [m, ...] stack on any host.

    Every process holds the full `base` (replicated seeded data) and the
    identical `ids`; `jax.make_array_from_callback` asks each process only
    for its addressable shards, so each host fancy-index-copies just its
    m/P rows. Correct for any mesh device order (hybrid ICI/DCN
    included)."""
    sharding = NamedSharding(mesh, P(AGENTS_AXIS))
    shape = (len(ids),) + base.shape[1:]
    return jax.make_array_from_callback(
        shape, sharding, lambda idx: base[ids[idx[0]]])


def take_agents_sharded_block(mesh: Mesh, base: np.ndarray,
                              ids_blk: np.ndarray):
    """`base[ids_blk]` for a [chain, m] id block as a global
    [chain, m, ...] jax.Array sharded on the m axis (P(None, agents)) —
    the chained-host payload (fl/rounds.make_chained_host). Same
    no-full-stack property as `take_agents_sharded`: each process
    fancy-index-copies only its addressable [chain, m/P, ...] block."""
    sharding = NamedSharding(mesh, P(None, AGENTS_AXIS))
    shape = ids_blk.shape + base.shape[1:]
    return jax.make_array_from_callback(
        shape, sharding, lambda idx: base[ids_blk[idx[0], idx[1]]])


def put_replicated(mesh: Mesh, x):
    """Promote (a pytree of) process-local arrays, identical on every host
    (seeded data / init), to fully-replicated global jax.Arrays."""
    sharding = NamedSharding(mesh, P())

    def one(a):
        a = np.asarray(a)
        if jax.process_count() == 1:
            return jax.device_put(a, sharding)
        from jax.experimental import multihost_utils
        return multihost_utils.host_local_array_to_global_array(
            a, mesh, P())
    return jax.tree_util.tree_map(one, x)


