"""Bucketed flat-vector layout for pod-shape aggregation collectives.

The leaf-wise aggregation plan in `parallel/rounds.py` issues one psum per
parameter leaf (2L+2 = 18 collectives on the flagship CNN) — free on one
chip where psums are memcpys, the wrong shape for a pod: Podracer
(arXiv:2104.06272) makes device utilization the scaling signal and wants
FEW, LARGE collectives so the interconnect runs at bandwidth instead of
latency. This module is the layout half of that rework (`--agg_layout
bucket`): flatten the update pytree ONCE into at most a few fixed-size
buckets, run one `reduce-scatter` per bucket, compute the weighted
average AND the RLR sign-vote on the scattered shard, and `all-gather`
only the already-LR-scaled result.

Layout rules (all static, computed at trace time from the leaf avals):

- leaves are flattened in pytree order and concatenated into one flat
  coordinate space of `total` real coordinates;
- the flat space is padded up to ``n_buckets * bucket`` where ``bucket``
  is divisible by the mesh size ``d`` — padding is EXPLICIT (zeros), and
  every consumer masks it out of statistics via `shard_coord_index`;
- ``n_buckets = ceil(total_bytes / BUCKET_BYTES)``: small models (the
  flagship CNN) take ONE bucket; a model too big to stage as a single
  flat copy splits into ~`BUCKET_BYTES` chunks so collective message
  sizes stay bounded (and real pods can pipeline them).

The layout is a pure function of (leaf shapes/dtypes, d, bucket bytes)
and is memoized on exactly that key — the same aval signature that keys
the AOT fingerprint (`utils/compile_cache.fingerprint`), so one layout
serves every trace of a program family and can never drift from the
banked executable's shapes.

Donation safety: `flatten_stacked`/`flatten_tree` build NEW buffers
(reshape+concat) and never alias their inputs, and `unflatten` returns
slices of the gathered vector — a donated `params` buffer is only ever
read leaf-wise on the `p + delta` tail, exactly like the leaf path.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp

# per-bucket payload ceiling: one bucket for anything up to ResNet-9
# scale (4.9M f32 params ~ 19 MiB -> 2 buckets), bounded message sizes
# beyond. A power of two keeps the padded length friendly to the d-way
# shard split at every topology in the contract matrix (1/8/16-way).
BUCKET_BYTES = 16 << 20


@dataclasses.dataclass(frozen=True)
class BucketLayout:
    """Static description of one flattened update space.

    `shapes`/`sizes`/`offsets` describe the leaves in pytree order;
    `total` is the real coordinate count, `padded = n_buckets * bucket`
    the explicit-padding extent; `bucket % d == 0` always holds so the
    per-bucket reduce-scatter shard is `bucket // d` on every device."""
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    offsets: Tuple[int, ...]
    total: int
    padded: int
    n_buckets: int
    bucket: int
    d: int

    @property
    def shard(self) -> int:
        """Per-bucket, per-device shard length of the scattered result."""
        return self.bucket // self.d

    @property
    def device_len(self) -> int:
        """Total scattered coordinates one device holds (all buckets)."""
        return self.n_buckets * self.shard


@functools.lru_cache(maxsize=64)
def _layout(leaf_key: Tuple[Tuple[Tuple[int, ...], str], ...], d: int,
            bucket_bytes: int) -> BucketLayout:
    import math
    shapes = tuple(s for s, _ in leaf_key)
    sizes = tuple(math.prod(s) for s in shapes)
    offsets, off = [], 0
    for n in sizes:
        offsets.append(off)
        off += n
    total = off
    # 4 bytes/coord: the flat space is f32 regardless of leaf dtype (the
    # aggregation arithmetic is f32 on the leaf path too)
    n_buckets = max(1, -(-total * 4 // bucket_bytes))
    bucket = -(-total // n_buckets)
    bucket += -bucket % max(d, 1)            # divisible by the mesh size
    return BucketLayout(shapes=shapes, sizes=sizes, offsets=tuple(offsets),
                        total=total, padded=n_buckets * bucket,
                        n_buckets=n_buckets, bucket=bucket, d=d)


def layout_for_leaves(tree, d: int,
                      bucket_bytes: int = 0) -> BucketLayout:
    """Layout keyed by the UNSTACKED per-coordinate leaf shapes of
    `tree` (aggregate/params-shaped pytree). `bucket_bytes` 0 = the
    module default (resolved at call time so tests can shrink it to
    force the multi-bucket path on tiny models)."""
    leaves = jax.tree_util.tree_leaves(tree)
    key = tuple((tuple(l.shape), str(l.dtype)) for l in leaves)
    return _layout(key, d, bucket_bytes or BUCKET_BYTES)


def layout_for_stacked(tree, d: int,
                       bucket_bytes: int = 0) -> BucketLayout:
    """Layout for a pytree of `[mb, ...]` stacked update leaves: the
    leading agent axis is stripped before keying, so the stacked and
    aggregate views of the same model share one layout object."""
    leaves = jax.tree_util.tree_leaves(tree)
    key = tuple((tuple(l.shape[1:]), str(l.dtype)) for l in leaves)
    return _layout(key, d, bucket_bytes or BUCKET_BYTES)


def flatten_stacked(layout: BucketLayout, tree) -> jnp.ndarray:
    """[mb, ...] stacked leaves -> one [mb, padded] f32 matrix (explicit
    zero padding on the tail). New buffers — never aliases the input."""
    leaves = jax.tree_util.tree_leaves(tree)
    mb = leaves[0].shape[0]
    flat = jnp.concatenate(
        [l.reshape(mb, -1).astype(jnp.float32) for l in leaves], axis=1)
    pad = layout.padded - layout.total
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return flat


def flatten_tree(layout: BucketLayout, tree) -> jnp.ndarray:
    """Aggregate-shaped pytree -> one [padded] f32 vector (zero-padded).
    Used to route replicated per-leaf values (server noise) through the
    scattered layout without changing their generation semantics."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = jnp.concatenate(
        [l.reshape(-1).astype(jnp.float32) for l in leaves])
    pad = layout.padded - layout.total
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat


def unflatten(layout: BucketLayout, flat, treedef):
    """[padded] (or longer; extra tail ignored) flat vector -> pytree of
    aggregate-shaped f32 leaves, inverse of `flatten_tree`."""
    leaves = [jax.lax.dynamic_slice_in_dim(flat, off, n, 0).reshape(shape)
              for off, n, shape in zip(layout.offsets, layout.sizes,
                                       layout.shapes, strict=True)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def device_shard(layout: BucketLayout, flat_1d, device_pos):
    """This device's scattered coordinates of a replicated [padded]
    vector: concat over buckets of the [shard] slice at `device_pos` —
    the exact coordinates `lax.psum_scatter(..., tiled=True)` leaves on
    that device. `device_pos` may be traced (lax.axis_index)."""
    return jnp.concatenate([
        jax.lax.dynamic_slice_in_dim(
            flat_1d, b * layout.bucket + device_pos * layout.shard,
            layout.shard, 0)
        for b in range(layout.n_buckets)])


def shard_coord_index(layout: BucketLayout, device_pos) -> jnp.ndarray:
    """[device_len] global flat-coordinate index of this device's
    scattered shard (all buckets concatenated). Compare against
    `layout.total` to mask padding out of shard-local statistics."""
    per_bucket = jnp.arange(layout.shard, dtype=jnp.int32)
    return jnp.concatenate([
        b * layout.bucket + device_pos * layout.shard + per_bucket
        for b in range(layout.n_buckets)])


def gathered_to_flat(layout: BucketLayout, gathered_rows) -> jnp.ndarray:
    """[d, device_len] all-gathered per-device rows -> the replicated
    [padded] flat vector. Device i's row holds its [shard] slice of every
    bucket back-to-back, so the bucket-major reassembly is a transpose."""
    rows = gathered_rows.reshape(layout.d, layout.n_buckets, layout.shard)
    return jnp.transpose(rows, (1, 0, 2)).reshape(layout.padded)
