"""JAX version-compat shims for the parallel layer.

`shard_map` graduated from `jax.experimental.shard_map` to the `jax.*`
namespace (and its replication-check kwarg was renamed `check_rep` ->
`check_vma` in the move). The repo targets the public `jax.shard_map`
surface; on installs that predate it (e.g. the pinned 0.4.37 toolchain)
this module adapts the call to the experimental entry point so one code
path serves both."""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """`jax.shard_map` when available, else the experimental equivalent.

    `check_vma` maps onto the experimental API's `check_rep` (same switch,
    renamed at graduation); callers use the new-world name only."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
