"""Sharded FL round: `shard_map` over the `agents` mesh axis.

This is the distributed-communication backend the reference lacks entirely
(SURVEY.md 2.2: no torch.distributed/NCCL/MPI — updates travel as an
in-process Python dict, src/federated.py:67-74). Mapping, per SURVEY.md
section 5.8:

    agg_avg          -> psum of locally-weighted sums            (ICI)
    agg_sign / RLR   -> psum of per-coordinate sign sums         (ICI)
    agg_comed        -> all_to_all transpose to param-sharded layout,
                        local median, all_gather of median chunks
    agg_trmean       -> same transpose, local sort + trimmed-band mean
    agg_krum         -> all_to_all transpose, chunk-partial pairwise
                        distances psummed to the full [m, m] matrix,
                        winner's chunks re-assembled by all_gather
    agg_rfa          -> replicated Weiszfeld iterate; two psums per
                        iteration (local-block distances, no transpose)

comed/krum deliberately avoid `all_gather`ing the full [m, n_params]
update matrix (SURVEY.md 7.3.1: ~1 GiB/device at 256 agents x 1M params).
The `all_to_all` transpose repurposes the mesh axis from agents to
parameter chunks: each device ends up holding ALL m agents for 1/d of the
coordinates — memory AND interconnect traffic drop by the mesh factor d,
and the median/distance arithmetic is d-way parallel instead of
replicated.

Every device trains its block of m/d sampled agents (local `vmap`), then the
collective aggregation produces *replicated* new global params — one compiled
program per round, no host round-trips. Parity with the single-device vmap
path is asserted in tests/test_parallel.py on a faked 8-device CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
    buffered)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
    bind_data, make_block_trainer, make_chained)
from defending_against_backdoors_with_robust_learning_rate_tpu.health import (
    sentinel as health_sentinel)
from defending_against_backdoors_with_robust_learning_rate_tpu.ops import tree
from defending_against_backdoors_with_robust_learning_rate_tpu.ops.aggregate import (
    RFA_EPS, RFA_ITERS, agent_sq_dists, apply_aggregate, gaussian_noise_like,
    rlr_from_sign_sum, sq_dist_accum, trmean_k)
from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
    buckets)
from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.compat import (
    shard_map)
from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
    AGENTS_AXIS)


def _to_param_shards(u, d):
    """[m/d, ...] local agent block -> ([m, c] all agents x local param chunk,
    flat length L). The all_to_all transposes the mesh axis from agents to
    parameter chunks; rows arrive in device order = global agent order."""
    mb = u.shape[0]
    flat = u.reshape(mb, -1)
    L = flat.shape[1]
    pad = -L % d
    if pad:
        flat = jnp.pad(flat, ((0, 0), (0, pad)))
    return jax.lax.all_to_all(flat, AGENTS_AXIS, split_axis=1, concat_axis=0,
                              tiled=True), L


def _from_param_shard(chunk, L, leaf_shape):
    """[c] local param chunk -> [...] full replicated leaf (all_gather)."""
    full = jax.lax.all_gather(chunk, AGENTS_AXIS, axis=0, tiled=True)
    return full[:L].reshape(leaf_shape)


def _sharded_aggregate(updates, sizes, cfg, d, key, mask_local=None,
                       mask_full=None, out=None):
    """Aggregation rules as collectives. `updates` leaves are the local block
    [m/d, ...]; `d` is the mesh size; returns the replicated aggregate.

    The faults path passes the participation mask twice: `mask_local`
    ([m/d] bool, this device's agent block) zeroes local rows/weights
    before the psums, and `mask_full` ([m] bool, replicated — every device
    derives the identical draw from the replicated fault key) drives the
    sentinel/index arithmetic on the all_to_all-transposed [m, c] chunks.
    None/None is the dense path, bit-for-bit the pre-faults behavior.

    `out` (optional dict): the sign branch stashes its raw per-leaf
    sign-sum psum results under ``"sign_sums"`` — the reputation lane
    (obs/reputation.py) re-reads the existing collective instead of
    issuing its own (the `_sharded_sign_shared` sharing discipline for
    the thresholdless sign aggregate)."""
    ax = AGENTS_AXIS
    masked = mask_local is not None
    if masked:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            masking)
        n_eff = masking.count(mask_full)
    if cfg.aggr == "avg":
        w = sizes.astype(jnp.float32)
        if masked:
            w = jnp.where(mask_local, w, 0.0)
            updates = masking.zero_masked(updates, mask_local)
        total = jax.lax.psum(jnp.sum(w), ax)

        def leaf(u):
            wshape = (-1,) + (1,) * (u.ndim - 1)
            return jax.lax.psum(jnp.sum(u * w.reshape(wshape), axis=0),
                                ax) / total
        agg = tree.map(leaf, updates)
    elif cfg.aggr == "sign":
        if masked:
            # zeroed rows vote sign(0) = 0 in the psum
            updates = masking.zero_masked(updates, mask_local)
        sums = tree.map(
            lambda u: jax.lax.psum(jnp.sum(jnp.sign(u), axis=0), ax),
            updates)
        if out is not None:
            out["sign_sums"] = sums
        agg = tree.map(jnp.sign, sums)
    elif cfg.aggr == "comed":
        m = cfg.agents_per_round

        def leaf(u):
            chunk, L = _to_param_shards(u, d)            # [m, c]
            if masked:
                med = masking.median_rows(chunk, mask_full, n_eff)
            else:
                med = jnp.sort(chunk, axis=0)[(m - 1) // 2]  # lower median
            return _from_param_shard(med, L, u.shape[1:])
        agg = tree.map(leaf, updates)
    elif cfg.aggr == "trmean":
        # coordinate-wise trimmed mean rides the same param-sharded
        # transpose as comed: sort the [m, c] chunk, mean the untrimmed
        # middle band (ops/aggregate.agg_trmean semantics)
        m = cfg.agents_per_round
        k = trmean_k(cfg.num_corrupt, m)

        def leaf(u):
            chunk, L = _to_param_shards(u, d)            # [m, c]
            if masked:
                band_mean = masking.trimmed_mean_rows(
                    chunk, mask_full, n_eff, cfg.num_corrupt)
            else:
                band_mean = jnp.mean(jnp.sort(chunk, axis=0)[k:m - k], axis=0)
            return _from_param_shard(band_mean, L, u.shape[1:])
        agg = tree.map(leaf, updates)
    elif cfg.aggr == "krum":
        m = cfg.agents_per_round
        if masked:
            # garbage payloads must not poison the distance matrix
            updates = masking.zero_masked(updates, mask_local)
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        shards = [_to_param_shards(u, d) for u in leaves]
        # chunk-partial pairwise squared distances; psum over the mesh axis
        # (now indexing param chunks) completes the sum over coordinates
        dist = jnp.zeros((m, m), jnp.float32)
        for chunk, _ in shards:
            dist = sq_dist_accum(dist, chunk)
        dist = jnp.maximum(jax.lax.psum(dist, ax), 0.0)
        if masked:
            best = masking.krum_best(dist, mask_full, n_eff, cfg.num_corrupt)
        else:
            k = max(m - cfg.num_corrupt - 2, 1)
            srt = jnp.sort(dist, axis=1)
            best = jnp.argmin(jnp.sum(srt[:, 1:k + 1], axis=1))
        agg = jax.tree_util.tree_unflatten(treedef, [
            _from_param_shard(chunk[best], L, u.shape[1:])
            for (chunk, L), u in zip(shards, leaves, strict=True)])
    elif cfg.aggr == "rfa":
        # geometric median (smoothed Weiszfeld, ops/aggregate.agg_rfa
        # semantics): the iterate v is replicated; per-agent distances are
        # computed on each device's local block, so every iteration costs
        # exactly two psums (weighted sum + weight total) over ICI — no
        # transpose needed
        m = cfg.agents_per_round
        if masked:
            updates = masking.zero_masked(updates, mask_local)
            # reciprocal-multiply matches the dense divide-by-constant
            # after XLA strength reduction (faults/masking.py)
            denom = 1.0 / masking.count_f32(mask_full)
            w_base = mask_local.astype(jnp.float32)
        else:
            denom = 1.0 / m
            w_base = 1.0
        v = tree.map(
            lambda u: jax.lax.psum(jnp.sum(u.astype(jnp.float32), axis=0),
                                   ax) * denom, updates)
        for _ in range(RFA_ITERS):
            w = w_base / jnp.maximum(jnp.sqrt(agent_sq_dists(updates, v)),
                                     RFA_EPS)
            wsum = jax.lax.psum(jnp.sum(w), ax)

            def leaf(u, w=w, wsum=wsum):
                wshape = (-1,) + (1,) * (u.ndim - 1)
                return jax.lax.psum(
                    jnp.sum(u * w.reshape(wshape), axis=0), ax) / wsum
            v = tree.map(leaf, updates)
        agg = v
    else:
        raise ValueError(f"unknown aggr {cfg.aggr!r}")
    if cfg.noise > 0:
        # key is replicated across devices -> identical noise everywhere
        agg = tree.add(agg, gaussian_noise_like(agg, key,
                                                cfg.noise * cfg.clip))
    if masked:
        # all payloads dropped/rejected -> zero aggregate (noise included),
        # making the round a full no-op — matches the vmap path's guard
        agg = masking.guard_empty(agg, mask_full)
    return agg


def _sharded_sign_shared(updates, cfg, noise_key, mask_local=None,
                         mask_full=None, knobs=None):
    """aggr='sign' + RLR: ONE sign-sum psum per leaf, read twice — the
    vote takes |s| and the aggregate takes sign(s).

    The code used to issue the two textually-identical psums and rely on
    XLA CSE to merge them; the jaxpr contract checker measured that the
    partitioned all-reduces (distinct channel ids) never CSE — 20
    all-reduces where the plan promises 12 (analysis_baseline.json,
    sharded_rlr_sign). Sharing the collective here makes the documented
    budget true by construction; values are bit-identical (same
    arithmetic, same order). Returns (lr_tree, agg_tree, sign_sums_tree)
    with server noise + empty-electorate guard applied, mirroring
    _sharded_aggregate's tail; `sign_sums` is the raw per-leaf psum
    result, handed to full telemetry so its vote-margin histogram reads
    the SAME collective instead of issuing a third copy per leaf.
    `knobs` (fl/tenancy.TenantKnobs scalars, inside the tenant vmap)
    overrides the threshold/server-lr constants per tenant."""
    thr = (float(cfg.robustLR_threshold) if knobs is None
           else knobs.rlr_threshold)
    if mask_local is not None:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            masking)
        updates = masking.zero_masked(updates, mask_local)
        thr = masking.rlr_threshold(
            cfg, mask_full,
            base=None if knobs is None else knobs.rlr_threshold)
    slr = cfg.effective_server_lr if knobs is None else knobs.server_lr
    leaves, treedef = jax.tree_util.tree_flatten(updates)
    lr_leaves, agg_leaves, s_leaves = [], [], []
    for u in leaves:
        s = jax.lax.psum(jnp.sum(jnp.sign(u), axis=0), AGENTS_AXIS)
        lr_leaves.append(rlr_from_sign_sum(s, thr, slr))
        agg_leaves.append(jnp.sign(s))
        s_leaves.append(s)
    lr = jax.tree_util.tree_unflatten(treedef, lr_leaves)
    agg = jax.tree_util.tree_unflatten(treedef, agg_leaves)
    sign_sums = jax.tree_util.tree_unflatten(treedef, s_leaves)
    if cfg.noise > 0:
        agg = tree.add(agg, gaussian_noise_like(agg, noise_key,
                                                cfg.noise * cfg.clip))
    if mask_local is not None:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            masking)
        agg = masking.guard_empty(agg, mask_full)
    return lr, agg, sign_sums


def _sharded_robust_lr(updates, cfg, mask_local=None, mask_full=None,
                       knobs=None):
    """RLR sign-agreement vote as a psum (src/aggregation.py:48-54 semantics,
    vote over exactly the m sampled agents — minus masked-out voters on the
    faults path, where the threshold may also scale with the electorate).
    Returns (lr_tree, sign_sums_tree): the RAW signed per-leaf psums —
    `rlr_from_sign_sum` takes |s| internally and full telemetry's margin
    histogram takes |s| at the read site, so handing the raw sums out is
    value-identical to the historical |psum| hand-off while ALSO carrying
    the vote's direction, which the reputation lane (obs/reputation.py)
    compares per-client updates against. Zero extra psums either way
    (the same sharing `_sharded_sign_shared` does for the sign
    aggregate). `knobs` overrides the threshold/server-lr constants per
    tenant (fl/tenancy.py)."""
    thr = (float(cfg.robustLR_threshold) if knobs is None
           else knobs.rlr_threshold)
    if mask_local is not None:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            masking)
        updates = masking.zero_masked(updates, mask_local)
        thr = masking.rlr_threshold(
            cfg, mask_full,
            base=None if knobs is None else knobs.rlr_threshold)
    slr = cfg.effective_server_lr if knobs is None else knobs.server_lr
    leaves, treedef = jax.tree_util.tree_flatten(updates)
    lr_leaves, s_leaves = [], []
    for u in leaves:
        s = jax.lax.psum(jnp.sum(jnp.sign(u), axis=0), AGENTS_AXIS)
        lr_leaves.append(rlr_from_sign_sum(s, thr, slr))
        s_leaves.append(s)
    return (jax.tree_util.tree_unflatten(treedef, lr_leaves),
            jax.tree_util.tree_unflatten(treedef, s_leaves))


def _bucket_applicable(cfg) -> bool:
    """The bucketed reduce-scatter layout covers the psum-shaped rules
    (weighted FedAvg and signSGD, RLR on or off — the paper's headline
    configurations). The transpose rules (comed/trmean/krum) already run
    few large collectives (all_to_all + all_gather) and keep their plan;
    rfa's replicated Weiszfeld iterate keeps its per-iteration psums.
    Diagnostics need the full lr tree materialized, which the scattered
    vote never builds — `_build_sharded_body` refuses that combination
    loudly rather than silently mixing layouts across snap rounds."""
    return cfg.agg_layout == "bucket" and cfg.aggr in ("avg", "sign")


class _BucketInfo:
    """What the bucketed apply hands to telemetry: the post-noise/guard
    aggregate tree (full level only — reassembled from the same
    all_gather that carried the LR-scaled result), the globally-summed
    vote/flip stats vector that rode that gather (obs/telemetry.py
    shard_vote_stats; None when telemetry is off), the real (unpadded)
    coordinate count, and — when the reputation lane is on — this
    device's [m/d] rep_agree block (obs/reputation.py, computed against
    the full sign vote whose shard rode the same gather) plus its [m/d]
    rep_norm block (local: the flat block holds full coordinate rows)."""

    def __init__(self, agg=None, stats=None, total_coords=0,
                 rep_agree=None, rep_norm=None):
        self.agg = agg
        self.stats = stats
        self.total_coords = total_coords
        self.rep_agree = rep_agree
        self.rep_norm = rep_norm


def _bucketed_apply(params, updates, sizes, cfg, noise_key, d,
                    mask_local=None, mask_full=None, knobs=None):
    """avg/sign [+ RLR] aggregation on the bucketed flat layout
    (parallel/buckets.py): ONE reduce-scatter per bucket of the stacked
    partial sums (weighted sum and/or sign sum ride the SAME collective),
    the masked weighted-average AND the RLR sign-vote computed on the
    scattered shard, then ONE all_gather of the already-LR-scaled result.
    Collectives on the flagship (1 bucket): reduce-scatter + all-gather
    (+ the scalar weight-total psum for avg) — vs 2L+2 = 18 per-leaf
    psums on the leaf layout.

    Per-coordinate arithmetic is IDENTICAL to the leaf path (the flatten
    is a relayout, the local partial sums run over the same mb rows in
    the same order, noise is generated per leaf with the same key split,
    the empty-electorate guard multiplies the same replicated flag), so
    bucket-vs-leaf parity is pinned bitwise in fp32
    (tests/test_bucket_parity.py). Padding coordinates are explicit
    zeros: they vote margin 0 (=> lr -slr), aggregate 0, and are masked
    out of every statistic via `shard_coord_index`.

    Returns (new_params, _BucketInfo)."""
    ax = AGENTS_AXIS
    masked = mask_local is not None
    rlr = cfg.robustLR_threshold > 0
    thr = (float(cfg.robustLR_threshold) if knobs is None
           else knobs.rlr_threshold)
    if masked:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            masking)
        updates = masking.zero_masked(updates, mask_local)
        if rlr:
            thr = masking.rlr_threshold(
                cfg, mask_full,
                base=None if knobs is None else knobs.rlr_threshold)
    slr = cfg.effective_server_lr if knobs is None else knobs.server_lr
    layout = buckets.layout_for_stacked(updates, d)
    flat = buckets.flatten_stacked(layout, updates)       # [mb, padded]

    # the full level reads vote margins even without RLR (the leaf path
    # budgets its own per-leaf psums for that; here the sign sums ride
    # the one reduce-scatter for free)
    want_sign = rlr or cfg.aggr == "sign" or cfg.telemetry == "full"
    rows = []
    total = None
    if cfg.aggr == "avg":
        w = sizes.astype(jnp.float32)
        if masked:
            w = jnp.where(mask_local, w, 0.0)
        total = jax.lax.psum(jnp.sum(w), ax)              # scalar psum
        rows.append(jnp.sum(flat * w[:, None], axis=0))
    if want_sign:
        rows.append(jnp.sum(jnp.sign(flat), axis=0))
    stacked = jnp.stack(rows)                             # [r, padded]
    # one reduce-scatter per bucket; both quantities share each collective
    scat = jnp.concatenate([
        jax.lax.psum_scatter(
            stacked[:, b * layout.bucket:(b + 1) * layout.bucket],
            ax, scatter_dimension=1, tiled=True)
        for b in range(layout.n_buckets)], axis=1)        # [r, device_len]

    sign_s = scat[-1] if want_sign else None
    if cfg.aggr == "avg":
        agg_s = scat[0] / total
    else:
        agg_s = jnp.sign(sign_s)
    if cfg.noise > 0:
        # generated per leaf from the identical key split as the leaf
        # path (gaussian_noise_like over the same tree structure), then
        # relayed out through the flat space — bitwise the same noise
        noise = gaussian_noise_like(params, noise_key,
                                    cfg.noise * cfg.clip)
        pos = jax.lax.axis_index(ax)
        agg_s = agg_s + buckets.device_shard(
            layout, buckets.flatten_tree(layout, noise), pos)
    if masked:
        agg_s = masking.guard_empty(agg_s, mask_full)
    if rlr:
        lr_s = rlr_from_sign_sum(sign_s, thr, slr)
    else:
        lr_s = None
    delta_s = (lr_s if lr_s is not None else slr) * agg_s

    # ONE all_gather carries the LR-scaled result — plus, under
    # telemetry, the unscaled aggregate (full: the cosine split needs
    # the replicated agg tree) and the tiny vote/flip stats vector
    # (basic/full: summed across devices after the gather), so telemetry
    # adds ZERO collectives here
    from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
        reputation as rep_mod)
    rep_on = rep_mod.reputation_on(cfg)
    payload = [delta_s]
    stats_len = 0
    if cfg.telemetry == "full":
        payload.append(agg_s)
    if rep_on:
        # the reputation lane needs the FULL signed vote replicated to
        # compare each local client block against — the sign-sum shard
        # rides the SAME result all_gather (a widened payload, never a
        # new collective; the *_rep CheckSpecs pin the unchanged plan)
        payload.append(sign_s)
    if cfg.telemetry != "off":
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            telemetry)
        pos = jax.lax.axis_index(ax)
        real = buckets.shard_coord_index(layout, pos) < layout.total
        stats = telemetry.shard_vote_stats(cfg, sign_s, real, lr_s,
                                           cfg.agents_per_round)
        if stats is not None:
            payload.append(stats)
            stats_len = stats.shape[0]
    gathered = jax.lax.all_gather(
        jnp.concatenate(payload) if len(payload) > 1 else payload[0],
        ax, axis=0, tiled=True).reshape(d, -1)

    dl = layout.device_len
    treedef = jax.tree_util.tree_structure(params)
    delta = buckets.unflatten(
        layout, buckets.gathered_to_flat(layout, gathered[:, :dl]),
        treedef)
    new_params = tree.astype(
        tree.map(lambda p, dlt: p + dlt, params, delta), jnp.float32)
    info = _BucketInfo(total_coords=layout.total)
    if cfg.telemetry == "full":
        info.agg = buckets.unflatten(
            layout, buckets.gathered_to_flat(layout, gathered[:, dl:2 * dl]),
            treedef)
    if rep_on:
        off = dl * (2 if cfg.telemetry == "full" else 1)
        sign_full = buckets.gathered_to_flat(layout,
                                             gathered[:, off:off + dl])
        real_full = jnp.arange(sign_full.shape[0]) < layout.total
        info.rep_agree = rep_mod.agree_rows_flat(flat, sign_full,
                                                 real_full, layout.total)
        # norm is local: flat's padding coordinates are explicit zeros,
        # so the row L2 over the padded block equals the real-coord norm
        info.rep_norm = rep_mod.norm_rows(flat)
    if stats_len:
        info.stats = jnp.sum(gathered[:, -stats_len:], axis=0)
    return new_params, info


def _bucket_async_contribs(cfg, params, updates, szs, mask_local, T_loc,
                           d, ax):
    """Buffered-async contributions through the bucketed collective shape
    (`--agg_mode buffered --agg_layout bucket`): the tick's per-level
    partial sums flatten into level-stacked rows of the bucket layout,
    ride ONE `psum_scatter` per bucket, and ONE `all_gather` reconstructs
    the globally-summed rows, which unflatten back into the contribution
    trees the shared replicated fold consumes (fl/buffered.fold_commit).

    Collective count: n_buckets reduce-scatters + 1 all_gather (+ the
    caller's packed scalar psum) — within the sync bucket plan's pinned
    budget (reduce-scatter 1, all_gather 1, psum 2 on the flagship). The
    gather carries `levels x quantities` rows instead of sync's one
    LR-scaled row; a real pod deployment would fold pending state on the
    scattered shard to keep wire bytes flat — simulation-side this keeps
    the buffer state layout-uniform with the leaf path (one checkpoint /
    carry shape per config), which the crash-exact drill depends on."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
        masking)
    avg = cfg.aggr == "avg"
    sgn = buffered.wants_sign(cfg)
    layout = buckets.layout_for_stacked(updates, d)
    if mask_local is not None:
        updates = masking.zero_masked(updates, mask_local)
    flat = buckets.flatten_stacked(layout, updates)      # [mb, padded]
    w = szs.astype(jnp.float32)
    sw = buffered._level_weights(cfg, T_loc)
    if sw is not None:
        w = w * sw
    sflat = jnp.sign(flat) if sgn else None
    avg_rows, sign_rows, cnt, wsum = [], [], [], []
    if T_loc is None:
        valid = (mask_local if mask_local is not None
                 else jnp.ones(w.shape, bool))
        wv = jnp.where(valid, w, 0.0)
        cnt.append(masking.count_f32(valid))
        if avg:
            wsum.append(jnp.sum(wv))
            avg_rows.append(jnp.sum(flat * wv[:, None], axis=0))
        if sgn:
            sign_rows.append(jnp.sum(sflat, axis=0))
    else:
        S = buffered.max_staleness(cfg)
        valid = (mask_local if mask_local is not None
                 else jnp.ones(T_loc.shape, bool))
        for s in range(S + 1):
            lvl = valid & (T_loc == s)
            wl = jnp.where(lvl, w, 0.0)
            cnt.append(masking.count_f32(lvl))
            if avg:
                wsum.append(jnp.sum(wl))
                avg_rows.append(jnp.sum(flat * wl[:, None], axis=0))
            if sgn:
                sign_rows.append(
                    jnp.sum(jnp.where(lvl[:, None], sflat, 0.0), axis=0))
    rows = jnp.stack(avg_rows + sign_rows)               # [R, padded]
    scat = jnp.concatenate([
        jax.lax.psum_scatter(
            rows[:, b * layout.bucket:(b + 1) * layout.bucket],
            ax, scatter_dimension=1, tiled=True)
        for b in range(layout.n_buckets)], axis=1)       # [R, device_len]
    gathered = jax.lax.all_gather(scat, ax, axis=0)      # [d, R, dl]
    treedef = jax.tree_util.tree_structure(params)

    def row_tree(r):
        return buckets.unflatten(
            layout, buckets.gathered_to_flat(layout, gathered[:, r, :]),
            treedef)

    n_lvl = len(avg_rows) if avg else len(sign_rows)
    trees = {}
    stack = jax.tree_util.tree_map
    if T_loc is None:
        if avg:
            trees["buf"] = row_tree(0)
        if sgn:
            trees["sign"] = row_tree(len(avg_rows))
        return (trees, cnt[0], wsum[0] if avg else None)
    if avg:
        trees["buf"] = stack(lambda *xs: jnp.stack(xs),
                             *[row_tree(s) for s in range(n_lvl)])
    if sgn:
        off = len(avg_rows)
        trees["sign"] = stack(lambda *xs: jnp.stack(xs),
                              *[row_tree(off + s) for s in range(n_lvl)])
    return (trees, jnp.stack(cnt), jnp.stack(wsum) if avg else None)


def _sharded_pallas_apply(params, updates, sizes, cfg):
    """Fused server step over the mesh: ONE Pallas pass per device over each
    local [m/d, leaf] update block (partial sign-sum + partial weighted sum,
    the leaf consumed in place — no ravel/concat staging, VERDICT r2 weak
    #4), psum of the partial trees, then an elementwise lr/apply that XLA
    fuses. HBM reads U exactly once per device — the single-device kernel's
    property (ops/pallas_rlr.py), composed with ICI collectives (XLA's
    collective-combiner batches the per-leaf psums)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.ops.pallas_rlr import (
        partial_vote_avg_flat)

    interp = jax.default_backend() != "tpu"
    w = sizes.astype(jnp.float32)
    total = jax.lax.psum(jnp.sum(w), AGENTS_AXIS)
    wn = w / total
    slr = cfg.effective_server_lr
    thr = float(cfg.robustLR_threshold)

    p_leaves, treedef = jax.tree_util.tree_flatten(params)
    u_leaves = jax.tree_util.tree_leaves(updates)
    new_leaves = []
    for p, u in zip(p_leaves, u_leaves, strict=True):
        mb = u.shape[0]
        ssum, wsum = partial_vote_avg_flat(u.reshape(mb, -1), wn,
                                           interpret=interp)
        ssum = jax.lax.psum(ssum, AGENTS_AXIS)
        if cfg.aggr == "sign":
            agg = jnp.sign(ssum)
        else:
            agg = jax.lax.psum(wsum, AGENTS_AXIS)
        if thr > 0:
            lr = jnp.where(jnp.abs(ssum) >= thr, slr, -slr)
        else:
            lr = slr
        new_leaves.append(
            (p.reshape(-1).astype(jnp.float32) + lr * agg).reshape(p.shape))
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _loss_and_health(cfg, losses, updates_local, new_params, mask_local, d):
    """The shard body's loss reduction, with the health-sentinel lanes
    packed into the SAME collective when the lane is on
    (health/sentinel.py): pmean's scalar psum becomes one [3] vector
    psum — a shape change, never a new collective (the ``*_hlth``
    CheckSpecs pin the unchanged plan at 1/8/16-way). Lane 0 is exactly
    pmean's arithmetic (psum/d), so the loss is bitwise the health-off
    value."""
    if not health_sentinel.health_on(cfg):
        return jax.lax.pmean(jnp.mean(losses), AGENTS_AXIS), {}
    with jax.named_scope("health"):
        lanes = jnp.concatenate(
            [jnp.mean(losses)[None],
             health_sentinel.local_lanes(updates_local, mask_local)])
        packed = jax.lax.psum(lanes, AGENTS_AXIS)
        extras = health_sentinel.finish_sharded(packed[1], packed[2],
                                                new_params)
    return packed[0] / d, extras


def _build_sharded_body(cfg, model, normalize, mesh, take_flags=None,
                        take_active=None, mt=False):
    """The shard_mapped round body shared by the per-round and chained fns.

    With faults — or full telemetry — configured the body takes a trailing
    replicated [m] bool `corrupt_flags` input (`take_flags`; single source
    fl/rounds.host_takes_flags, overridable to False for the chained host
    scan, which has no per-round flag channel). Under faults every device
    derives the IDENTICAL fault draw from the replicated fault key
    (faults/model.py — no collective needed to agree on who failed),
    slices its local block of the draw by mesh position, and the only
    added communication is one tiny all_gather of the per-device
    payload-validation bits.

    `take_active` adds the trailing replicated [m] bool availability mask
    input (default: on iff churn is configured). The cohort-sampled
    builders force it on — their active mask (shortfall padding) rides
    the same input whether or not churn is configured — still with ZERO
    added collectives (the mask arrives replicated).

    An in-jit attack strategy (attack/registry.py) scales this device's
    corrupt rows right after local training — the flags arrive replicated
    and the transform is elementwise, so the collective plan is untouched
    on the leaf AND bucketed layouts (pinned by the *_atk_* contract
    specs). A *scheduled* attack adds one more trailing replicated input:
    the scalar schedule gate, computed OUTSIDE shard_map from the round
    index (like the churn mask — the body never needs the index itself).

    ``mt`` (ISSUE 13, fl/tenancy.py) builds the tenant-pack variant: the
    body is `jax.vmap`ped over a leading [E] tenant axis INSIDE the
    shard_map, a trailing replicated TenantKnobs input carries the
    per-tenant scalar knobs, and the in-jit attack gate input is forced
    on whenever the strategy is in-jit (every tenant carries its own
    schedule window). Collectives under vmap batch over the tenant axis
    — one psum of an [E, ...] payload, not E psums — so the leaf AND
    bucket collective plans are unchanged by construction (pinned by the
    *_mt CheckSpecs at 1/8/16-way)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        _pallas_applicable, host_takes_flags)
    faults_on = cfg.faults_enabled
    # a quarantine set (health/monitor.py) rides the same replicated
    # availability-mask input as churn — the caller composes both masks
    # outside shard_map, so the body only sees one [m] bool channel
    churn_on = ((cfg.churn_enabled or health_sentinel.has_quarantine(cfg))
                if take_active is None else take_active)
    atk_on = attack_registry.in_jit(cfg)
    # tenant packs gate every in-jit attack per tenant (the trivial
    # schedule's traced gate is always-on); solo bodies only take the
    # gate input when a schedule actually needs the round index
    atk_sched = (atk_on if mt else attack_registry.needs_round(cfg))
    if take_flags is None:
        take_flags = host_takes_flags(cfg)
    if faults_on:
        from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
            model as fmodel)
    if churn_on:
        from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
            churn as churn_mod)
    # layout-dispatched client-block trainer (ISSUE 10): under
    # --train_layout megabatch each device folds ITS m/d-client block
    # into one [mb*bs, ...] megabatch — the fold happens inside the
    # shard, so the collective plan is untouched by construction
    train_block = make_block_trainer(model, cfg, normalize)
    m = cfg.agents_per_round
    d = mesh.devices.size
    assert m % d == 0, f"agents_per_round={m} not divisible by mesh size {d}"
    mb = m // d
    if cfg.agg_layout not in ("leaf", "bucket"):
        raise ValueError(f"agg_layout must be 'leaf' or 'bucket', got "
                         f"{cfg.agg_layout!r}")
    if cfg.agg_layout == "bucket" and cfg.diagnostics:
        # the scattered vote never materializes the full lr tree the
        # diagnostics extras (lr_flat) read; mixing layouts between snap
        # and off-snap rounds would silently compare different programs
        raise ValueError(
            "--agg_layout bucket does not support --diagnostics (the "
            "lr tree is never materialized on the scattered path); "
            "re-run with --agg_layout leaf — the per-leaf psum plan "
            "keeps the full lr tree and supports every diagnostic")

    is_async = buffered.is_buffered(cfg)

    def shard_body(carry, imgs, lbls, szs, keys, noise_key, *rest):
        # trailing replicated inputs, in order: [m] corrupt flags (faults /
        # full telemetry / in-jit attack), the [m] churn availability
        # mask, then the scalar attack-schedule gate — the caller
        # computes the lifecycle draw and the schedule gate OUTSIDE
        # shard_map (they need the sampled ids / round index) and they
        # arrive replicated, so neither adds a collective (analysis
        # *_churn / *_atk_* specs pin this).
        # Buffered mode: the lead argument is the (params, buffer-state)
        # carry — both replicated; the fold is elementwise post-psum
        # (fl/buffered.py), so the collective plan is the sync family's.
        params, astate = carry if is_async else (carry, None)
        # tenant-pack mode: the LAST trailing input is the per-tenant
        # TenantKnobs (scalars here — the tenant vmap wraps this body)
        knobs = rest[-1] if mt else None
        idx = 0
        corrupt_full = churn_full = atk_active = None
        if take_flags:
            corrupt_full = rest[idx]
            idx += 1
        if churn_on:
            churn_full = rest[idx]
            idx += 1
        if atk_sched:
            atk_active = rest[idx]
        mask_local = mask_full = draw = ep_local = None
        if faults_on or churn_on or atk_on:
            pos = jax.lax.axis_index(AGENTS_AXIS) * mb

            def local(v):
                return jax.lax.dynamic_slice_in_dim(v, pos, mb, 0)
        if faults_on:
            # replicated draw: every device computes the same [m] pattern
            draw = fmodel.sample_faults(cfg, fmodel.fault_key(noise_key), m,
                                        corrupt_full)
            if cfg.straggler_rate > 0:
                ep_local = local(draw.ep_budget)
        # chunking applies to the per-device agent block (m/d agents)
        with jax.named_scope("local_train"):
            updates, losses = train_block(params, imgs, lbls, szs, keys,
                                          cfg.agent_chunk,
                                          ep_budget=ep_local)
        if atk_on:
            # each device scales ITS corrupt rows — elementwise on the
            # local block, replicated inputs, zero collectives
            updates = attack_registry.apply_update_attack(
                cfg, updates, local(corrupt_full), atk_active,
                boost=None if knobs is None else knobs.attack_boost)
        if faults_on:
            from defending_against_backdoors_with_robust_learning_rate_tpu.faults import (
                masking)
            if cfg.corrupt_rate > 0:
                updates = fmodel.inject_corrupt(updates, local(draw.corrupt),
                                                cfg.corrupt_mode)
            valid = jax.lax.all_gather(
                fmodel.payload_valid(updates, cfg.payload_norm_cap),
                AGENTS_AXIS, axis=0, tiled=True)
            mask_full = draw.participate & valid
            mask_local = local(mask_full)
        if churn_full is not None:
            # the replicated lifecycle mask joins the participation mask
            # exactly like a dropout draw — away clients are excluded
            # arithmetically, no shape changes, no collective
            mask_full = (churn_full if mask_full is None
                         else mask_full & churn_full)
            mask_local = local(mask_full)
        if is_async:
            # buffered-async tail: this tick's per-level contributions
            # ride the sync plan's collectives (per-leaf psums on the
            # leaf layout, per-bucket reduce-scatter + one all_gather on
            # the bucket layout; the tiny count/weight/loss lanes pack
            # into ONE vector psum), then the shared replicated fold
            # advances the carried buffer (fl/buffered.fold_commit —
            # zero collectives of its own, pinned by the *_async specs)
            with jax.named_scope("buffered_fold"):
                T_full = buffered.latency(
                    cfg, noise_key,
                    draw.straggler if draw is not None else None)
                T_loc = local(T_full) if T_full is not None else None
                loss_local = jnp.mean(losses)
                if _bucket_applicable(cfg):
                    g_trees, cnt_l, wsum_l = _bucket_async_contribs(
                        cfg, params, updates, szs, mask_local, T_loc, d,
                        AGENTS_AXIS)
                else:
                    c = buffered.tick_contributions(cfg, updates, szs,
                                                    mask_local, T_loc)
                    g_trees = {
                        k: tree.map(
                            lambda x: jax.lax.psum(x, AGENTS_AXIS), c[k])
                        for k in ("buf", "sign") if k in c}
                    cnt_l, wsum_l = c["cnt"], c.get("wsum")
                lanes = [jnp.atleast_1d(cnt_l)]
                if wsum_l is not None:
                    lanes.append(jnp.atleast_1d(wsum_l))
                lanes.append(loss_local[None])
                h_on = health_sentinel.health_on(cfg)
                if h_on:
                    # the health-sentinel lanes ride the SAME packed
                    # psum (health/sentinel.py — zero added collectives)
                    lanes.append(health_sentinel.local_lanes(updates,
                                                             mask_local))
                packed = jax.lax.psum(jnp.concatenate(lanes), AGENTS_AXIS)
                n1 = lanes[0].shape[0]
                contribs = dict(g_trees)
                contribs["cnt"] = packed[:n1] if n1 > 1 else packed[0]
                if wsum_l is not None:
                    contribs["wsum"] = (packed[n1:2 * n1] if n1 > 1
                                        else packed[1])
                # the loss lane rides the packed psum: psum/d is exactly
                # pmean's arithmetic, so the budget stays the sync plan's
                loss = (packed[-3] if h_on else packed[-1]) / d
                new_params, new_astate, lr, agg, a_extras, vote_sign = \
                    buffered.fold_commit(cfg, params, astate, contribs,
                                         noise_key, m, knobs=knobs)
            extras = dict(a_extras)
            if h_on:
                with jax.named_scope("health"):
                    extras.update(health_sentinel.finish_sharded(
                        packed[-2], packed[-1], new_params))
            if faults_on:
                extras.update(fmodel.fault_scalars(draw, mask_full))
                if churn_full is not None and cfg.churn_enabled:
                    extras["churn_away"] = churn_mod.churn_away(churn_full)
            elif churn_full is not None and cfg.churn_enabled:
                extras.update(churn_mod.churn_only_scalars(churn_full,
                                                           mask_full))
            if cfg.telemetry != "off":
                from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
                    telemetry)
                extras.update(telemetry.compute_sharded(
                    cfg, updates,
                    lr if cfg.robustLR_threshold > 0 else None, agg,
                    AGENTS_AXIS, mask_local=mask_local,
                    mask_full=mask_full, corrupt_full=corrupt_full,
                    sign_sums=vote_sign,
                    vote_range=buffered.vote_range(cfg)))
            from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
                reputation as rep_mod)
            if rep_mod.reputation_on(cfg):
                # agreement vs the BUFFER's replicated accumulated sign
                # vote (fold_commit's vote_sign) on the local block —
                # elementwise; shard_map's P(AGENTS_AXIS) out_spec
                # stitches the [m] row with zero collectives
                extras["rep_agree"] = rep_mod.agree_rows(
                    updates, vote_sign, mask=mask_local)
                extras["rep_norm"] = rep_mod.norm_rows(updates,
                                                       mask=mask_local)
            return (new_params, new_astate), loss, extras
        if _pallas_applicable(cfg):
            new_params = _sharded_pallas_apply(params, updates, szs, cfg)
            loss, hextras = _loss_and_health(cfg, losses, updates,
                                             new_params, None, d)
            return new_params, loss, hextras
        sign_sums = None
        bucket_info = None
        with jax.named_scope("aggregate_rlr"):
            if _bucket_applicable(cfg):
                # pod-shape plan: per-bucket reduce-scatter + one
                # all_gather of the LR-scaled result, vote + average on
                # the scattered shard (parallel/buckets.py)
                lr = agg = None
                new_params, bucket_info = _bucketed_apply(
                    params, updates, szs, cfg, noise_key, d,
                    mask_local, mask_full, knobs=knobs)
            elif cfg.robustLR_threshold > 0 and cfg.aggr == "sign":
                # vote + aggregate share one sign-sum psum per leaf (the
                # CSE XLA was measured not to do — see _sharded_sign_shared)
                lr, agg, sign_sums = _sharded_sign_shared(
                    updates, cfg, noise_key, mask_local, mask_full,
                    knobs=knobs)
                new_params = apply_aggregate(params, lr, agg)
            else:
                if cfg.robustLR_threshold > 0:
                    lr, sign_sums = _sharded_robust_lr(updates, cfg,
                                                       mask_local,
                                                       mask_full,
                                                       knobs=knobs)
                else:
                    lr = (cfg.effective_server_lr if knobs is None
                          else knobs.server_lr)
                agg_out = {}
                agg = _sharded_aggregate(updates, szs, cfg, d, noise_key,
                                         mask_local, mask_full,
                                         out=agg_out)
                if sign_sums is None:
                    # thresholdless sign aggregation: the sign branch's
                    # own psum results, re-read for the reputation lane
                    sign_sums = agg_out.get("sign_sums")
                new_params = apply_aggregate(params, lr, agg)
        loss, extras = _loss_and_health(cfg, losses, updates, new_params,
                                        mask_local, d)
        if faults_on:
            extras.update(fmodel.fault_scalars(draw, mask_full))
            if churn_full is not None and cfg.churn_enabled:
                extras["churn_away"] = churn_mod.churn_away(churn_full)
        elif churn_full is not None and cfg.churn_enabled:
            # emission gated on churn actually being configured: the
            # cohort builders force the active INPUT on (shortfall
            # padding joins the mask) without growing churn series
            extras.update(churn_mod.churn_only_scalars(churn_full,
                                                       mask_full))
        if cfg.telemetry != "off":
            from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
                telemetry)
            if bucket_info is not None:
                # the vote/flip stats and (full) the aggregate tree rode
                # the bucketed result all_gather — zero extra psums, the
                # leaf path's sign_sums sharing discipline on the new
                # layout
                extras.update(telemetry.compute_sharded_bucket(
                    cfg, updates, bucket_info, AGENTS_AXIS,
                    mask_local=mask_local, mask_full=mask_full,
                    corrupt_full=corrupt_full))
            else:
                # sign_sums: the vote's per-leaf psum results, so full
                # telemetry's margin histogram re-reads the existing
                # collective instead of duplicating it per leaf
                extras.update(telemetry.compute_sharded(
                    cfg, updates,
                    lr if cfg.robustLR_threshold > 0 else None, agg,
                    AGENTS_AXIS, mask_local=mask_local, mask_full=mask_full,
                    corrupt_full=corrupt_full, sign_sums=sign_sums))
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            reputation as rep_mod)
        if rep_mod.reputation_on(cfg):
            if bucket_info is not None:
                # computed inside _bucketed_apply against the full vote
                # whose shard rode the existing result all_gather
                rep_local = bucket_info.rep_agree
                rep_nrm = bucket_info.rep_norm
                if mask_local is not None:
                    rep_local = jnp.where(mask_local, rep_local,
                                          rep_mod.MASKED)
                    rep_nrm = jnp.where(mask_local, rep_nrm,
                                        rep_mod.MASKED)
            else:
                # leaf layout: the vote's replicated sign-sum psums,
                # re-read — local [m/d] block, stitched to [m] by the
                # P(AGENTS_AXIS) out_spec, zero collectives
                rep_local = rep_mod.agree_rows(updates, sign_sums,
                                               mask=mask_local)
                rep_nrm = rep_mod.norm_rows(updates, mask=mask_local)
            extras["rep_agree"] = rep_local
            extras["rep_norm"] = rep_nrm
        if cfg.diagnostics:
            from defending_against_backdoors_with_robust_learning_rate_tpu.fl.diagnostics import (
                per_agent_norms)
            from jax.flatten_util import ravel_pytree
            extras["agent_norms"] = jax.lax.all_gather(
                per_agent_norms(updates), AGENTS_AXIS, axis=0, tiled=True)
            if cfg.robustLR_threshold > 0:
                extras["lr_flat"] = ravel_pytree(lr)[0]
        return new_params, loss, extras

    extras_specs = {}
    if is_async:
        extras_specs.update({k: P() for k in buffered.ASYNC_INFO_KEYS})
    if faults_on or (churn_on and cfg.churn_enabled):
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
            FAULT_INFO_KEYS)
        extras_specs.update({k: P() for k in FAULT_INFO_KEYS})
    if churn_on and cfg.churn_enabled:
        extras_specs["churn_away"] = P()
    if cfg.telemetry != "off":
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs.telemetry import (
            telemetry_keys)
        extras_specs.update({k: P() for k in telemetry_keys(cfg)})
    if cfg.diagnostics:
        extras_specs["agent_norms"] = P()
        if cfg.robustLR_threshold > 0:
            extras_specs["lr_flat"] = P()
    # health-sentinel scalars (health/sentinel.py): replicated outputs
    # (the psummed lanes + the params-finite bit); the sharded key set
    # excludes the [m] suspect vector by construction
    extras_specs.update({k: P() for k in
                         health_sentinel.health_keys(cfg, sharded=True)})
    # reputation lane (obs/reputation.py): each device emits its LOCAL
    # [m/d] rep_agree + rep_norm blocks ([E, m/d] in a tenant pack) and
    # shard_map's out_spec stitches the full [m] rows — the free
    # materialization the health lane's hlth_agent_bad could not afford
    # (its value is replicated; the rep lanes are sharded by construction)
    from defending_against_backdoors_with_robust_learning_rate_tpu.obs.reputation import (
        rep_keys)
    extras_specs.update({k: (P(None, AGENTS_AXIS) if mt
                             else P(AGENTS_AXIS)) for k in rep_keys(cfg)})

    if mt:
        # tenant axis INSIDE the shard: every input grows a leading [E]
        # (the data stacks shard the AGENTS axis at position 1), the
        # knobs ride as one more replicated input, and jax.vmap batches
        # the body — collectives batch over the tenant axis instead of
        # multiplying, so the pinned plan is unchanged by construction
        agents = P(None, AGENTS_AXIS)
        in_specs = (P(), agents, agents, agents, agents, P()) \
            + ((P(),) if take_flags else ()) \
            + ((P(),) if churn_on else ()) \
            + ((P(),) if atk_sched else ()) + (P(),)
        return shard_map(
            jax.vmap(shard_body), mesh=mesh,
            in_specs=in_specs,
            out_specs=(P(), P(), extras_specs),
            check_vma=False)
    in_specs = (P(), P(AGENTS_AXIS), P(AGENTS_AXIS), P(AGENTS_AXIS),
                P(AGENTS_AXIS), P()) + ((P(),) if take_flags else ()) \
        + ((P(),) if churn_on else ()) + ((P(),) if atk_sched else ())
    return shard_map(
        shard_body, mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(), P(), extras_specs),
        check_vma=False)


def _make_sample_step(cfg, model, normalize, mesh):
    """Shared sharded sample-and-step fn: step(params, key, images, labels,
    sizes).

    Samples the round's m agents, gathers their shards in-jit (partitioned
    over the mesh by shard_map's in_specs), and runs the shard_mapped body.
    Both the per-round and chained fns wrap THIS fn — chained execution
    stays bit-identical to per-round dispatch. The dataset stacks are jit
    ARGUMENTS, not closure captures (closure arrays get inlined into the
    lowered HLO as dense constants — see fl/rounds._make_sample_step)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        host_takes_flags, step_takes_round)
    sharded = _build_sharded_body(cfg, model, normalize, mesh)
    K, m = cfg.num_agents, cfg.agents_per_round
    want_flags = host_takes_flags(cfg)

    def body(params, key, rnd, images, labels, sizes):
        k_sample, k_train, k_noise = jax.random.split(key, 3)
        with jax.named_scope("sample_gather"):
            sampled = jax.random.permutation(k_sample, K)[:m]
            imgs = jnp.take(images, sampled, axis=0)
            lbls = jnp.take(labels, sampled, axis=0)
            szs = jnp.take(sizes, sampled, axis=0)
        agent_keys = jax.random.split(k_train, m)
        extra = ((sampled < cfg.num_corrupt,) if want_flags else ())
        active = None
        if cfg.churn_enabled:
            from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
                churn as churn_mod)
            # lifecycle draw computed OUTSIDE shard_map (it needs the
            # sampled ids + round index); enters the body replicated
            with jax.named_scope("churn_mask"):
                active = churn_mod.active_slots(cfg, sampled, rnd)
        if health_sentinel.has_quarantine(cfg):
            # quarantine membership composes into the same replicated
            # availability input (health/monitor.py QUARANTINE rung)
            qmask = health_sentinel.quarantine_mask(cfg, sampled)
            active = qmask if active is None else active & qmask
        if active is not None:
            extra = extra + (active,)
        if attack_registry.needs_round(cfg):
            # schedule gate computed OUTSIDE shard_map from the round
            # index; enters the body as a replicated scalar
            extra = extra + (attack_registry.schedule_active(cfg, rnd),)
        new_params, train_loss, extras = sharded(params, imgs, lbls, szs,
                                                 agent_keys, k_noise, *extra)
        return new_params, {"train_loss": train_loss, "sampled": sampled,
                            **extras}

    if step_takes_round(cfg):
        def step(params, key, rnd, images, labels, sizes):
            return body(params, key, rnd, images, labels, sizes)
        step.takes_round = True
        return step

    def step(params, key, images, labels, sizes):
        return body(params, key, jnp.int32(0), images, labels, sizes)
    step.takes_round = False
    return step


def make_sharded_round_fn(cfg, model, normalize, mesh,
                          images, labels, sizes):
    """Device-resident sharded round fn: round(params, key) -> (params, info).

    images/labels/sizes: full K-agent stacked arrays. The per-round gather of
    the m sampled shards happens in-jit; the gathered [m, ...] arrays are
    partitioned over the mesh by shard_map's in_specs.
    """
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    return bind_data(jax.jit(_make_sample_step(cfg, model, normalize, mesh)),
                     (images, labels, sizes),
                     family=("round_sharded_diag" if cfg.diagnostics
                             else "round_sharded"
                             + compile_cache.family_suffix(cfg)))


def make_sharded_round_fn_mt(cfg, model, normalize, mesh,
                             images, labels, sizes):
    """Tenant-pack sharded round fn (ISSUE 13, fl/tenancy.py):
    round(params_E, keys_E, rnd, knobs) -> (params_E, info) with every
    carried array [E]-stacked and the tenant axis folded INSIDE the
    shard (each device trains its m/d-agent block for all E tenants; the
    per-leaf psums / bucketed reduce-scatters batch over the tenant axis
    instead of multiplying — the *_mt CheckSpecs pin the unchanged plan
    at 1/8/16-way). Per-tenant sampling, corrupt flags, churn masks and
    schedule gates are computed OUTSIDE shard_map from the per-tenant
    keys/knobs and enter replicated, the solo body's exact discipline.
    Buffered mode carries (params_E, astate_E) — both [E]-stacked,
    replicated across the mesh like the solo sharded-async carry — and
    each tenant runs on its EFFECTIVE clock rnd + knobs.rnd_offset (the
    scheduler's backfill skew; 0 on the FIFO path)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry, schedule as attack_schedule)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        host_takes_flags)
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    sharded = _build_sharded_body(cfg, model, normalize, mesh, mt=True)
    K, m = cfg.num_agents, cfg.agents_per_round
    want_flags = host_takes_flags(cfg)
    atk_gated = attack_registry.in_jit(cfg)

    def step(carry_E, keys_E, rnd, knobs, images, labels, sizes):
        rnd_E = rnd + knobs.rnd_offset  # [E] effective round indices

        def sample(key):
            k_sample, k_train, k_noise = jax.random.split(key, 3)
            sampled = jax.random.permutation(k_sample, K)[:m]
            return sampled, jax.random.split(k_train, m), k_noise

        with jax.named_scope("sample_gather"):
            sampled_E, agent_keys_E, k_noise_E = jax.vmap(sample)(keys_E)
            imgs = jnp.take(images, sampled_E, axis=0)   # [E, m, ...]
            lbls = jnp.take(labels, sampled_E, axis=0)
            szs = jnp.take(sizes, sampled_E, axis=0)
        extra = ()
        if want_flags:
            extra += (sampled_E < cfg.num_corrupt,)
        active_E = None
        if cfg.churn_enabled:
            from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
                churn as churn_mod)
            with jax.named_scope("churn_mask"):
                active_E = jax.vmap(
                    lambda s, r: churn_mod.active_slots(cfg, s, r))(
                        sampled_E, rnd_E)
        if health_sentinel.has_quarantine(cfg):
            q_E = jax.vmap(
                lambda s: health_sentinel.quarantine_mask(cfg, s))(
                    sampled_E)
            active_E = q_E if active_E is None else active_E & q_E
        if active_E is not None:
            extra += (active_E,)
        if atk_gated:
            # per-tenant schedule gates from the traced knob triples —
            # replicated [E] input, zero collectives (the solo gate
            # idiom); the gate reads each tenant's effective clock
            extra += (attack_schedule.active_traced(
                knobs.attack_start, knobs.attack_stop,
                knobs.attack_every, rnd_E),)
        new_carry, train_loss, extras = sharded(
            carry_E, imgs, lbls, szs, agent_keys_E, k_noise_E,
            *extra, knobs)
        return new_carry, {"train_loss": train_loss,
                           "sampled": sampled_E, **extras}

    jitted = jax.jit(step)

    def bound(params_E, keys_E, rnd, knobs):
        return jitted(params_E, keys_E, rnd, knobs, images, labels, sizes)

    bound.jitted, bound.data = jitted, (images, labels, sizes)
    bound.family = "round_sharded" + compile_cache.family_suffix(cfg)
    return bound


def make_sharded_host_step(cfg, model, normalize, mesh, take_flags=None):
    """Unjitted sharded host step(params, key, imgs, lbls, sizes) — shared
    body of the per-round and chained sharded host fns. Key derivation
    (split into k_train/k_noise, then m agent keys) matches
    fl/rounds.make_host_step bit-for-bit, so the sharded and single-device
    host paths are comparable round-for-round."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        host_takes_flags)
    if cfg.churn_enabled:
        # same contract as fl/rounds.make_host_step: the host-sampled
        # program never sees the sampled ids the lifecycle draw hashes
        raise ValueError(
            "client churn (--churn_available < 1) is not supported in "
            "host-sampled mode; run device-resident (--host_sampled off)")
    if buffered.is_buffered(cfg):
        # same contract as the single-device host step (fl/rounds)
        raise ValueError(
            "--agg_mode buffered is not supported in host-sampled mode; "
            "run device-resident (--host_sampled off) or cohort-sampled "
            "(--cohort_sampled on)")
    if attack_registry.needs_round(cfg):
        # same contract as the single-device host step: no round channel
        # for the schedule gate (fl/rounds.make_host_step)
        raise ValueError(
            f"--attack {cfg.attack} with a schedule is not supported in "
            f"host-sampled mode; run device-resident (--host_sampled "
            f"off) or cohort-sampled")
    if take_flags is False and attack_registry.in_jit(cfg):
        raise ValueError(
            f"--attack {cfg.attack} transforms updates in-jit and needs "
            f"the corrupt-slot flags, which the chained host scan does "
            f"not carry — the driver must dispatch host-sampled attack "
            f"rounds unchained (train.py disables --chain here)")
    if take_flags is None:
        take_flags = host_takes_flags(cfg)
    sharded = _build_sharded_body(cfg, model, normalize, mesh,
                                  take_flags=take_flags)
    m = cfg.agents_per_round

    if take_flags:
        # faults / full telemetry: the driver passes the sampled slots'
        # corrupt flags (it owns the host-side id sampling) — see
        # fl/rounds.make_host_step
        def step(params, key, imgs, lbls, szs, corrupt_flags):
            k_train, k_noise = jax.random.split(key)
            agent_keys = jax.random.split(k_train, m)
            new_params, train_loss, extras = sharded(
                params, imgs, lbls, szs, agent_keys, k_noise, corrupt_flags)
            return new_params, {"train_loss": train_loss, **extras}
        return step

    def step(params, key, imgs, lbls, szs):
        k_train, k_noise = jax.random.split(key)
        agent_keys = jax.random.split(k_train, m)
        new_params, train_loss, extras = sharded(params, imgs, lbls, szs,
                                                 agent_keys, k_noise)
        return new_params, {"train_loss": train_loss, **extras}

    return step


def make_sharded_round_fn_host(cfg, model, normalize, mesh):
    """Host-sampled sharded round fn: round(params, key, imgs, lbls, sizes).

    The fedemnist-scale path (3383 users, ref runner.sh:34-38): the full
    agent stack exceeds the device-resident budget, so the driver gathers the
    round's m sampled shards host-side and THIS fn partitions them over the
    `agents` mesh (m/d per device) before the shard_mapped body runs."""
    return jax.jit(make_sharded_host_step(cfg, model, normalize, mesh))


def make_sharded_chained_round_fn_host(cfg, model, normalize, mesh):
    """Chained sharded host rounds: chained(params, base_key, round_ids,
    imgs, lbls, sizes) over [chain, m, ...] blocks sharded on the m axis
    (P(None, agents)); `lax.scan` slices one round's [m, ...] stack per step
    and runs the shard_mapped body — collectives inside the scan, one XLA
    program per block."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        make_chained_host)
    return make_chained_host(
        make_sharded_host_step(cfg.replace(diagnostics=False), model,
                               normalize, mesh, take_flags=False))


# ----------------------------------------------------------- cohort path ---

def make_sharded_cohort_step(cfg, model, normalize, mesh):
    """Unjitted sharded cohort step(params, key, rnd, imgs, lbls, szs):
    the cohort-sampled round (fl/rounds.make_cohort_step) over the agents
    mesh. The seeded cohort draw runs OUTSIDE shard_map (replicated — it
    needs no per-shard data) and its ids/active/corrupt-flags enter the
    body as replicated [m] inputs, so the whole population/cohort split
    adds ZERO collectives to the documented communication plan (pinned by
    the *_cohort specs in analysis/contracts.py)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
        registry as attack_registry)
    from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
        cohort as cohort_mod)
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        host_takes_flags)
    want_flags = host_takes_flags(cfg)
    sharded = _build_sharded_body(cfg, model, normalize, mesh,
                                  take_flags=want_flags, take_active=True)
    m = cfg.agents_per_round

    def step(params, key, rnd, imgs, lbls, szs):
        with jax.named_scope("cohort_sample"):
            ids, active = cohort_mod.sample_cohort(cfg, rnd)
        if health_sentinel.has_quarantine(cfg):
            # quarantined members leave through the active mask, the
            # shortfall-padding / churn-absence protocol (fl/rounds
            # make_cohort_step does the same on the single-device path)
            active = active & health_sentinel.quarantine_mask(cfg, ids)
        k_train, k_noise = jax.random.split(key)
        agent_keys = jax.random.split(k_train, m)
        extra = (((ids < cfg.num_corrupt) & active,) if want_flags else ())
        extra = extra + (active,)
        if attack_registry.needs_round(cfg):
            extra = extra + (attack_registry.schedule_active(cfg, rnd),)
        new_params, train_loss, extras = sharded(params, imgs, lbls, szs,
                                                 agent_keys, k_noise, *extra)
        return new_params, {"train_loss": train_loss, "sampled": ids,
                            **extras}

    step.takes_round = True
    return step


def make_sharded_cohort_round_fn(cfg, model, normalize, mesh):
    """Sharded cohort round fn: round(params, key, rnd, imgs, lbls, szs) —
    the bank-gathered [m, ...] cohort stacks partitioned over the agents
    mesh (m/d per device), cohort ids recomputed in-program."""
    return jax.jit(make_sharded_cohort_step(cfg, model, normalize, mesh))


def make_sharded_chained_cohort_round_fn(cfg, model, normalize, mesh):
    """Chained sharded cohort rounds over [chain, m, ...] blocks sharded on
    the m axis; the scanned round index re-derives each round's cohort
    ids, flags and churn mask in-program (fl/rounds.make_chained_host)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
        make_chained_host)
    return make_chained_host(
        make_sharded_cohort_step(cfg.replace(diagnostics=False), model,
                                 normalize, mesh))


def make_sharded_chained_round_fn(cfg, model, normalize, mesh,
                                  images, labels, sizes):
    """Chained sharded rounds: chained(params, base_key, round_ids).

    `lax.scan` over a block of rounds with the shard_mapped round body inside
    — one XLA program per block, collectives included; key derivation
    (`fold_in(base_key, r)`) matches the driver loop bit-for-bit (see
    fl/rounds.make_chained_round_fn). Diagnostics extras unsupported."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
        compile_cache)
    plain = cfg.replace(diagnostics=False)
    return make_chained(_make_sample_step(plain, model, normalize, mesh),
                        (images, labels, sizes),
                        family="chained_sharded"
                        + compile_cache.family_suffix(plain))
