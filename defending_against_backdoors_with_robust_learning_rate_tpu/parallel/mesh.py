"""Device-mesh construction for the `agents` axis.

The reference's only multi-device story is backgrounding independent
processes pinned to cuda:0/cuda:1 (src/runner.sh:12-18; SURVEY.md 2.2). The
TPU build owns one 1-D mesh with a named axis ``"agents"``: the m sampled
clients of a round are blocked m/d per device, local training runs under
``shard_map``, and aggregation is psum/all_gather collectives over ICI.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

AGENTS_AXIS = "agents"


def pick_agent_mesh_size(requested: int, agents_per_round: int,
                         n_devices: int | None = None) -> int:
    """Largest device count <= min(requested or all, available) that divides
    the per-round participant count (blocking policy, SURVEY.md 7.2.5 — e.g.
    m=10 on a v5e-8 slice uses 5 devices, 2 agents per device)."""
    avail = n_devices if n_devices is not None else len(jax.devices())
    cap = min(requested if requested > 0 else avail, avail)
    for d in range(cap, 0, -1):
        if agents_per_round % d == 0:
            return d
    return 1


def make_mesh(n_devices: int = 0) -> Mesh:
    devs = jax.devices()
    n = n_devices if n_devices > 0 else len(devs)
    return Mesh(np.array(devs[:n]), (AGENTS_AXIS,))
