from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    pick_agent_mesh_size,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (  # noqa: F401
    make_sharded_round_fn,
)
