from defending_against_backdoors_with_robust_learning_rate_tpu.models.cnn import (  # noqa: F401
    CNN_MNIST,
    CNN_CIFAR,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.models.resnet import (  # noqa: F401
    ResNet9,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (  # noqa: F401
    get_model,
    init_params,
    param_count,
)
