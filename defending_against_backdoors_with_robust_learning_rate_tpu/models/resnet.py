"""ResNet-9 for the BASELINE.json north-star configs[3-4] (cifar10 at scale).

The reference has no ResNet (its CIFAR model is a 3-conv CNN, src/models.py:
33-58); BASELINE.json explicitly asks for "cifar10 ResNet-9" (SURVEY.md
2.3.11), so this is a framework extension. Design choices, TPU/FL-native:

- GroupNorm instead of BatchNorm: the reference's models have no BN (so the
  flat-parameter-vector currency carries no running stats); GroupNorm keeps
  that property — all state is parameters, so FedAvg/comed/sign/RLR apply
  unchanged to every tensor — and avoids cross-client BN-statistic leakage.
- NHWC, 3x3 SAME convs, classic DAWNBench ResNet-9 topology:
  conv(64) -> conv(128)+pool -> residual(128) -> conv(256)+pool
  -> conv(512)+pool -> residual(512) -> global maxpool -> fc, output scaled
  by 0.125 (the standard ResNet-9 logit scale).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class ConvGN(nn.Module):
    width: int
    pool: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=min(32, self.width),
                         dtype=self.dtype)(x)
        x = nn.relu(x)
        if self.pool:
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x


class Residual(nn.Module):
    width: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = ConvGN(self.width, dtype=self.dtype)(x)
        y = ConvGN(self.width, dtype=self.dtype)(y)
        return x + y


class ResNet9(nn.Module):
    n_classes: int = 10
    dtype: Any = jnp.float32
    # blockwise rematerialization (jax.checkpoint via nn.remat): backward
    # recomputes each block's activations instead of stashing them — the
    # standard TPU trade of FLOPs for HBM. Exact (bitwise-equal grads);
    # needed when many agents' ResNet batches are vmapped on one chip
    # (40 agents x bs 256 stashes ~19 GB un-remated, > v5e's 16 GB HBM).
    remat: bool = False

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        Conv = nn.remat(ConvGN) if self.remat else ConvGN
        Res = nn.remat(Residual) if self.remat else Residual
        # explicit names: nn.remat prefixes auto-generated module names
        # ("CheckpointConvGN_0"), which would fork the param tree between
        # remat on/off — same tree means checkpoints interchange freely
        x = x.astype(self.dtype)
        x = Conv(64, dtype=self.dtype, name="ConvGN_0")(x)
        x = Conv(128, pool=True, dtype=self.dtype, name="ConvGN_1")(x)
        x = Res(128, dtype=self.dtype, name="Residual_0")(x)
        x = Conv(256, pool=True, dtype=self.dtype, name="ConvGN_2")(x)
        x = Conv(512, pool=True, dtype=self.dtype, name="ConvGN_3")(x)
        x = Res(512, dtype=self.dtype, name="Residual_1")(x)
        x = jnp.max(x, axis=(1, 2))          # global max pool
        x = nn.Dense(self.n_classes, dtype=self.dtype)(x)
        return (x * 0.125).astype(jnp.float32)
