"""ResNet-9 for the BASELINE.json north-star configs[3-4] (cifar10 at scale).

The reference has no ResNet (its CIFAR model is a 3-conv CNN, src/models.py:
33-58); BASELINE.json explicitly asks for "cifar10 ResNet-9" (SURVEY.md
2.3.11), so this is a framework extension. Design choices, TPU/FL-native:

- GroupNorm instead of BatchNorm: the reference's models have no BN (so the
  flat-parameter-vector currency carries no running stats); GroupNorm keeps
  that property — all state is parameters, so FedAvg/comed/sign/RLR apply
  unchanged to every tensor — and avoids cross-client BN-statistic leakage.
- NHWC, 3x3 SAME convs, classic DAWNBench ResNet-9 topology:
  conv(64) -> conv(128)+pool -> residual(128) -> conv(256)+pool
  -> conv(512)+pool -> residual(512) -> global maxpool -> fc, output scaled
  by 0.125 (the standard ResNet-9 logit scale).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name


class ConvGN(nn.Module):
    width: int
    pool: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.width, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        # names the MXU output for the selective remat policy below; a
        # transparent no-op under no remat / full blockwise remat
        x = checkpoint_name(x, "conv_out")
        x = nn.GroupNorm(num_groups=min(32, self.width),
                         dtype=self.dtype)(x)
        x = nn.relu(x)
        if self.pool:
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x


class Residual(nn.Module):
    width: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        y = ConvGN(self.width, dtype=self.dtype)(x)
        y = ConvGN(self.width, dtype=self.dtype)(y)
        return x + y


class ResNet9(nn.Module):
    n_classes: int = 10
    dtype: Any = jnp.float32
    # blockwise rematerialization (jax.checkpoint via nn.remat): backward
    # recomputes each block's activations instead of stashing them — the
    # standard TPU trade of FLOPs for HBM. Exact (bitwise-equal grads);
    # needed when many agents' ResNet batches are vmapped on one chip
    # (40 agents x bs 256 stashes ~19 GB un-remated, > v5e's 16 GB HBM).
    remat: bool = False
    # remat_policy (active only when remat=True):
    #   "block" — save block inputs only, recompute EVERYTHING in backward
    #             (the r4-measured +33.3% forward-recompute tax)
    #   "conv"  — selective: additionally save the named conv (MXU) outputs
    #             and recompute only the cheap elementwise tail (GN, relu,
    #             pool) — ~3x the saved bytes of "block", none of the conv
    #             recompute FLOPs (VERDICT r4 next #4)
    remat_policy: str = "block"

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        if self.remat and self.remat_policy == "conv":
            pol = jax.checkpoint_policies.save_only_these_names("conv_out")
            Conv = nn.remat(ConvGN, policy=pol)
            Res = nn.remat(Residual, policy=pol)
        elif self.remat:
            Conv, Res = nn.remat(ConvGN), nn.remat(Residual)
        else:
            Conv, Res = ConvGN, Residual
        # explicit names: nn.remat prefixes auto-generated module names
        # ("CheckpointConvGN_0"), which would fork the param tree between
        # remat on/off — same tree means checkpoints interchange freely
        x = x.astype(self.dtype)
        x = Conv(64, dtype=self.dtype, name="ConvGN_0")(x)
        x = Conv(128, pool=True, dtype=self.dtype, name="ConvGN_1")(x)
        x = Res(128, dtype=self.dtype, name="Residual_0")(x)
        x = Conv(256, pool=True, dtype=self.dtype, name="ConvGN_2")(x)
        x = Conv(512, pool=True, dtype=self.dtype, name="ConvGN_3")(x)
        x = Res(512, dtype=self.dtype, name="Residual_1")(x)
        x = jnp.max(x, axis=(1, 2))          # global max pool
        x = nn.Dense(self.n_classes, dtype=self.dtype)(x)
        return (x * 0.125).astype(jnp.float32)
