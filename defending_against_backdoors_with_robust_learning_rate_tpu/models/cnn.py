"""Faithful Flax re-expressions of the reference CNNs (src/models.py:11-58).

Shape parity (VALID convs, 2x2 maxpool, same widths/dropout rate):

CNN_MNIST (src/models.py:11-31), ~1.2M params:
  28x28x1 -conv3x3(32)-> 26 -conv3x3(64)-> 24 -pool2-> 12 -> flatten 9216
  -> dropout(.5) -> fc 128 -> relu -> dropout(.5) -> fc 10

CNN_CIFAR (src/models.py:33-58), ~0.9M params:
  32x32x3 -conv(64)+pool-> 15 -conv(128)+pool-> 6 -conv(256)+pool-> 2
  -> flatten 1024 -> dropout -> fc 128 -> relu -> dropout -> fc 256 -> relu
  -> dropout -> fc 10
  (the reference's `fc1 = Linear(64*4*4, 128)` coincidentally equals the true
  flatten size 256*2*2 = 1024, SURVEY.md C14 quirk)

Differences, deliberate: NHWC layout (TPU-native) so the flatten ordering is
HWC-major rather than torch's CHW-major — identical parameter counts and
function class, not bit-identical weight layout. The reference's `Dropout2d`
on already-flattened 2D tensors degenerates to per-feature dropout, which is
what `nn.Dropout` does here. Inputs of arbitrary HxW are supported (the
synthetic fallback uses small images); flatten size adapts.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class CNN_MNIST(nn.Module):
    n_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(32, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(64, (3, 3), padding="VALID", dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.n_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class CNN_CIFAR(nn.Module):
    n_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, *, train: bool = False):
        x = x.astype(self.dtype)
        for width in (64, 128, 256):
            x = nn.relu(nn.Conv(width, (3, 3), padding="VALID",
                                dtype=self.dtype)(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(256, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.n_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
