"""Model registry (reference: `get_model`, src/models.py:4-8)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.models.cnn import (
    CNN_MNIST, CNN_CIFAR)
from defending_against_backdoors_with_robust_learning_rate_tpu.models.resnet import (
    ResNet9)

_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16}


def get_model(data: str, arch: str = "cnn", dtype: str = "f32",
              n_classes: int = 10, remat: bool = False,
              remat_policy: str = "block"):
    """fmnist/fedemnist -> CNN_MNIST; cifar10 -> CNN_CIFAR (src/models.py:4-8);
    arch='resnet9' selects the BASELINE north-star ResNet-9 extension.
    `remat` enables rematerialization (ResNet-9 only; the small CNNs'
    activations never pressure HBM); `remat_policy` picks full blockwise
    ("block") or selective save-conv-outputs ("conv") recompute."""
    dt = _DTYPES[dtype]
    if arch == "resnet9":
        return ResNet9(n_classes=n_classes, dtype=dt, remat=remat,
                       remat_policy=remat_policy)
    if data in ("fmnist", "fedemnist", "synthetic"):
        return CNN_MNIST(n_classes=n_classes, dtype=dt)
    if data == "cifar10":
        return CNN_CIFAR(n_classes=n_classes, dtype=dt)
    raise ValueError(f"no model for data={data!r} arch={arch!r}")


def init_params(model, image_shape, key=None, batch: int = 2):
    key = key if key is not None else jax.random.PRNGKey(0)
    x = jnp.zeros((batch,) + tuple(image_shape), jnp.float32)
    return model.init({"params": key}, x, train=False)["params"]


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def flops_per_example(data: str, arch: str, image_shape,
                      n_classes: int = 10):
    """Analytic FORWARD FLOPs for one example through the registry's
    model (ISSUE 10: bench.py's MFU trajectory must be computable on any
    backend — XLA cost analysis needs a compile, this is arithmetic).

    Multiply-accumulates count as 2 FLOPs; elementwise tails (relu,
    pool, dropout, bias) are <1% on these architectures and are ignored
    — the same convention as the public MFU formulas. One fwd+bwd
    training step costs ~3x the forward (the standard 2x-backward
    estimate). Returns None for architectures without an analytic model
    here (resnet9) — callers fall back to XLA's cost analysis."""
    h, w, c = image_shape
    if arch == "resnet9":
        return None

    def conv(h, w, cin, cout, k=3):
        # VALID 3x3 conv: output (h-2)x(w-2), 2*k*k*cin*cout MACs/pixel
        ho, wo = h - (k - 1), w - (k - 1)
        return 2 * k * k * cin * cout * ho * wo, ho, wo

    flops = 0
    if data in ("fmnist", "fedemnist", "synthetic"):
        # CNN_MNIST: conv(32) -> conv(64) -> pool2 -> fc128 -> fc10
        f, h, w = conv(h, w, c, 32)
        flops += f
        f, h, w = conv(h, w, 32, 64)
        flops += f
        h, w = h // 2, w // 2
        flat = h * w * 64
        flops += 2 * flat * 128 + 2 * 128 * n_classes
        return float(flops)
    if data == "cifar10":
        # CNN_CIFAR: [conv(width) -> pool2] x (64, 128, 256) -> fc128
        # -> fc256 -> fc10
        cin = c
        for width in (64, 128, 256):
            f, h, w = conv(h, w, cin, width)
            flops += f
            h, w, cin = h // 2, w // 2, width
        flat = h * w * 256
        flops += 2 * flat * 128 + 2 * 128 * 256 + 2 * 256 * n_classes
        return float(flops)
    return None
