"""Model registry (reference: `get_model`, src/models.py:4-8)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.models.cnn import (
    CNN_MNIST, CNN_CIFAR)
from defending_against_backdoors_with_robust_learning_rate_tpu.models.resnet import (
    ResNet9)

_DTYPES = {"f32": jnp.float32, "bf16": jnp.bfloat16}


def get_model(data: str, arch: str = "cnn", dtype: str = "f32",
              n_classes: int = 10, remat: bool = False,
              remat_policy: str = "block"):
    """fmnist/fedemnist -> CNN_MNIST; cifar10 -> CNN_CIFAR (src/models.py:4-8);
    arch='resnet9' selects the BASELINE north-star ResNet-9 extension.
    `remat` enables rematerialization (ResNet-9 only; the small CNNs'
    activations never pressure HBM); `remat_policy` picks full blockwise
    ("block") or selective save-conv-outputs ("conv") recompute."""
    dt = _DTYPES[dtype]
    if arch == "resnet9":
        return ResNet9(n_classes=n_classes, dtype=dt, remat=remat,
                       remat_policy=remat_policy)
    if data in ("fmnist", "fedemnist", "synthetic"):
        return CNN_MNIST(n_classes=n_classes, dtype=dt)
    if data == "cifar10":
        return CNN_CIFAR(n_classes=n_classes, dtype=dt)
    raise ValueError(f"no model for data={data!r} arch={arch!r}")


def init_params(model, image_shape, key=None, batch: int = 2):
    key = key if key is not None else jax.random.PRNGKey(0)
    x = jnp.zeros((batch,) + tuple(image_shape), jnp.float32)
    return model.init({"params": key}, x, train=False)["params"]


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
