"""The experiment driver — reference src/federated.py:21-95 re-built around
jitted round functions.

Round loop shape (reference src/federated.py:65-92): sample agents -> local
training -> aggregate -> eval every `snap` rounds, logging the reference's
exact TensorBoard scalar names. Differences: the whole round is one compiled
XLA program (vmap on one device, shard_map over the `agents` mesh axis when
--mesh > 1); client sampling is seeded; checkpoint/resume via Orbax
(SURVEY.md section 5.4 gap); rounds/sec throughput is measured (section 5.1
gap, and BASELINE.json's headline metric).

Structure (ISSUE 6): all driver state lives in `RoundEngine`, a *resumable
round engine* whose loop body is exposed as explicit steps —
``dispatch(unit)`` / ``eval_boundary(rnd)`` / ``save_checkpoint(rnd)`` /
``post_unit()`` — over engine state. ``run`` (the one-shot trainer) iterates
them exactly as the historical monolithic loop did; the continuous-service
driver (service/driver.py) iterates the same steps indefinitely with a
supervisor wrapped around each one. The factoring is what makes crash-exact
recovery possible: every step is re-enterable from restored state."""

from __future__ import annotations

import os
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.config import (
    Config, args_parser, print_exp_details)
from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
    get_federated_data)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.common import (
    make_normalizer)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.evaluate import (
    make_eval_fn, pad_eval_set)
from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
    registry as attack_registry)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
    buffered as buffered_mod)
from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
    CHAINED_INFO_KEYS, FAULT_INFO_KEYS, host_takes_flags, make_round_fn,
    make_round_fn_host, step_takes_round)
from defending_against_backdoors_with_robust_learning_rate_tpu.health import (
    monitor as health_monitor, sentinel as health_sentinel)
from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    Heartbeat, NullHeartbeat, SpanTracer, attribution as obs_attribution,
    events as obs_events, flight as obs_flight,
    reputation as obs_reputation, telemetry as obs_telemetry)
from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
    get_model, init_params, param_count)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
    checkpoint as ckpt, compile_cache)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.guards import (
    all_finite_device, guard_round_fn)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.metrics import (
    MetricsDrain, MetricsWriter, NullWriter, run_name)

# above this many stacked-array bytes the driver switches to host-side
# per-round shard gathering (the fedemnist path: 3383 users, SURVEY.md 7.3.2)
DEVICE_RESIDENT_BYTES = compile_cache.DEVICE_RESIDENT_BYTES


def _adopt_aot(bank, cfg, family, jit_obj, example_args):
    """Swap a jitted program for its banked (or freshly banked) AOT
    executable. Returns the Compiled, or None when the bank can't serve
    this family — the caller keeps the plain jit path, which still
    warm-starts through the persistent XLA cache."""
    if bank is None:
        return None
    try:
        compiled, hit, secs, _ = bank.get_or_compile(
            family, cfg, jit_obj, example_args)
    except Exception as e:
        print(f"[aot] {family}: falling back to jit "
              f"({type(e).__name__}: {e})")
        return None
    print(f"[aot] {family}: "
          + ("loaded from cache" if hit else "compiled+banked")
          + f" in {secs:.1f}s")
    return compiled


def _bind_compiled(compiled, data):
    """Rebind an adopted executable to the bound-fn calling convention:
    (params, key[, round_idx]) with the dataset stacks appended."""
    def bound(params, key, *lead):
        return compiled(params, key, *lead, *data)
    return bound


def dispatch_schedule(start, total, snap, chain_n, diagnostics, chaining):
    """The driver's dispatch plan: a list of round-id tuples, one per
    dispatch — a chained block (len == chain_n) whenever the budget to the
    next eval boundary allows, else a single round. A chained block never
    crosses an eval boundary, and a diagnostics run keeps its snap rounds
    unchained (they need prev_params + the diag-compiled variant). This is
    the SINGLE source of truth: the run loop iterates these units directly
    and the host-mode prefetcher produces payloads against the same list."""
    units, rnd = [], start
    while rnd < total:
        to_eval = min(snap - rnd % snap, total - rnd)
        diag_boundary = diagnostics and (rnd + to_eval) % snap == 0
        budget = to_eval - (1 if diag_boundary else 0)
        if chaining and budget >= chain_n:
            units.append(tuple(range(rnd + 1, rnd + chain_n + 1)))
            rnd += chain_n
        else:
            units.append((rnd + 1,))
            rnd += 1
    return units


def apply_rng_impl(choice: str) -> str:
    """Resolve and install the PRNG bit generator BEFORE any key is made.

    'auto' picks the TPU's hardware RNG (rbg) on the tpu backend — measured
    +13% round throughput on v5e (threefry dropout-mask generation is 15%
    of the round, profile_round.py --ablate) — and threefry elsewhere, so
    CPU tests and cross-path parity are stream-identical to before. Streams
    differ between impls: a checkpoint resumes only under the impl that
    wrote it (key data shapes differ; restore fails loudly)."""
    impls = {"auto": ("rbg" if jax.default_backend() == "tpu"
                      else "threefry2x32"),
             "threefry": "threefry2x32", "rbg": "rbg"}
    if choice not in impls:
        raise ValueError(f"rng_impl must be one of {sorted(impls)}, "
                         f"got {choice!r}")
    impl = impls[choice]
    jax.config.update("jax_default_prng_impl", impl)
    return impl


class RoundEngine:
    """Resumable round engine: program building, restored state, and the
    loop body as explicit re-enterable steps.

    Construction does everything up to (not including) the first dispatch:
    data/model/program building, AOT adoption, checkpoint restore, metrics
    plumbing. The caller then drives:

        for unit in engine.schedule():      # or its own unit stream
            engine.dispatch(unit)
            if engine.rnd % cfg.snap == 0:
                engine.eval_boundary(engine.rnd)
                engine.save_checkpoint(engine.rnd)   # if checkpointing
            engine.post_unit()
        ...
        engine.close()                      # in a finally
        summary = engine.finalize()

    ``run`` below is exactly that loop (the historical one-shot trainer);
    service/driver.py wraps each step in a supervisor and streams units
    indefinitely. State (params, base_key, rnd, cumulative metrics) lives
    on the engine, so a crash resumes by building a fresh engine from the
    journaled checkpoint (utils/checkpoint.py) and re-entering the loop —
    bit-identical to never having crashed."""

    def __init__(self, cfg: Config, writer: Optional[MetricsWriter] = None,
                 resume_upto: Optional[int] = None):
        # resume_upto pins the newest checkpoint round restore may pick
        # (0 = none): the service driver passes its journal-agreed resume
        # round so a kill between ckpt.save and journal_record cannot make
        # the engine restore past the metrics splice point. None (the
        # one-shot trainer) keeps newest-valid semantics. The producer
        # (prepare_crash_exact_resume) has already digest-validated that
        # round, so restore skips re-hashing it.
        if cfg.tenants > 0:
            # the tenant axis is the experiment QUEUE's pack knob
            # (service/queue.py --tenants routes shape-compatible cells
            # through service/tenancy.run_pack); this engine runs ONE
            # experiment and must never half-adopt the *_mt families
            raise ValueError(
                f"--tenants {cfg.tenants} packs experiments in the "
                f"queue (service/queue.py --tenants E, or "
                f"scripts/sweep_scenarios.py --tenants E); train.run "
                f"runs a single experiment — drop --tenants here")
        resolved_layout = compile_cache.resolved_train_layout(cfg)
        if cfg.train_layout != resolved_layout:
            # same shape as the bucket+diagnostics refusal, but megabatch
            # has an exact fallback, so degrade loudly instead of dying:
            # the per-client loss curves (and the diag-variant program
            # pairing) want the per-client axis, and mixing layouts
            # between snap and off-snap rounds would silently compare
            # different programs. The resolver is the single source of
            # the degrade rule; the engine only normalizes cfg to it.
            print(f"[layout] --train_layout {cfg.train_layout} does not "
                  f"support --diagnostics (per-client loss curves need "
                  f"the per-client axis); degrading this run to "
                  f"--train_layout {resolved_layout} — drop "
                  f"--diagnostics to keep the {cfg.train_layout} layout")
            cfg = cfg.replace(train_layout=resolved_layout)
        self.cfg = cfg
        self._resume_upto = resume_upto
        print_exp_details(cfg)
        if compile_cache.resolved_train_layout(cfg) == "megabatch":
            print("[layout] megabatch local training: the client axis "
                  "folds into the batch — one [m*bs, ...] gather + "
                  "normalize pass per minibatch step with "
                  "client-segmented loss/mask reductions (fl/client.py; "
                  "--train_layout vmap restores the per-client layout)")
        obs_telemetry.check_level(cfg.telemetry)
        # health-lane + policy validation (health/monitor.py), loudly
        # and before any build
        health_monitor.check(cfg)
        if health_sentinel.has_quarantine(cfg):
            print(f"[health] quarantined clients: "
                  f"{list(health_sentinel.quarantine_ids(cfg))} "
                  f"(excluded via the participation mask)")
        # attack-config validation, loudly and before any build
        # (attack/registry.py: unknown strategy, bad boost, schedule on a
        # data-side strategy)
        attack_registry.check(cfg)
        atk_banner = attack_registry.banner(cfg)
        if atk_banner:
            print(atk_banner)
        # buffered-async validation (fl/buffered.py: order-statistic
        # aggregators, diagnostics, pallas, host-sampled — each refusal
        # names its remediation)
        buffered_mod.check(cfg)
        self.async_mode = async_mode = buffered_mod.is_buffered(cfg)
        async_banner = buffered_mod.banner(cfg)
        if async_banner:
            print(async_banner)
        impl = apply_rng_impl(cfg.rng_impl)
        if impl != "threefry2x32":
            print(f"[rng] {impl} bit generator")
        # observability (obs/): host-side round-trace spans + the
        # status.json heartbeat, lead process only. The heartbeat rides the
        # tracer's span-completion hook, so `last_span` tracks without
        # extra calls.
        self.lead = lead = jax.process_index() == 0
        self.hb = hb = (Heartbeat(cfg.status_file
                                  or os.path.join(cfg.log_dir,
                                                  "status.json"))
                        if cfg.heartbeat and lead else NullHeartbeat())
        self.tracer = tracer = SpanTracer(enabled=cfg.spans and lead,
                                          on_end=hb.span_hook)
        hb.update(phase="setup", rounds=cfg.rounds, force=True)
        if cfg.telemetry != "off":
            print(f"[telemetry] in-jit defense telemetry: {cfg.telemetry} "
                  f"(Defense/* scalars ride the metrics stream)")
        # reputation-plane validation (obs/reputation.py), loudly and
        # before any build; the lane itself resolves after the pallas
        # decision (`auto` rides the jnp paths only)
        obs_reputation.check(cfg)
        self._rep_on = obs_reputation.reputation_on(cfg)
        if self._rep_on:
            print(f"[reputation] per-client suspicion lanes: rep_agree + "
                  f"rep_norm ride the round program (zero added "
                  f"collectives); host ledger keyed by real client ids "
                  f"(--reputation off disables)")
        # persistent XLA cache + AOT executable bank — must be configured
        # before the first compile so every program family persists
        bank = compile_cache.setup(cfg)
        if cfg.compile_cache:
            print(f"[cache] persistent XLA cache at "
                  f"{compile_cache.cache_root(cfg)}"
                  + ("" if bank is not None
                     else " (AOT bank off: --debug_nan)"))
        # population/cohort split (ISSUE 7): the cfg-only decision comes
        # FIRST — a million-client population must never be materialized
        # densely just to decide not to materialize it. The client bank
        # (data/bank.py) holds the population offset-indexed on disk;
        # `fed` then carries a zero-client shape shim plus the eval sets.
        cohort_mode = compile_cache.is_cohort_mode(cfg)
        cohort_src = None
        if (not cohort_mode and cfg.cohort_sampled == "auto"
                and cfg.num_agents
                >= compile_cache.COHORT_AUTO_MIN_POPULATION):
            print(f"[cohort] population {cfg.num_agents:,} is above the "
                  f"auto threshold but the implied cohort of "
                  f"{cfg.agents_per_round} cannot be sampled "
                  f"(data/cohort.py MAX_CANDIDATES); staying on the dense "
                  f"path — set --cohort_size to decouple population from "
                  f"cohort")
        if cohort_mode:
            from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
                get_cohort_data)
            cohort_src = fed = get_cohort_data(cfg)
        else:
            fed = get_federated_data(cfg)
        if fed.synthetic and cfg.data != "synthetic":
            print(f"[data] {cfg.data} files not found under "
                  f"{cfg.data_dir!r}; using the deterministic synthetic "
                  f"fallback")

        model = get_model(cfg.data, cfg.model_arch, cfg.dtype,
                          remat=cfg.remat, remat_policy=cfg.remat_policy)
        params = init_params(model, fed.train.images.shape[2:],
                             jax.random.PRNGKey(cfg.seed))
        print(f"[model] {type(model).__name__}: "
              f"{param_count(params):,} params")
        norm = make_normalizer(fed.mean, fed.std, fed.raw_is_normalized)

        # single source with the precompile planner
        # (compile_cache.is_host_mode) so banked families always match what
        # this loop dispatches; the threshold stays the module global for
        # test monkeypatching
        host_mode = (not cohort_mode) and compile_cache.is_host_mode(
            cfg, fed, threshold=DEVICE_RESIDENT_BYTES)
        if host_mode and (cfg.churn_enabled or cfg.traffic_enabled):
            # churn/traffic-aware cohorting (ROADMAP carry-over from PR
            # 6; diurnal traffic joins in ISSUE 17): a host-sampled run
            # under churn or diurnal traffic routes through the cohort
            # program — cohorts sampled in-program from the present set
            # over the dense host stacks — instead of the old loud
            # refusal. The decision defers to is_cohort_mode (the same
            # single source the planner and precompile consult), which
            # honors an explicit --cohort_sampled off AND requires the
            # implied cohort to be samplable; either way the refusal
            # stays loud rather than crashing mid-construction.
            what = "churn" if cfg.churn_enabled else "traffic"
            if compile_cache.is_cohort_mode(
                    cfg, fed, threshold=DEVICE_RESIDENT_BYTES):
                cohort_mode, host_mode = True, False
                print(f"[cohort] host-sampled + {what}: cohorts are "
                      f"sampled from the {what}-present set (the refusal "
                      "path is retired)")
            else:
                raise ValueError(
                    f"host-sampled + {what} needs the cohort program "
                    f"(cohorts sampled from the {what}-present set), but "
                    "this config cannot take it: --cohort_sampled is "
                    "'off', or the implied cohort of "
                    f"{cfg.agents_per_round} clients is not samplable "
                    "(data/cohort.py MAX_CANDIDATES) — set "
                    "--cohort_size, raise availability, or disable "
                    f"{what}")
        n_mesh = 1
        if cfg.mesh != 1 and not host_mode and not cohort_mode:
            from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
                make_mesh, pick_agent_mesh_size)
            from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
                make_sharded_round_fn)
            n_mesh = pick_agent_mesh_size(cfg.mesh, cfg.agents_per_round)

        # diagnostics extras (lr vector, agent norms) are only consumed on
        # snap rounds; off-snap rounds run a variant compiled without them
        plain_cfg = cfg.replace(diagnostics=False)
        host_sampler = None
        chained_fn = None
        host_chained_fn = None
        get_unit = None   # host-mode payload fetch, set in the host branch
        self._prefetcher = None   # host-mode RoundPrefetcher, created lazily
        self._sched_units = None  # set by set_schedule (prefetch order)
        # a diagnostic snap round always runs unchained, so it is excluded
        # from the per-boundary chain budget (single source:
        # utils/compile_cache — the precompile planner must agree with the
        # driver on chain length)
        chain_n = compile_cache.chain_budget(cfg)
        mesh = None
        if n_mesh > 1:
            if jax.process_count() > 1:
                # multi-host: one global agents mesh, DCN-aware device
                # order. The mesh must span every host's devices, so the
                # blocking policy cannot shrink it — the participant count
                # has to divide over the full pod (global_agents_mesh
                # raises otherwise).
                from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
                    multihost)
                n_mesh = multihost.require_pod_divisible(
                    cfg.agents_per_round, "multi-host")
                mesh = multihost.global_agents_mesh(n_mesh)
                arrays = multihost.put_replicated(
                    mesh, (fed.train.images, fed.train.labels,
                           fed.train.sizes))
                params = multihost.put_replicated(mesh, params)
            else:
                mesh = make_mesh(n_mesh)
                arrays = (jnp.asarray(fed.train.images),
                          jnp.asarray(fed.train.labels),
                          jnp.asarray(fed.train.sizes))
            print(f"[mesh] {n_mesh} devices on the `agents` axis "
                  f"({cfg.agents_per_round // n_mesh} agents/device), "
                  f"{jax.process_count()} process(es)")
            from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
                multihost as mh)
            print(f"[agg] {mh.agg_plan_note(cfg, params, mesh)}")
            round_fn = make_sharded_round_fn(plain_cfg, model, norm, mesh,
                                             *arrays)
            diag_round_fn = (make_sharded_round_fn(cfg, model, norm, mesh,
                                                   *arrays)
                             if cfg.diagnostics else round_fn)
            if chain_n > 1:
                from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
                    make_sharded_chained_round_fn)
                chained_fn = make_sharded_chained_round_fn(
                    plain_cfg, model, norm, mesh, *arrays)
        elif cohort_mode:
            # ----------------------------------------------- cohort mode
            # population decoupled from cohort (ISSUE 7): the driver
            # mirrors the seeded in-program cohort draw (data/cohort.py)
            # to gather only the m sampled clients' rows — from the
            # memory-mapped client bank, or (churn-aware host mode) from
            # the dense host stacks — and the round program recomputes
            # the same ids from the traced round index to derive corrupt
            # and churn flags per cohort MEMBER. Host/HBM stay O(cohort).
            m = cfg.agents_per_round
            if jax.process_count() > 1:
                raise NotImplementedError(
                    "cohort-sampled mode is single-process for now — the "
                    "pod-scale aggregation rework (ROADMAP) will shard "
                    "the cohort gather across hosts")
            if cohort_src is not None:
                print(f"[cohort] population {cfg.num_agents:,} clients -> "
                      f"{m}-client cohorts ({cfg.partitioner} client "
                      f"bank, {cohort_src.max_n} rows/cohort member; "
                      f"in-program sampling, cohort_seed "
                      f"{cfg.cohort_seed})")
                gather_rows = cohort_src.gather_cohort
            else:
                print(f"[cohort] {cfg.num_agents} clients -> {m}-client "
                      f"cohorts sampled from the churn-present set over "
                      f"the host shard stacks")

                def gather_rows(ids):
                    return (fed.train.images[ids], fed.train.labels[ids],
                            fed.train.sizes[ids])
            take = lambda a: jnp.asarray(a)  # noqa: E731
            take_block = take
            round_fn_host = None
            if cfg.mesh != 1:
                from jax.sharding import NamedSharding, PartitionSpec as P
                from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
                    AGENTS_AXIS, make_mesh, pick_agent_mesh_size)
                from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
                    make_sharded_cohort_round_fn)
                n_mesh = pick_agent_mesh_size(cfg.mesh, m)
                if n_mesh > 1:
                    mesh = make_mesh(n_mesh)
                    print(f"[mesh] {n_mesh} devices on the `agents` axis "
                          f"({m // n_mesh} cohort members/device), "
                          f"cohort-sampled")
                    from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
                        multihost as mh)
                    print(f"[agg] {mh.agg_plan_note(cfg, params, mesh)}")
                    agents_sharding = NamedSharding(mesh, P(AGENTS_AXIS))
                    block_sharding = NamedSharding(mesh,
                                                   P(None, AGENTS_AXIS))
                    take = lambda a: jax.device_put(  # noqa: E731
                        a, agents_sharding)
                    take_block = lambda a: jax.device_put(  # noqa: E731
                        a, block_sharding)
                    round_fn_host = make_sharded_cohort_round_fn(
                        plain_cfg, model, norm, mesh)
                    diag_round_fn_host = (
                        make_sharded_cohort_round_fn(cfg, model, norm,
                                                     mesh)
                        if cfg.diagnostics else round_fn_host)
                else:
                    print(f"[mesh] no device count <= {cfg.mesh or 'all'} "
                          f"divides the cohort of {m}; --mesh request "
                          f"ignored")
            if round_fn_host is None:
                from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
                    make_cohort_round_fn)
                round_fn_host = make_cohort_round_fn(plain_cfg, model, norm)
                diag_round_fn_host = (
                    make_cohort_round_fn(cfg, model, norm)
                    if cfg.diagnostics else round_fn_host)
            if chain_n > 1:
                # cohort chaining survives faults AND keeps the full-
                # telemetry cosine split: the scanned round index
                # re-derives flags in-program (fl/rounds.make_cohort_step)
                if n_mesh > 1:
                    from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
                        make_sharded_chained_cohort_round_fn)
                    host_chained_fn = make_sharded_chained_cohort_round_fn(
                        plain_cfg, model, norm, mesh)
                else:
                    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
                        make_chained_cohort_round_fn)
                    host_chained_fn = make_chained_cohort_round_fn(
                        plain_cfg, model, norm)

            from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
                cohort as cohort_mod)

            def sample_ids(rnd):
                # the host mirror of the in-program draw — bit-identical
                # ids (data/cohort.py), evaluated on the prefetch thread.
                # static: ok(host-sync)
                ids, _active = cohort_mod.sample_cohort_host(cfg, rnd)
                return ids

            def gather_unit(unit):
                """One dispatch unit's cohort payload: a single round's
                [m, ...] stacks or a chained block's [chain, m, ...]
                stacks — O(cohort) gather riding the prefetch thread, so
                bank reads + H2D overlap the running round program."""
                with tracer.span("prefetch/gather", rounds=len(unit)):
                    ids = np.stack([sample_ids(r) for r in unit])
                    if len(unit) == 1:
                        imgs, lbls, szs = gather_rows(ids[0])
                        return (ids[0], take(imgs), take(lbls), take(szs))
                    rows = [gather_rows(i) for i in ids]
                    return (ids,
                            take_block(np.stack([r[0] for r in rows])),
                            take_block(np.stack([r[1] for r in rows])),
                            take_block(np.stack([r[2] for r in rows])))

            if cfg.host_prefetch > 0:
                print(f"[prefetch] cohort gather pipeline, depth "
                      f"{cfg.host_prefetch}")
            get_unit = self._unit_fetcher(gather_unit)

            def host_sampler(params, key, rnd, want_diag):
                with tracer.span("round/data_prep", round=rnd):
                    _ids, imgs, lbls, szs = get_unit((rnd,))
                fn = diag_round_fn_host if want_diag else round_fn_host
                with tracer.span("round/dispatch", round=rnd):
                    # the round index is a traced int32 lead argument —
                    # the program recomputes the cohort (ids, flags,
                    # churn mask) from it; `sampled` in the info dict is
                    # the program's own draw
                    new_params, info = fn(params, key, jnp.int32(rnd),
                                          imgs, lbls, szs)
                return new_params, info
        elif host_mode:
            print(f"[data] host-sampled mode "
                  f"({fed.train.images.nbytes / 2**30:.1f} GiB of shards)")
            # take(base, ids) materializes the round's sampled [m, ...]
            # stack for this mode: the multi-process variant never gathers
            # rows this process's devices don't own. take_block is the
            # chained variant: ids [chain, m] -> [chain, m, ...] block in
            # one placement.
            take = lambda a, ids: jnp.asarray(a[ids])  # noqa: E731
            take_block = take
            round_fn_host = None
            if cfg.mesh != 1 and jax.process_count() > 1:
                # multi-process host-sampled: every process runs the
                # identical seeded sampling over its (replicated) host
                # dataset, then materializes only its addressable shards
                # of the global [m, ...] stacks
                # (multihost.take_agents_sharded); the shard_mapped round
                # runs over ONE global agents mesh exactly like the
                # device-resident multi-host path
                from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
                    multihost)
                from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
                    make_sharded_round_fn_host)
                n_mesh = multihost.require_pod_divisible(
                    cfg.agents_per_round, "multi-host host-sampled")
                mesh = multihost.global_agents_mesh(0)
                print(f"[mesh] {n_mesh} global devices on the `agents` "
                      f"axis ({cfg.agents_per_round // n_mesh} "
                      f"agents/device), host-sampled shards, "
                      f"{jax.process_count()} processes")
                take = lambda a, ids: multihost.take_agents_sharded(  # noqa: E731
                    mesh, a, ids)
                take_block = lambda a, ids: \
                    multihost.take_agents_sharded_block(  # noqa: E731
                        mesh, a, ids)
                params = multihost.put_replicated(mesh, params)
                round_fn_host = make_sharded_round_fn_host(plain_cfg, model,
                                                           norm, mesh)
                diag_round_fn_host = (
                    make_sharded_round_fn_host(cfg, model, norm, mesh)
                    if cfg.diagnostics else round_fn_host)
            elif cfg.mesh != 1:
                # the m sampled shards gathered each round are fixed-shape
                # [m, ...] stacks — partition them over the agents mesh
                # (m/d per device) and run the shard_mapped round body
                from jax.sharding import NamedSharding, PartitionSpec as P
                from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.mesh import (
                    AGENTS_AXIS, make_mesh, pick_agent_mesh_size)
                from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
                    make_sharded_round_fn_host)
                n_mesh = pick_agent_mesh_size(cfg.mesh,
                                              cfg.agents_per_round)
                if n_mesh > 1:
                    mesh = make_mesh(n_mesh)
                    print(f"[mesh] {n_mesh} devices on the `agents` axis "
                          f"({cfg.agents_per_round // n_mesh} "
                          f"agents/device), host-sampled shards")
                    agents_sharding = NamedSharding(mesh, P(AGENTS_AXIS))
                    block_sharding = NamedSharding(mesh,
                                                   P(None, AGENTS_AXIS))
                    # device_put on the host array splits host->devices in
                    # one step (no staging copy through device 0)
                    take = lambda a, ids: jax.device_put(  # noqa: E731
                        a[ids], agents_sharding)
                    take_block = lambda a, ids: jax.device_put(  # noqa: E731
                        a[ids], block_sharding)
                    round_fn_host = make_sharded_round_fn_host(
                        plain_cfg, model, norm, mesh)
                    diag_round_fn_host = (
                        make_sharded_round_fn_host(cfg, model, norm, mesh)
                        if cfg.diagnostics else round_fn_host)
                else:
                    print(f"[mesh] no device count <= {cfg.mesh or 'all'} "
                          f"divides agents_per_round="
                          f"{cfg.agents_per_round}; --mesh request ignored")
            if round_fn_host is None:
                round_fn_host = make_round_fn_host(plain_cfg, model, norm)
                diag_round_fn_host = (make_round_fn_host(cfg, model, norm)
                                      if cfg.diagnostics else round_fn_host)
            # one site builds the chained-host variant for whichever round
            # fn was picked above (sharded single- or multi-process mesh,
            # or single-device); a multi-process job WITHOUT the global
            # mesh gets no chaining (it is the redundant-work warning case
            # below). Host-sampled chaining is also skipped under faults:
            # the host step then takes per-round corrupt flags the chained
            # scan doesn't carry (device-resident chaining computes them
            # in-jit and is unaffected).
            if chain_n > 1 and (cfg.faults_enabled
                                or attack_registry.in_jit(cfg)):
                chain_n = 1
                tag, why = (("faults", "faults") if cfg.faults_enabled
                            else ("attack", f"--attack {cfg.attack}"))
                print(f"[{tag}] host-sampled mode: --chain disabled "
                      f"({why} needs per-round corrupt flags riding "
                      f"each dispatch)")
            if chain_n > 1:
                if n_mesh > 1:
                    from defending_against_backdoors_with_robust_learning_rate_tpu.parallel.rounds import (
                        make_sharded_chained_round_fn_host)
                    host_chained_fn = make_sharded_chained_round_fn_host(
                        plain_cfg, model, norm, mesh)
                elif jax.process_count() == 1:
                    from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
                        make_chained_round_fn_host)
                    host_chained_fn = make_chained_round_fn_host(
                        plain_cfg, model, norm)

            def sample_ids(rnd):
                # per-round generator so --resume continues the same
                # sampling sequence the uninterrupted run would have used
                rng = np.random.default_rng(cfg.seed * 100_003 + rnd)
                return rng.choice(cfg.num_agents, cfg.agents_per_round,
                                  replace=False)

            def gather_unit(unit):
                """One dispatch unit's payload: a single round's [m, ...]
                stacks or a chained block's [chain, m, ...] stacks (one
                placement). The span lands on whichever thread runs the
                gather — the prefetch worker in pipelined mode, so
                trace.json shows the overlap."""
                with tracer.span("prefetch/gather", rounds=len(unit)):
                    ids = np.stack([sample_ids(r) for r in unit])
                    if len(unit) == 1:
                        return (ids[0], take(fed.train.images, ids[0]),
                                take(fed.train.labels, ids[0]),
                                take(fed.train.sizes, ids[0]))
                    return (ids, take_block(fed.train.images, ids),
                            take_block(fed.train.labels, ids),
                            take_block(fed.train.sizes, ids))

            # host gather + H2D transfer overlap the running round program
            # (data/prefetch.py); created lazily at the first dispatch so
            # a resumed run prefetches from its restored start round
            if cfg.host_prefetch > 0:
                print(f"[prefetch] host->device pipeline, depth "
                      f"{cfg.host_prefetch}")

            get_unit = self._unit_fetcher(gather_unit)

            def host_sampler(params, key, rnd, want_diag):
                with tracer.span("round/data_prep", round=rnd):
                    ids, imgs, lbls, szs = get_unit((rnd,))
                fn = diag_round_fn_host if want_diag else round_fn_host
                with tracer.span("round/dispatch", round=rnd):
                    if host_takes_flags(cfg):
                        # faults: the host-sampled ids determine which
                        # slots hold malicious agents
                        # (--faults_spare_corrupt participation); full
                        # telemetry: the honest/corrupt cosine split needs
                        # the same flags
                        flags = jnp.asarray(ids < cfg.num_corrupt)
                        new_params, info = fn(params, key, imgs, lbls, szs,
                                              flags)
                    else:
                        new_params, info = fn(params, key, imgs, lbls, szs)
                info["sampled"] = ids
                return new_params, info
        else:
            arrays = (jnp.asarray(fed.train.images),
                      jnp.asarray(fed.train.labels),
                      jnp.asarray(fed.train.sizes))
            round_fn = make_round_fn(plain_cfg, model, norm, *arrays)
            diag_round_fn = (make_round_fn(cfg, model, norm, *arrays)
                             if cfg.diagnostics else round_fn)
            if chain_n > 1:
                from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
                    make_chained_round_fn)
                chained_fn = make_chained_round_fn(plain_cfg, model, norm,
                                                   *arrays)
        if chained_fn is not None or host_chained_fn is not None:
            print(f"[chain] {chain_n} rounds per compiled dispatch "
                  f"(lax.scan"
                  + (", host-sampled blocks)" if host_chained_fn is not None
                     else ")"))

        if async_mode and host_mode:
            raise ValueError(
                "--agg_mode buffered is not supported in host-sampled "
                "mode (this dataset is above the device-resident budget "
                "and the host step has no channel for the arrival draw); "
                "run cohort-sampled (--cohort_sampled on) so the round "
                "program owns the cohort, or --agg_mode sync")
        if async_mode and jax.process_count() > 1:
            raise NotImplementedError(
                "--agg_mode buffered is single-process for now — the "
                "carried buffer state is not yet multi-host replicated; "
                "run --agg_mode sync on multi-process jobs")
        if async_mode:
            # the engine's "params" slot becomes the (params, buffer)
            # carry: checkpointing, AOT avals, donation and the chained
            # scan all treat it as one pytree, which is what makes a
            # mid-buffer kill recover crash-exactly — the buffer rides
            # the digest-verified checkpoint like params do. Per-bin
            # telemetry accumulators ride the vmap paths only
            # (fl/buffered.init_state; the sharded paths degrade the
            # per-staleness split rather than paying per-bin collectives).
            params = (params, buffered_mod.init_state(
                cfg, params, per_bin=(n_mesh == 1)))

        if cfg.faults_enabled:
            print(f"[faults] dropout={cfg.dropout_rate} "
                  f"straggler={cfg.straggler_rate}@{cfg.straggler_epochs}ep "
                  f"corrupt={cfg.corrupt_rate}/{cfg.corrupt_mode} "
                  f"norm_cap={cfg.payload_norm_cap} "
                  f"rlr_threshold={cfg.rlr_threshold_mode}"
                  + (" spare_corrupt" if cfg.faults_spare_corrupt else ""))
        if cfg.churn_enabled:
            print(f"[churn] client lifecycles: available "
                  f"{cfg.churn_available} of phases, period "
                  f"{cfg.churn_period} rounds, churn_seed {cfg.churn_seed} "
                  f"(service/churn.py; away clients ride the "
                  f"participation mask)")
        if cfg.traffic_enabled:
            from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
                traffic as traffic_mod)
            print(f"[traffic] diurnal availability: peak "
                  f"{cfg.traffic_peak_frac} / trough "
                  f"{cfg.traffic_trough_frac} over "
                  f"{cfg.traffic_day_rounds}-round days (mean "
                  f"{traffic_mod.mean_available(cfg):.2f}), latency sigma "
                  f"{cfg.traffic_latency_sigma}, traffic_seed "
                  f"{cfg.traffic_seed} (data/traffic.py; present clients "
                  f"ride the participation mask)")

        if jax.process_count() > 1 and n_mesh <= 1:
            # no global-mesh SPMD path was taken: every process would run
            # the identical seeded program independently — N-way duplicated
            # work, not a distributed job (ADVICE r1)
            print("[WARN] multi-process job without the global agents "
                  f"mesh: {jax.process_count()} processes are training "
                  "REDUNDANTLY. Set --mesh=0 (all devices) to distribute "
                  "the round over the pod.")

        if cfg.debug_nan:
            # sanitizer mode (SURVEY.md section 5.2): float checks compiled
            # into every round variant; raises on the first NaN/inf
            print("[guards] checkify float checks enabled (--debug_nan)")
            if host_sampler is None:
                round_fn = guard_round_fn(round_fn)
                diag_round_fn = guard_round_fn(diag_round_fn)
            else:
                round_fn_host = guard_round_fn(round_fn_host)
                diag_round_fn_host = guard_round_fn(diag_round_fn_host)
            if chained_fn is not None:
                chained_fn = guard_round_fn(chained_fn)
            if host_chained_fn is not None:
                host_chained_fn = guard_round_fn(host_chained_fn)

        if cfg.use_pallas:
            from defending_against_backdoors_with_robust_learning_rate_tpu.fl.rounds import (
                _pallas_applicable)
            if n_mesh > 1 and _pallas_applicable(plain_cfg):
                print("[pallas] sharded fused server step: one Pallas pass "
                      "per device + psum of the sign/avg partials")
            elif _pallas_applicable(plain_cfg):
                msg = "[pallas] fused RLR+FedAvg+apply server kernel enabled"
                if cfg.diagnostics:
                    msg += (" (snap rounds use the jnp path: diagnostics "
                            "need the explicit lr vector)")
                print(msg)
            else:
                print(f"[pallas] fused kernel covers aggr=avg/sign with "
                      f"noise=0; aggr={cfg.aggr!r} noise={cfg.noise} falls "
                      f"back to the jnp path")

        eval_fn = make_eval_fn(model, norm, cfg.n_classes)
        self._fisher_fn = None
        if cfg.diagnostics:
            from defending_against_backdoors_with_robust_learning_rate_tpu.fl.diagnostics import (
                make_fisher_fn)
            self._fisher_fn = make_fisher_fn(model, norm)
        val = tuple(map(jnp.asarray, pad_eval_set(
            fed.val_images, fed.val_labels, cfg.eval_bs)))
        pval = tuple(map(jnp.asarray, pad_eval_set(
            fed.pval_images, fed.pval_labels, cfg.eval_bs)))

        if writer is None:
            writer = (MetricsWriter(cfg.log_dir, run_name(cfg),
                                    cfg.tensorboard)
                      if lead else NullWriter())
        self.writer = writer

        base_key = jax.random.PRNGKey(cfg.seed)

        start_round, cum_poison_acc, self.cum_net_mov = 0, 0.0, 0.0
        health_ema = None
        # per-client suspicion ledger (obs/reputation.py): the host fold
        # of the in-jit rep_agree lane — lead process only (the writer's
        # discipline); every process still COMPILES the lane so program
        # families match across the pod. Observe-only: quarantine stays
        # the health ladder's decision.
        self._rep_tracker = (obs_reputation.ReputationTracker.for_config(
            cfg, population=cfg.num_agents)
            if self._rep_on and lead else None)
        self._rep_pending = []
        # ground truth touches ONLY the AUC evaluation row — the ranking
        # itself never reads a corrupt flag (obs/reputation.py)
        self._rep_pred = ((lambda cid: cid < cfg.num_corrupt)
                          if cfg.num_corrupt > 0 else None)
        if self._rep_tracker is not None and self._rep_tracker.sketch_mode:
            print(f"[reputation] population {cfg.num_agents:,} > cap "
                  f"{cfg.rep_population_cap:,}: count-min sketch + "
                  f"top-{cfg.rep_topk} heavy-hitter ledger "
                  f"(O(cohort + k) RSS)")
        if cfg.resume and cfg.checkpoint_dir:
            restored = ckpt.restore(
                cfg.checkpoint_dir, params, upto=self._resume_upto,
                upto_validated=self._resume_upto is not None)
            if restored is not None:
                (start_round, params, base_key, cum_poison_acc,
                 self.cum_net_mov) = restored
                if jax.process_count() > 1 and n_mesh > 1:
                    from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
                        multihost)
                    params = multihost.put_replicated(mesh, params)
                else:
                    params = jax.device_put(params)
                # the health-EMA baseline rides the round journal
                # (save_checkpoint writes it): restoring it is what keeps
                # replayed Health/Loss_Z rows byte-identical across a
                # crash-exact resume
                for entry in ckpt.journal_read(cfg.checkpoint_dir):
                    if entry["round"] == start_round:
                        health_ema = entry.get("health") or None
                        # the suspicion ledger rides the same journal
                        # entry; restoring it is what keeps replayed
                        # Reputation/* rows byte-identical
                        if self._rep_tracker is not None:
                            self._rep_tracker.load_state(
                                entry.get("reputation") or None)
                print(f"[ckpt] resumed from round {start_round}")
                # a per-life record (obs/events.PER_LIFE_PREFIXES): each
                # process/segment that restores emits its own — a no-op
                # outside the service plane (no ledger installed)
                obs_events.emit("checkpoint/restore", round=start_round)

        # --- AOT adoption: swap jitted program families for banked
        # serialized executables (utils/compile_cache.py). A warm start
        # skips XLA entirely; a cold start compiles ahead-of-time and banks
        # the result. Scope: single-process, single-device programs only —
        # sharded round fns produce mesh-replicated params whose shardings
        # a Compiled lowered from plain avals rejects at call time, and
        # multi-process executables embed the local topology; both keep
        # plain jit, which still warm-starts through the persistent XLA
        # cache. Any per-family failure also falls back to jit.
        eval_val_fn = eval_pval_fn = eval_fn
        # the stall detectors must not kill a first-time compile (the
        # documented tunnel-wedge cause): flag the compile window until the
        # first dispatch unit has executed
        hb.update(phase="compile", compile_in_flight=True, force=True)
        if bank is not None and jax.process_count() == 1 and n_mesh == 1:
            ab = compile_cache.abstractify
            p_aval, k_aval = ab(params), ab(base_key)
            # eval programs take the BARE model params — in buffered mode
            # `params` is the (params, buffer-state) carry and handing
            # that aval to eval would lower model.apply over a tuple
            mp_aval = ab(params[0]) if async_mode else p_aval
            ids_aval = jax.ShapeDtypeStruct((chain_n,), jnp.int32)
            # churn — and scheduled-attack — round programs take the
            # round index as a traced int32 scalar (single source
            # fl/rounds.step_takes_round, with plan_programs)
            lead_avals = ((jax.ShapeDtypeStruct((), jnp.int32),)
                          if step_takes_round(cfg) else ())
            if cohort_mode or host_sampler is not None:
                # one adoption triad (round / diag / chained block) for
                # both [m, ...]-stack branches; they differ only in
                # family names and the per-round signature — cohort
                # takes the traced round index as a lead int32 and no
                # flag avals (flags derive in-program from the
                # recomputed cohort ids), host takes trailing corrupt
                # flags when faults/full telemetry need them
                m = cfg.agents_per_round
                shard_avals = tuple(
                    jax.ShapeDtypeStruct((m,) + a.shape[1:], a.dtype)
                    for a in (fed.train.images, fed.train.labels,
                              fed.train.sizes))
                sfx = compile_cache.family_suffix(cfg)
                if cohort_mode:
                    fams = ("round_cohort" + sfx, "round_cohort_diag",
                            "chained_cohort" + sfx)
                    round_avals = (
                        (p_aval, k_aval,
                         jax.ShapeDtypeStruct((), jnp.int32))
                        + shard_avals)
                else:
                    fams = ("round_host" + sfx, "round_host_diag",
                            "chained_host" + sfx)
                    flag_avals = ((jax.ShapeDtypeStruct((m,), jnp.bool_),)
                                  if host_takes_flags(cfg) else ())
                    round_avals = ((p_aval, k_aval) + shard_avals
                                   + flag_avals)
                shared = diag_round_fn_host is round_fn_host
                fn = _adopt_aot(bank, cfg, fams[0], round_fn_host,
                                round_avals)
                if fn is not None:
                    round_fn_host = fn
                    if shared:
                        diag_round_fn_host = fn
                if cfg.diagnostics:
                    fn = _adopt_aot(bank, cfg, fams[1],
                                    diag_round_fn_host, round_avals)
                    if fn is not None:
                        diag_round_fn_host = fn
                if host_chained_fn is not None:
                    block_avals = tuple(
                        jax.ShapeDtypeStruct((chain_n,) + a.shape, a.dtype)
                        for a in shard_avals)
                    fn = _adopt_aot(bank, cfg, fams[2], host_chained_fn,
                                    (p_aval, k_aval, ids_aval)
                                    + block_avals)
                    if fn is not None:
                        host_chained_fn = fn
            else:
                data_avals = ab(arrays)
                fn = _adopt_aot(bank, cfg, round_fn.family, round_fn.jitted,
                                (p_aval, k_aval) + lead_avals + data_avals)
                if fn is not None:
                    round_fn = _bind_compiled(fn, round_fn.data)
                    if not cfg.diagnostics:
                        diag_round_fn = round_fn
                if cfg.diagnostics:
                    fn = _adopt_aot(bank, cfg, diag_round_fn.family,
                                    diag_round_fn.jitted,
                                    (p_aval, k_aval) + lead_avals
                                    + data_avals)
                    if fn is not None:
                        diag_round_fn = _bind_compiled(fn,
                                                       diag_round_fn.data)
                if chained_fn is not None:
                    fn = _adopt_aot(bank, cfg, chained_fn.family,
                                    chained_fn.jitted,
                                    (p_aval, k_aval, ids_aval) + data_avals)
                    if fn is not None:
                        chained_fn = _bind_compiled(fn, chained_fn.data)
            fn = _adopt_aot(bank, cfg, "eval_val", eval_fn,
                            (mp_aval,) + ab(val))
            if fn is not None:
                eval_val_fn = fn
            fn = _adopt_aot(bank, cfg, "eval_poison", eval_fn,
                            (mp_aval,) + ab(pval))
            if fn is not None:
                eval_pval_fn = fn

        # sampled device-trace window (--profile_rounds N,
        # obs/attribution.py): opens at the first STEADY dispatch unit
        # (never the compile unit), closes after N rounds, and is parsed
        # into Device/* + Memory/* attribution rows after the loop. A bare
        # --profile_dir (without --profile_rounds) keeps its historical
        # whole-run trace semantics.
        self.prof = None
        if cfg.profile_rounds > 0 and lead:
            run_dir_hint = getattr(writer, "dir", None) or cfg.log_dir
            self.prof = obs_attribution.RoundProfiler(
                cfg.profile_rounds,
                cfg.profile_dir or os.path.join(run_dir_hint, "profile"))
        self._whole_run_trace = bool(cfg.profile_dir and lead
                                     and self.prof is None)
        if self._whole_run_trace:
            jax.profiler.start_trace(cfg.profile_dir)

        # incident flight recorder (obs/flight.py): a bounded per-round
        # ring + crash-exact flight.jsonl next to metrics.jsonl, lead
        # process only. Span durations ride the tracer's completion
        # hook, chained after the heartbeat's — no extra timing calls
        # on the hot path.
        self.flight = None
        if cfg.flight == "on" and lead:
            flight_dir = getattr(writer, "dir", None) or cfg.log_dir
            flight_run = run_name(cfg)
            self.flight = obs_flight.FlightRecorder(
                os.path.join(flight_dir, obs_flight.STREAM_NAME),
                run=flight_run, corr=obs_events.corr_id(flight_run),
                slot=f"p{jax.process_index()}"
                     + (f"-E{cfg.tenants}" if cfg.tenants > 0 else ""))
            tracer.chain_on_end(self.flight.observe_span)

        # --- async metrics pipeline: per-round/eval scalars stay on device
        # and drain through a background thread's batched device_get, so
        # the round loop never blocks on a host sync (~24% of round time on
        # the small CNN, r3 flagship ladder). Diagnostics and --debug_nan
        # need inline host values; multi-process jobs keep the lead-only
        # writer synchronous.
        use_async = (cfg.async_metrics and not cfg.debug_nan
                     and not cfg.diagnostics and jax.process_count() == 1)
        self.drain = MetricsDrain(tracer=tracer) if use_async else None
        if self.drain is not None:
            print("[metrics] async drain: host syncs ride a background "
                  "thread (--sync_metrics restores the inline path)")
        # steady-state clock (VERDICT r1 #9): stamped in emit_eval, i.e.
        # when a boundary's values ARRIVE (post-execution) — in async mode
        # the dispatch timestamps would measure queueing, not compute
        self.mstate = {"cum_poison_acc": cum_poison_acc, "summary": {},
                       "t_steady": None, "r_steady": 0,
                       "t_steady_end": None, "r_steady_end": 0,
                       # health-EMA baseline (health/sentinel.py):
                       # journal-restored on resume so replayed Health/*
                       # rows are byte-identical
                       "health_ema": health_ema}

        # engine state the step methods advance
        self.params = params
        self.base_key = base_key
        self.start_round = start_round
        self.rnd = start_round
        self.rounds_done = 0
        self.first_unit = True
        self.chain_n = chain_n
        self.n_mesh = n_mesh
        self.host_mode = host_mode
        self.cohort_mode = cohort_mode
        self.val, self.pval = val, pval
        self._round_fn, self._diag_round_fn = (
            (round_fn, diag_round_fn) if host_sampler is None
            else (None, None))
        self._host_sampler = host_sampler
        self._get_unit_impl = get_unit
        self._chained_fn, self._host_chained_fn = chained_fn, host_chained_fn
        self._eval_val_fn, self._eval_pval_fn = eval_val_fn, eval_pval_fn
        self._last_info = {}
        self._last_unit_rounds = 1
        self._want_diag = False
        self._prev_params = None
        self.t_loop = time.perf_counter()

    # ------------------------------------------------------------- schedule

    @property
    def chaining(self) -> bool:
        return (self._chained_fn is not None
                or self._host_chained_fn is not None)

    @property
    def model_params(self):
        """The bare model parameters: in buffered-async mode the engine's
        ``params`` slot holds the (params, buffer-state) carry
        (fl/buffered.py) — eval, profiling and the summary read the model
        half through this property."""
        return self.params[0] if self.async_mode else self.params

    def schedule(self):
        """The one-shot dispatch plan from the engine's (restored) start
        round to cfg.rounds. ONE source of truth for chaining decisions:
        the loop consumes the same schedule the host-mode prefetcher
        produces against, so the two cannot desynchronize (code review
        r3)."""
        units = dispatch_schedule(
            self.start_round, self.cfg.rounds, self.cfg.snap, self.chain_n,
            self.cfg.diagnostics, self.chaining)
        self.set_schedule(units)
        return units

    def set_schedule(self, units) -> None:
        """Pin the unit stream the host-mode prefetcher will produce
        against (any iterable of round-id tuples; the service driver
        passes a generator). Must be called before the first dispatch."""
        self._sched_units = units

    # ------------------------------------------------------------- stepping

    def _round_lead(self, rnd):
        # churn — and scheduled-attack — round programs take the round
        # index as a traced lead argument (fl/rounds.step_takes_round is
        # the single source; the AOT aval planner agrees)
        return ((jnp.int32(rnd),)
                if step_takes_round(self.cfg) else ())

    def dispatch(self, unit, nonce: int = 0) -> None:
        """Run one dispatch unit (a single round or a chained block):
        advances params/rnd/rounds_done, records spans/heartbeat, feeds
        the profiler, and emits the snap-round diagnostics scalars.

        ``nonce`` (health/monitor.py DISCARD rung) folds a recovery
        nonce into the single-round key so a withdrawn round re-draws
        its stochastic choices deterministically; 0 (every normal
        dispatch) keeps the historical derivation bit-for-bit. Chained
        blocks never take a nonce (the service driver, the only ladder
        host, dispatches unchained)."""
        cfg, tracer = self.cfg, self.tracer
        self.hb.update(phase="train", round=unit[-1])
        if self.flight is not None:
            self.flight.begin_unit()
        self._last_unit_rounds = len(unit)
        if self.prof is not None and not self.first_unit:
            # steady state: every hot-path program compiled during the
            # first unit, so the window never captures XLA working
            self.prof.maybe_start()
        if len(unit) > 1:
            # chained block: fixed length => one compilation per shape
            with tracer.span("round/data_prep", round=unit[-1]):
                ids = jnp.arange(unit[0], unit[-1] + 1)
                payload = (None if self._chained_fn is not None
                           else self._get_unit(unit))
            with tracer.span("round/dispatch", round=unit[-1],
                             chain=len(unit)):
                if self._chained_fn is not None:
                    self.params, stacked = self._chained_fn(
                        self.params, self.base_key, ids)
                else:
                    # host-sampled block: the prefetcher hands over the
                    # whole [chain, m, ...] shard-stack payload at once
                    _, imgs, lbls, szs = payload
                    self.params, stacked = self._host_chained_fn(
                        self.params, self.base_key, ids, imgs, lbls, szs)
            self.rnd = unit[-1]
            self.rounds_done += len(unit)
            info = {"train_loss": stacked["train_loss"][-1]}
            info.update({k: stacked[k][-1] for k in CHAINED_INFO_KEYS
                         if k in stacked})
            info.update({k: stacked[k][-1] for k in stacked
                         if k.startswith(("tel_", "hlth_"))})
            if self._rep_tracker is not None and "rep_agree" in stacked:
                # [chain, m] agreement rows + matching REAL client ids:
                # device-resident scans stack their in-program draw
                # ("sampled"); host/cohort blocks don't carry it through
                # the scan — the payload's id block is the bit-identical
                # host mirror. Rows stay on device until the boundary's
                # (async) drain fetch.
                ids_blk = stacked.get("sampled")
                if ids_blk is None and payload is not None:
                    ids_blk = payload[0]
                if ids_blk is not None:
                    self._rep_pending.append((tuple(unit), ids_blk,
                                              stacked["rep_agree"],
                                              stacked["rep_norm"]))
            self._want_diag, self._prev_params = False, None
        else:
            rnd = unit[0]
            with tracer.span("round/data_prep", round=rnd):
                key = jax.random.fold_in(self.base_key, rnd)
                if nonce:
                    key = jax.random.fold_in(
                        key, health_monitor.RECOVERY_NONCE + nonce)
                snap_round = rnd % cfg.snap == 0
                self._want_diag = cfg.diagnostics and snap_round
                self._prev_params = self.params if self._want_diag else None
            if self._host_sampler is not None:
                # host_sampler opens its own data_prep/dispatch spans (the
                # gather is the interesting part there)
                self.params, info = self._host_sampler(
                    self.params, key, rnd, self._want_diag)
            else:
                with tracer.span("round/dispatch", round=rnd):
                    fn = (self._diag_round_fn if self._want_diag
                          else self._round_fn)
                    self.params, info = fn(self.params, key,
                                           *self._round_lead(rnd))
            self.rnd = rnd
            self.rounds_done += 1
            if (self._rep_tracker is not None and "rep_agree" in info
                    and "sampled" in info):
                if nonce:
                    # DISCARD-rung re-dispatch: the withdrawn attempt's
                    # evidence must not fold alongside the redrawn round
                    self._rep_pending = [p for p in self._rep_pending
                                         if p[0] != (rnd,)]
                self._rep_pending.append(((rnd,), info["sampled"],
                                          info["rep_agree"],
                                          info["rep_norm"]))
        self._last_info = info
        if self.prof is not None:
            # accounts the unit toward the capture budget and polls the
            # HBM watermarks; closes the window (blocking on params first)
            # once the budget is reached
            self.prof.after_unit(self.params, len(unit))
        if self._want_diag:
            self._emit_diagnostics(info)

    def _unit_fetcher(self, gather_unit):
        """The payload-fetch closure shared by the host-sampled and
        cohort-sampled branches: direct gather, or the depth-bounded
        prefetch pipeline (data/prefetch.py) created lazily at the first
        dispatch. _sched_units is THE loop's schedule (set before the
        loop starts; the first get_unit call is its first entry), so
        production order provably matches consumption order."""
        cfg = self.cfg

        def get_unit(unit):
            if cfg.host_prefetch > 0:
                if self._prefetcher is None:
                    from defending_against_backdoors_with_robust_learning_rate_tpu.data.prefetch import (
                        RoundPrefetcher)
                    self._prefetcher = RoundPrefetcher(
                        gather_unit, self._sched_units,
                        depth=cfg.host_prefetch)
                return self._prefetcher.get(unit)
            return gather_unit(unit)

        return get_unit

    def _get_unit(self, unit):
        if self._get_unit_impl is None:
            raise RuntimeError("host payload requested outside host mode")
        # the host branch's get_unit closure (set in __init__)
        return self._get_unit_impl(unit)

    def _emit_diagnostics(self, info) -> None:
        cfg, writer, rnd = self.cfg, self.writer, self.rnd
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl.diagnostics import (
            norm_scalars, sign_agreement)
        if "agent_norms" in info:
            for tag, v in norm_scalars(info["agent_norms"],
                                       info["sampled"],
                                       cfg.num_corrupt).items():
                writer.scalar(tag, v, rnd)
        if "lr_flat" in info:
            from jax.flatten_util import ravel_pytree
            pval = self.pval
            # Fisher at the pre-update params (aggregation.py:146-148)
            f_adv = ravel_pytree(self._fisher_fn(self._prev_params,
                                                 *pval))[0]
            hon_labels = jnp.full_like(pval[1], cfg.base_class)
            f_hon = ravel_pytree(
                self._fisher_fn(self._prev_params, pval[0], hon_labels,
                                pval[2]))[0]
            upd_flat = (ravel_pytree(self.params)[0]
                        - ravel_pytree(self._prev_params)[0])
            # --diagnostics is the synchronous research mode by design
            # (the async drain is disabled); these fetches happen at snap
            # cadence only.
            # static: ok(host-sync)
            scalars, self.cum_net_mov = sign_agreement(
                np.asarray(info["lr_flat"]), np.asarray(upd_flat),
                np.asarray(f_adv), np.asarray(f_hon),
                cfg.top_frac, cfg.effective_server_lr, self.cum_net_mov)
            for tag, v in scalars.items():
                writer.scalar(tag, v, rnd)

    def eval_boundary(self, rnd: int) -> None:
        """One eval boundary: dispatch the two eval programs on the
        (un-donated) params and route the values through the async drain
        (or emit inline in sync mode)."""
        cfg, tracer, info = self.cfg, self.tracer, self._last_info
        # HBM watermarks ride the heartbeat so the session stall detectors
        # see memory pressure, not just phase ({} on backends without
        # allocator stats)
        mem = obs_attribution.memory_watermarks()
        self.hb.update(phase="eval", round=rnd, **mem)
        if self.flight is not None and mem:
            self.flight.note(**mem)
        # divergence aborts only under --debug_nan (sync mode); otherwise
        # the finite check rides the drain and warns, and the run keeps
        # recording its (NaN) metrics
        vals = {"finite": all_finite_device(self.params)}
        # eval dispatches on the (un-donated) params BEFORE the next
        # dispatch unit runs: in async mode round r's eval executes
        # overlapped with the round r+1 training block
        with tracer.span("eval/val_dispatch", round=rnd):
            val_loss_d, val_acc_d, per_class_d = self._eval_val_fn(
                self.model_params, *self.val)
        with tracer.span("eval/poison_dispatch", round=rnd):
            poison_loss_d, poison_acc_d, _ = self._eval_pval_fn(
                self.model_params, *self.pval)
        vals.update(val_loss=val_loss_d, val_acc=val_acc_d,
                    base_acc=per_class_d[cfg.base_class],
                    poison_loss=poison_loss_d,
                    poison_acc=poison_acc_d,
                    train_loss=info["train_loss"])
        if "fault_voters" in info:
            vals.update({k: info[k] for k in FAULT_INFO_KEYS})
        if "churn_away" in info:
            vals["churn_away"] = info["churn_away"]
        if "async_fill" in info:
            # buffered-aggregation observability (fl/buffered.py)
            vals.update({k: info[k]
                         for k in buffered_mod.ASYNC_INFO_KEYS})
        # in-jit defense telemetry rides the same (async) fetch
        vals.update({k: info[k] for k in info if k.startswith("tel_")})
        # health-sentinel scalars (health/sentinel.py): the [m] suspect
        # vector stays in the info dict — it is ladder evidence
        # (service/driver.py), not a metrics row
        vals.update({k: info[k]
                     for k in health_sentinel.boundary_keys(cfg)
                     if k in info})
        if self._rep_tracker is not None and self._rep_pending:
            # per-round (round_ids, client_ids, rep_agree, rep_norm) rows
            # since the last boundary ride the same (async) fetch; the
            # tracker fold happens host-side in _emit_eval_body, on the
            # drain thread in async mode
            vals["rep_rows"] = self._rep_pending
            self._rep_pending = []
        if self.drain is not None:
            elapsed = time.perf_counter() - self.t_loop
            self.drain.submit(self._emit_eval, vals, rnd, self.rounds_done,
                              elapsed)
        else:
            with tracer.span("metrics/host_sync", round=rnd):
                # this IS the --sync_metrics fallback path; async mode
                # routes the same fetch through the MetricsDrain instead.
                # static: ok(host-sync)
                vals = jax.device_get(vals)  # THE per-round sync
            elapsed = time.perf_counter() - self.t_loop
            self._emit_eval(vals, rnd, self.rounds_done, elapsed)

    def _emit_eval(self, vals, ernd, rounds_done_now, elapsed):
        """One eval boundary's host side-effects, in the exact synchronous
        order. Sync mode calls it inline with fetched values; async mode
        runs it on the drain thread — one code path, so metrics.jsonl is
        bit-identical between the modes (tests/test_async_metrics.py).
        The cumulative poison mean accumulates HERE in host float64,
        matching the synchronous semantics exactly."""
        with self.tracer.span("metrics/emit", round=ernd):
            self._emit_eval_body(vals, ernd, rounds_done_now, elapsed)

    def _emit_eval_body(self, vals, ernd, rounds_done_now, elapsed):
        # service/tenancy.run_pack's emit() mirrors this row schema
        # per tenant — a new scalar series added here must be fanned
        # out there too, or packed tenants' streams silently diverge
        # from their solo twins (the tenancy parity tests pin the
        # series they exercise, not future ones)
        cfg, writer, mstate = self.cfg, self.writer, self.mstate
        # unified divergence policy (health/monitor.py): the historical
        # finite_warn / --debug_nan endpoints AND the sentinel-lane
        # judgement (z-score, norm spike) route through ONE assessment;
        # `abort` raises here, `record`/`recover` warn and keep the
        # metrics flowing. The EMA state commits LAST (with
        # cum_poison_acc): a supervised retry of this body must not
        # double-fold the baseline.
        health_report = health_monitor.assess(cfg, mstate["health_ema"],
                                              vals)
        health_monitor.emit_rows(writer, health_report, ernd)
        health_monitor.enforce(cfg, health_report, where=f"round {ernd}")
        val_loss = float(vals["val_loss"])
        val_acc = float(vals["val_acc"])
        poison_loss = float(vals["poison_loss"])
        poison_acc = float(vals["poison_acc"])
        # computed into a local and committed to mstate only at the very
        # end: the service supervisor retries a transiently-failed eval
        # unit by re-running this body, and an accumulate-first ordering
        # would double-count poison_acc into the checkpointed cumulative
        cum_poison_acc = mstate["cum_poison_acc"] + poison_acc
        # scalar names preserved from src/federated.py:81-91
        writer.scalar("Validation/Loss", val_loss, ernd)
        writer.scalar("Validation/Accuracy", val_acc, ernd)
        writer.scalar("Poison/Base_Class_Accuracy",
                      float(vals["base_acc"]), ernd)
        writer.scalar("Poison/Poison_Accuracy", poison_acc, ernd)
        writer.scalar("Poison/Poison_Loss", poison_loss, ernd)
        writer.scalar("Poison/Cumulative_Poison_Accuracy_Mean",
                      cum_poison_acc / ernd, ernd)
        writer.scalar("Train/Loss", float(vals["train_loss"]), ernd)
        if "fault_voters" in vals:
            # degradation observability (faults/ + service/churn.py): who
            # failed this round, and how thin the electorate got
            writer.scalar("Faults/Dropped",
                          float(vals["fault_dropped"]), ernd)
            writer.scalar("Faults/Straggled",
                          float(vals["fault_straggled"]), ernd)
            writer.scalar("Faults/Effective_Voters",
                          float(vals["fault_voters"]), ernd)
        if "churn_away" in vals:
            writer.scalar("Churn/Sampled_Away",
                          float(vals["churn_away"]), ernd)
        if "async_fill" in vals:
            # buffered-mode observability: how full the buffer ran, and
            # the staleness mix it accumulated since the last commit
            writer.scalar("Async/Buffer_Fill",
                          float(vals["async_fill"]), ernd)
            if self.flight is not None:
                # flight-record the fill on the same (possibly drain-)
                # thread that materialized it — note() is lock-guarded
                self.flight.note(buffer_fill=float(vals["async_fill"]))
            writer.scalar("Async/Committed",
                          float(vals["async_committed"]), ernd)
            for i, c in enumerate(vals["async_stale_hist"]):
                writer.scalar(f"Async/Staleness_Hist/{i}", float(c), ernd)
        # Defense/* telemetry scalars (obs/telemetry.py), shared emit path
        # so sync and async streams stay bit-identical
        obs_telemetry.emit_scalars(writer, vals, ernd)
        # suspicion ledger fold + Reputation/* rows (obs/reputation.py):
        # popped so a supervised retry of this body cannot double-fold
        # the longitudinal EMA/streak state
        rep_rows = vals.pop("rep_rows", None)
        if self._rep_tracker is not None and rep_rows is not None:
            tracker = self._rep_tracker
            for rnds, row_ids, agrees, norms in rep_rows:
                row_ids, agrees = np.asarray(row_ids), np.asarray(agrees)
                norms = np.asarray(norms)
                if agrees.ndim == 1:
                    tracker.fold(rnds[0], row_ids, agrees, norms)
                else:
                    for j, r in enumerate(rnds):
                        tracker.fold(r, row_ids[j], agrees[j], norms[j])
            obs_reputation.emit_rows(writer, tracker, ernd,
                                     self._rep_pred)
            for ev in tracker.drain_events():
                # typed ledger event on the streak crossing; replay-
                # deduped (obs/events.REPLAY_DEDUPE_EVENTS) so crash-
                # exact resumes don't re-announce the same suspect
                obs_events.emit(obs_reputation.SUSPECT_EVENT,
                                severity="warn", **ev)
        writer.scalar("Throughput/Rounds_Per_Sec",
                      rounds_done_now / elapsed, ernd)
        now = time.perf_counter()
        if (mstate["t_steady"] is not None
                and rounds_done_now > mstate["r_steady"]):
            writer.scalar("Throughput/Steady_Rounds_Per_Sec",
                          (rounds_done_now - mstate["r_steady"])
                          / (now - mstate["t_steady"]), ernd)
        print(f'| Rnd {ernd}: Val_Loss/Val_Acc: {val_loss:.3f} / '
              f'{val_acc:.3f} |')
        print(f'| Rnd {ernd}: Poison Loss/Poison Acc: {poison_loss:.3f} / '
              f'{poison_acc:.3f} |')
        mstate["summary"] = {
            "round": ernd, "val_loss": val_loss, "val_acc": val_acc,
            "poison_loss": poison_loss, "poison_acc": poison_acc,
            "rounds_per_sec": rounds_done_now / elapsed}
        if health_report["rows"]:
            # the lane's verdict as data: queue rows read it from the
            # run summary (service/queue.SUMMARY_KEYS "health"); the
            # service LADDER deliberately does not — it judges the raw
            # sentinel lanes synchronously from eng._last_info
            # (health/monitor.HealthLadder.check), ahead of this
            # (possibly async-drained) emit
            mstate["summary"]["health"] = {
                k: float(v) for k, v in health_report["rows"].items()}
        tel = obs_telemetry.host_summary(vals)
        if tel:
            # the mechanism's state as data: the scenario-matrix rows
            # (service/queue.py SUMMARY_KEYS) and the online threshold-
            # adaptation controller (attack/adapt.py — reads the stash
            # after the boundary's drain flush) both consume this
            mstate["summary"]["defense"] = tel
            mstate["defense"] = tel
            # freshness stamp: a skipped/degraded eval boundary must not
            # let the adaptation controller decide on the previous
            # boundary's snapshot (service/driver.py checks this)
            mstate["defense_round"] = ernd
        if self._rep_tracker is not None:
            rep_sum = self._rep_tracker.summary(self._rep_pred)
            # the queue/sweep cells read this key (service/queue.py
            # SUMMARY_KEYS "suspicion")
            mstate["summary"]["suspicion"] = rep_sum
            if tel:
                # scalar enrichment of the defense block — float values
                # only, so consumers that iterate the block's rows
                # (attack/adapt.py) stay type-stable
                tel["rep_suspects"] = float(rep_sum["suspect_count"])
                if "auc" in rep_sum:
                    tel["rep_auc"] = float(rep_sum["auc"])
        if mstate["t_steady"] is None:
            # first eval boundary done: every program variant on the hot
            # path has now compiled (or loaded) at least once
            mstate["t_steady"] = now
            mstate["r_steady"] = rounds_done_now
        else:
            # steady window always ends at a snap boundary: a final
            # partial segment (rounds % snap != 0) may fall back to the
            # never-yet-compiled unchained round fn, and that compile must
            # not pollute the compile-free metric
            mstate["t_steady_end"] = now
            mstate["r_steady_end"] = rounds_done_now
        writer.flush()
        mstate["cum_poison_acc"] = cum_poison_acc   # commit LAST (see top)
        mstate["health_ema"] = health_report["new_state"]

    def drain_flush(self, timeout: Optional[float] = None) -> None:
        """Surface queued metrics (and any drain-thread error) now."""
        if self.drain is not None:
            with self.tracer.span("drain/wait", round=self.rnd):
                self.drain.flush(timeout=timeout)

    def save_checkpoint(self, rnd: int, journal: bool = True,
                        drain_timeout: Optional[float] = None) -> None:
        """Checkpoint at an eval boundary. Every process calls save: orbax
        runs cross-process barriers inside and writes replicated data from
        the primary only — lead-gating it would deadlock a multi-host job.
        The drain is flushed first (`drain_timeout` is the service
        supervisor's wedge budget — TimeoutError classifies as wedged):
        the saved cum_poison_acc must include every eval boundary up to
        this round. With `journal`, the metrics byte offset is recorded
        for crash-exact resume (utils/checkpoint.py round journal)."""
        cfg = self.cfg
        if not cfg.checkpoint_dir:
            return
        self.drain_flush(timeout=drain_timeout)
        self.hb.update(phase="checkpoint", round=rnd)
        with self.tracer.span("ckpt/save", round=rnd):
            # -1 = auto: keep everything in the one-shot trainer (historic
            # behavior); serve() replaces it with its bounded default
            keep = max(cfg.service_keep_ckpts, 0)
            ckpt.save(cfg.checkpoint_dir, rnd, self.params, self.base_key,
                      self.mstate["cum_poison_acc"], self.cum_net_mov,
                      keep_last=keep)
        # replay-deduped (obs/events.REPLAY_DEDUPE_EVENTS): a crash-exact
        # resume that re-saves an already-ledgered boundary re-emits
        # nothing, so interrupted and uninterrupted twins stay
        # byte-identical; emitted BEFORE the journal write so a kill in
        # between leaves the dedupe mark, not a missing record
        obs_events.emit("checkpoint/save", round=rnd)
        if journal:
            offset = getattr(self.writer, "offset", None)
            if offset is not None:
                # the health-EMA baseline rides the journal entry: a
                # crash-exact resume restores it alongside the metrics
                # splice so replayed Health/* rows are byte-identical
                extra = {"health": self.mstate["health_ema"]}
                if self._rep_tracker is not None:
                    # the suspicion ledger rides the same entry
                    # (crash-exact Reputation/* rows); keyed only when
                    # the lane is on, so an off run's journal is
                    # byte-identical to the pre-plane format
                    extra["reputation"] = self._rep_tracker.state_dict()
                ckpt.journal_record(cfg.checkpoint_dir, rnd, offset(),
                                    keep_last=keep, **extra)

    def post_unit(self) -> None:
        """End-of-unit bookkeeping: flip the compile flag after the first
        unit (from here a silent heartbeat means a stall, not XLA working),
        close the flight record and flush the writer in sync mode."""
        if self.first_unit:
            self.first_unit = False
            self.hb.update(compile_in_flight=False, force=True)
        if self.flight is not None:
            self.flight.end_unit(
                self.rnd, unit_rounds=self._last_unit_rounds,
                drain_depth=(self.drain.pending
                             if self.drain is not None else None))
        if self.drain is None:
            self.writer.flush()

    # ------------------------------------------------------------- teardown

    def close(self) -> None:
        """Release threads/devices — the `finally` step. Any exception must
        still tear down the prefetch worker (it pins device arrays and
        would leak per failed run); the drain closes without raising, to
        not mask a loop exception with a secondary metrics error."""
        if self.drain is not None:
            self.drain.close(raise_errors=False)
        if self._prefetcher is not None:
            self._prefetcher.close()
            self._prefetcher = None
        if self.prof is not None:
            # a run shorter than the budget still flushes its window
            self.prof.close(self.params)
        if self.flight is not None:
            # stream handle only — the ring stays live so the driver can
            # still snapshot a post-teardown incident (recovery re-entry)
            self.flight.close()

    def finalize(self) -> Dict:
        """Post-loop summary: throughput, attribution, memory watermarks,
        span aggregates; closes the writer and the heartbeat."""
        cfg, writer, mstate = self.cfg, self.writer, self.mstate
        if self._whole_run_trace:
            jax.profiler.stop_trace()
            self._whole_run_trace = False
        elapsed = time.perf_counter() - self.t_loop
        summary = dict(mstate["summary"])
        summary.setdefault("round", cfg.rounds)
        summary["rounds_per_sec"] = self.rounds_done / max(elapsed, 1e-9)
        if (mstate["t_steady"] is not None
                and mstate["t_steady_end"] is not None
                and mstate["r_steady_end"] > mstate["r_steady"]):
            summary["steady_rounds_per_sec"] = (
                (mstate["r_steady_end"] - mstate["r_steady"])
                / max(mstate["t_steady_end"] - mstate["t_steady"], 1e-9))
        summary["params"] = param_count(self.model_params)
        print("Training has finished!")
        print(f"[throughput] {summary['rounds_per_sec']:.3f} rounds/sec "
              f"({self.rounds_done} rounds in {elapsed:.1f}s)"
              + (f"; steady-state "
                 f"{summary['steady_rounds_per_sec']:.3f} r/s"
                 if "steady_rounds_per_sec" in summary else ""))
        # device-time attribution (obs/attribution.py): the sampled capture
        # window parses into Device/* rows + the summary; HBM watermarks
        # (the per-captured-unit maxima, plus a final poll) land as
        # Memory/* rows and heartbeat fields. All of it is absent when
        # --profile_rounds=0 and the backend exposes no memory_stats — the
        # off path emits nothing.
        mem = obs_attribution.memory_watermarks()
        # host RSS rides the same Memory/* rows: the population-axis CI
        # job pins it flat across the client-population ladder (ISSUE 7)
        mem.update(obs_attribution.host_watermarks())
        if self.prof is not None:
            for key, val in self.prof.mem.items():
                mem[key] = max(mem.get(key, 0), val)
            attr = self.prof.result()
            if attr is not None:
                for tag, v in obs_attribution.scalar_rows(attr):
                    writer.scalar(tag, v, self.rnd)
                summary["attribution"] = attr
                if attr.get("device_present"):
                    pr = attr.get("per_round", {})
                    print(f"[profile] device time/round: "
                          f"{pr.get('compute_ms', 0.0):.1f} ms compute + "
                          f"{pr.get('collective_ms', 0.0):.1f} ms "
                          f"collective + {pr.get('gap_ms', 0.0):.1f} ms "
                          f"gap ({100 * attr['collective_frac']:.1f}% "
                          f"collective)")
                else:
                    print(f"[profile] {attr.get('note', 'no device track')}")
        if mem:
            # memory_rows values are host ints from device.memory_stats()
            for tag, val in obs_attribution.memory_rows(mem):
                writer.scalar(tag, val, self.rnd)
            summary["memory"] = mem
            self.hb.update(**mem)
        # per-span aggregates -> metrics.jsonl (Spans/*) and the summary;
        # the full event stream -> trace.json in the run dir
        # (Perfetto-loadable)
        if self.tracer.enabled:
            for tag, v in self.tracer.scalar_rows():
                writer.scalar(tag, v, self.rnd)
            summary["spans"] = self.tracer.aggregates()
            run_dir = getattr(writer, "dir", None)
            if run_dir:
                trace_path = self.tracer.write_trace(
                    os.path.join(run_dir, "trace.json"))
                if trace_path:
                    summary["trace_path"] = trace_path
                    print(f"[spans] {trace_path} "
                          f"(load in https://ui.perfetto.dev)")
        if self.flight is not None:
            # the clean-exit snapshot: flight.json always reflects the
            # run's final window, incident or not
            self.flight.snapshot("clean_exit", self.rnd)
        writer.close()
        self.hb.close("done")
        return summary


def run(cfg: Config, writer: Optional[MetricsWriter] = None) -> Dict:
    """The one-shot trainer: build the engine, iterate its schedule, emit
    the summary — exactly the historical loop, now over RoundEngine
    steps."""
    eng = RoundEngine(cfg, writer=writer)
    try:
        for unit in eng.schedule():
            eng.dispatch(unit)
            if eng.rnd % cfg.snap == 0:
                eng.eval_boundary(eng.rnd)
                eng.save_checkpoint(eng.rnd)
            eng.post_unit()
        # surface any drain-thread error while the run's state is intact
        # (close() below closes without raising, to not mask a loop
        # exception with a secondary metrics error)
        if eng.drain is not None:
            eng.hb.update(phase="drain", force=True)
            with eng.tracer.span("drain/wait"):
                eng.drain.flush()
    finally:
        eng.close()
    return eng.finalize()


def main(argv=None):
    cfg = args_parser(argv)
    if cfg.platform:
        # must land before any backend use; this environment's
        # sitecustomize pins a platform at interpreter start, so env vars
        # alone are too late
        jax.config.update("jax_platforms", cfg.platform)
    if cfg.num_processes > 1 or cfg.coordinator:
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
            multihost)
        multihost.maybe_initialize(cfg.coordinator, cfg.num_processes,
                                   cfg.process_id)
    run(cfg)
    # entry-point contract: setuptools console scripts wrap this in
    # sys.exit(main()), so returning the summary dict would exit status 1
    return 0


if __name__ == "__main__":
    main()
