"""RLR-aware sign-flip voting: corrupt updates vote against the honest
sign to flip the per-parameter learning rate.

The RLR defense (PAPER.md) thresholds the per-coordinate sign-vote margin
|sum_k sign(u_k)|: coordinates without enough agreement get learning rate
-server_lr. An adaptive attacker who knows this ("Learning to Backdoor
Federated Learning", arXiv:2303.03320, treats the attacker as a learner
against the deployed defense) does not need a bigger payload — it needs
to *shrink honest margins*. Each corrupt client trains honestly, then
submits the NEGATED update: every coordinate where honest clients agree
loses 2 votes of margin per attacker, dragging coordinates below the
threshold so the defense itself flips honest progress backwards.

With c corrupt of m voters, a coordinate with unanimous honest agreement
drops from margin m to m - 2c — the attack wins exactly when the
threshold θ satisfies m - 2c < θ, which is why the scenario matrix
(scripts/sweep_scenarios.py) crosses this strategy against threshold
settings, and why the online threshold adaptation hook (attack/adapt.py)
watches the vote-margin histogram collapse this attack causes.

What the corrupt clients train ON is the orthogonal data axis: the
strategy negates whatever the local update is. With ``--poison_frac 0``
this is the pure untargeted anti-vote described above (honest training,
negated submission); with the paper's poison settings (the scenario
matrix's default base) the negated update is of trojan-trained local
steps — the negation then fights the trigger its own data planted, so
pair signflip with ``--poison_frac 0`` when you want the clean
margin-collapse attack in isolation.

``--attack_boost`` composes: scale -boost makes the flipped vote ALSO
dominate plain averaging (sign flip defeats the vote, boost defeats the
mean). The transform is attack/boost.py's per-row scale at ``-boost`` —
one shared gating implementation, collective-free on every path.
"""

from __future__ import annotations

from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
    boost as boost_mod)


def scale_rows(corrupt_flags, active, boost: float):
    """[m] f32 row scale: ``-boost`` on corrupt slots while the schedule
    is active (the anti-vote), 1 elsewhere — boost's scale at the
    negated factor, so the two strategies' gating can never drift."""
    return boost_mod.scale_rows(corrupt_flags, active, -boost)
