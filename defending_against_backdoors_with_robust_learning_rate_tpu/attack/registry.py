"""Adaptive-adversary attack registry: config-selected attack strategies
that compose with every round-program dispatch surface.

The attack surface used to be exactly one fixed behavior — the paper's
static trojan stamped at dataset construction (attack/poison.py). But
sign-vote defenses like RLR are broken by *adaptive* attackers, not fixed
triggers ("Learning to Backdoor Federated Learning", arXiv:2303.03320),
so the simulator needs a pluggable strategy space (FL_PyTorch,
arXiv:2202.03099, is the precedent for scenario-pluggable FL simulation).
This module is that space's single source: ``--attack <name>`` selects a
strategy, the strategy declares its two hooks, and every round builder
consults the SAME predicates so the dispatch surfaces can never drift.

Two hook kinds, both collective-free by construction:

- **data hook** (``data_mode``): which trigger geometry each corrupt
  client stamps at construction/gather time. ``legacy`` is the
  reference's exact behavior (per-agent stamp, bitwise-pinned — the
  ``static`` strategy IS the historical poison path, untouched);
  ``split`` deals the full pattern across the corrupt cohort
  (attack/dba.py).
- **in-jit update hook** (``in_jit``): a per-row multiplicative scale on
  the stacked client updates, applied INSIDE the round program right
  after local training — before fault injection and server-side payload
  validation, so norm caps and robust aggregators see what a real server
  would. Corrupt flags derive from real client ids on every path (in-jit
  sampling, cohort recomputation, or the host-sampled flag argument), and
  the schedule gate (attack/schedule.py) is a pure function of the traced
  round index — so the transform adds ZERO collectives on the vmap,
  shard_map, bucket, cohort and megabatch paths alike (pinned by the
  ``*_atk_*`` specs in analysis/contracts.py).

Adding a strategy: one module with its scale/stamp function, one
``AttackStrategy`` row here, and the scenario matrix
(scripts/sweep_scenarios.py) picks it up by name.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp

from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
    boost as boost_mod, schedule, signflip as signflip_mod)
from defending_against_backdoors_with_robust_learning_rate_tpu.ops import tree


@dataclasses.dataclass(frozen=True)
class AttackStrategy:
    """One registered adversary behavior.

    ``data_mode``: 'legacy' = the reference per-agent stamp (static
    parity), 'split' = the DBA round-robin pattern deal (attack/dba.py).
    ``scale_rows``: the strategy's in-jit update hook —
    ``(corrupt_flags, active, boost) -> [m] f32 row scale`` — or None
    for the data-poisoning strategies; a non-None hook needs the
    corrupt-slot flags in-program and composes with the round-index
    schedule."""
    name: str
    data_mode: str      # legacy | split
    summary: str        # one-line banner text
    scale_rows: Optional[Callable] = None

    @property
    def in_jit(self) -> bool:
        return self.scale_rows is not None


REGISTRY = {
    "static": AttackStrategy(
        "static", "legacy",
        "the paper's static trojan (data poisoning only; bitwise the "
        "pre-registry path)"),
    "dba": AttackStrategy(
        "dba", "split",
        "distributed trigger: the full pattern dealt round-robin across "
        "the corrupt cohort (attack/dba.py)"),
    "boost": AttackStrategy(
        "boost", "legacy",
        "model-replacement boosting: corrupt updates scaled by "
        "--attack_boost to survive averaging (attack/boost.py)",
        scale_rows=boost_mod.scale_rows),
    "signflip": AttackStrategy(
        "signflip", "legacy",
        "RLR-aware anti-vote: corrupt updates negated (x -boost) to "
        "shrink honest sign margins (attack/signflip.py)",
        scale_rows=signflip_mod.scale_rows),
}


def get(cfg) -> AttackStrategy:
    strat = REGISTRY.get(cfg.attack)
    if strat is None:
        raise ValueError(f"--attack must be one of {sorted(REGISTRY)}, "
                         f"got {cfg.attack!r}")
    return strat


def check(cfg) -> None:
    """Validate the whole attack config once, loudly, at engine/planner
    construction — not deep inside a trace."""
    strat = get(cfg)
    schedule.check(cfg)
    if cfg.attack_boost <= 0:
        raise ValueError(f"--attack_boost must be > 0, got "
                         f"{cfg.attack_boost} (signflip applies the "
                         f"negation itself)")
    if not strat.in_jit and not schedule.is_trivial(cfg):
        raise ValueError(
            f"--attack {strat.name} poisons data at construction time — "
            f"there is no per-round behavior for a schedule to gate; "
            f"attack_start/attack_stop/attack_every compose with the "
            f"in-jit strategies "
            f"({sorted(s.name for s in REGISTRY.values() if s.in_jit)})")


def in_jit(cfg) -> bool:
    """Does this config transform updates inside the round program?
    (Drives host_takes_flags, the pallas fallback and the host-mode
    chaining budget — single source for every builder.)"""
    return get(cfg).in_jit


def needs_round(cfg) -> bool:
    """Does the round program need the traced round index for the attack
    (an in-jit strategy under a non-trivial schedule)? Composes into
    fl/rounds.step_takes_round alongside the churn lifecycle."""
    return in_jit(cfg) and not schedule.is_trivial(cfg)


def update_scale(cfg, corrupt_flags, active, boost=None):
    """The strategy's [m] per-row multiplicative scale. ``boost``
    overrides ``cfg.attack_boost`` with a traced scalar — the
    multi-tenant pack's per-tenant knob (fl/tenancy.py); None keeps the
    config constant (the solo paths, program unchanged)."""
    strat = get(cfg)
    if strat.scale_rows is None:
        raise ValueError(f"attack {strat.name!r} has no in-jit update "
                         f"hook")
    return strat.scale_rows(corrupt_flags, active,
                            cfg.attack_boost if boost is None else boost)


def apply_update_attack(cfg, stacked_updates, corrupt_flags,
                        active=None, boost=None):
    """Apply the in-jit strategy to the [m(/d), ...]-stacked updates.

    ``corrupt_flags`` marks which rows hold malicious clients (the
    caller's slot flags — full [m] on single-device paths, this device's
    local block on shard_map paths); ``active`` is the scalar schedule
    gate (None = always on, the trivial-schedule fast path). A None
    flags argument is a wiring bug on the caller's dispatch surface, not
    a soft degrade: an attack silently not applied would corrupt every
    scenario-matrix row downstream, so fail at trace time."""
    if not in_jit(cfg):
        return stacked_updates
    if corrupt_flags is None:
        raise ValueError(
            f"--attack {cfg.attack} transforms updates in-jit and needs "
            f"the corrupt-slot flags; this dispatch surface has no flag "
            f"channel (host-sampled chained blocks) — run device-resident "
            f"or cohort-sampled")
    with jax.named_scope("attack"):
        scale = update_scale(cfg, corrupt_flags, active, boost=boost)

        def leaf(u):
            s = scale.reshape((-1,) + (1,) * (u.ndim - 1))
            return (u.astype(jnp.float32) * s).astype(u.dtype)
        return tree.map(leaf, stacked_updates)


def schedule_active(cfg, rnd):
    """Replicated scalar schedule gate for round ``rnd`` (None when the
    attack needs no gate — always-on or not in-jit)."""
    if not needs_round(cfg):
        return None
    if rnd is None:
        raise ValueError(
            f"--attack {cfg.attack} with a schedule needs the round index "
            f"in-program, but this dispatch surface has no round channel "
            f"(host-sampled mode) — run device-resident or "
            f"cohort-sampled, or drop attack_start/attack_stop/"
            f"attack_every")
    return schedule.active(cfg, rnd)


def stamp_for_agent(cfg, agent_id: int):
    """Corrupt agent ``agent_id``'s trigger stamp under the selected
    strategy — THE stamp source for the dense build, the bank-row gather
    and any future data surface (attack/poison.poison_client_row routes
    here, so every path stamps bitwise-identical pixels)."""
    if get(cfg).data_mode == "split":
        from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
            dba)
        return dba.stamp_for_agent(cfg, agent_id)
    from defending_against_backdoors_with_robust_learning_rate_tpu.attack.patterns import (
        build_stamp)
    return build_stamp(cfg.data, cfg.pattern_type, agent_idx=agent_id,
                       data_dir=cfg.data_dir)


def banner(cfg) -> Optional[str]:
    """Driver log line for a non-default attack config."""
    strat = get(cfg)
    if strat.name == "static":
        return None
    msg = f"[attack] {strat.name}: {strat.summary}"
    if strat.in_jit:
        msg += f"; boost x{cfg.attack_boost}"
        if not schedule.is_trivial(cfg):
            stop = cfg.attack_stop if cfg.attack_stop else "inf"
            msg += (f"; schedule rounds [{cfg.attack_start}, {stop}) "
                    f"every {cfg.attack_every}")
    return msg
