"""Attack schedules: is the adversary active this round?

A pure function of the traced round index — the exact idiom
service/churn.py established for client lifecycles: no sequential state,
so per-round dispatch, chained `lax.scan` blocks and a crash-resumed
service all reconstruct the identical attack history from the config
alone, and every device of a mesh computes the same replicated answer
with zero collectives.

Three shapes compose from the same three fields (rounds are 1-based,
matching the driver's dispatch schedule):

- **late start** (``--attack_start r``): dormant until round r — the
  model-replacement regime of arXiv:1807.00459 (attack near convergence,
  when honest gradients are small and a boosted update survives
  averaging);
- **one-shot** (``--attack_start r --attack_stop r+1``): exactly one
  poisoned round;
- **intermittent** (``--attack_every n``): every n-th round from
  ``attack_start``, the low-duty-cycle attacker that dodges
  rate-triggered defenses.

The schedule gates the *in-jit update strategies* (attack/boost.py,
attack/signflip.py). The data-poisoning strategies (static, dba) stamp
client shards at construction time — there is no per-round data to gate —
so a non-trivial schedule on them is refused loudly
(attack/registry.check).
"""

from __future__ import annotations

import jax.numpy as jnp


def is_trivial(cfg) -> bool:
    """True when the schedule is the always-on default — the round index
    is then not needed in-program (fl/rounds.step_takes_round)."""
    return (cfg.attack_start, cfg.attack_stop, cfg.attack_every) == (0, 0, 1)


def check(cfg) -> None:
    """Validate the schedule fields (registry.check calls this)."""
    if cfg.attack_start < 0:
        raise ValueError(f"--attack_start must be >= 0, got "
                         f"{cfg.attack_start}")
    if cfg.attack_every < 1:
        raise ValueError(f"--attack_every must be >= 1, got "
                         f"{cfg.attack_every}")
    if cfg.attack_stop < 0 or (cfg.attack_stop > 0
                               and cfg.attack_stop <= cfg.attack_start):
        raise ValueError(
            f"--attack_stop must be 0 (never) or > --attack_start for a "
            f"non-empty active window, got stop={cfg.attack_stop} "
            f"start={cfg.attack_start}")


def active(cfg, rnd):
    """Scalar bool: is the attack active at round ``rnd``?

    ``rnd`` may be a traced int32 (the round program's lead argument) or
    a Python int (host-side mirror — same jnp ops, bit-identical
    answer)."""
    rnd = jnp.asarray(rnd, jnp.int32)
    on = rnd >= cfg.attack_start
    if cfg.attack_stop > 0:
        on = on & (rnd < cfg.attack_stop)
    if cfg.attack_every > 1:
        on = on & ((rnd - cfg.attack_start) % cfg.attack_every == 0)
    return on


def active_traced(start, stop, every, rnd):
    """`active` with the schedule fields as TRACED int32 values — the
    multi-tenant pack's gate (fl/tenancy.py), where every tenant carries
    its own (start, stop, every) triple as [E]-vector knobs and the
    Python-level `if`s above cannot branch per tenant. Fully-traced
    equivalents of the same three conditions: a trivial (0, 0, 1)
    schedule evaluates to always-on, matching the solo paths' gate-free
    fast path arithmetically."""
    rnd = jnp.asarray(rnd, jnp.int32)
    start = jnp.asarray(start, jnp.int32)
    stop = jnp.asarray(stop, jnp.int32)
    every = jnp.asarray(every, jnp.int32)
    on = rnd >= start
    on = on & ((stop <= 0) | (rnd < stop))
    # every >= 1 is validated at pack construction; % every is safe
    on = on & ((rnd - start) % jnp.maximum(every, 1) == 0)
    return on
