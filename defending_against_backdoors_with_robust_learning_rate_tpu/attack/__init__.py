from defending_against_backdoors_with_robust_learning_rate_tpu.attack.patterns import (  # noqa: F401
    Stamp,
    build_stamp,
    apply_stamp,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.attack.poison import (  # noqa: F401
    select_poison_idxs,
    poison_agent_shards,
    build_poisoned_val,
)
from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (  # noqa: F401
    registry,
)
