"""Online RLR-threshold adaptation: the defense side of the adaptive
scenario matrix.

The RLR threshold θ is a *program constant* — the paper tunes it offline
per experiment. An adaptive attacker (attack/signflip.py) specifically
manufactures the regime a fixed θ handles worst: honest vote margins
collapse until the chosen θ stops separating backdoor coordinates from
honest ones. The continuous-service driver already computes the
mechanism's state every round, in-jit, on every path (obs/telemetry.py:
flip fraction, vote-margin histogram, honest/corrupt cosine split) and
drains it to the host — this module closes the loop: a deterministic
host-side controller reads the mid-run ``Defense/*`` telemetry at eval
boundaries and recommends threshold moves, which ``service.driver.serve``
applies by rebuilding the round programs from the boundary's checkpoint
(``--rlr_adapt on``; the AOT bank + persistent XLA cache make a revisited
threshold a cache hit, not a recompile).

The policy (``recommend_threshold``) is a pure function — unit-tested
against synthetic telemetry, reproducible in every re-run:

- **raise θ** when the electorate is splitting under the defense's nose:
  the low-margin mass of the vote-margin histogram is large (the
  adaptive-attack signature, arXiv:2303.03320) — or the cosine split
  shows corrupt updates anti-aligned with the aggregate — while the flip
  fraction says the current θ is barely biting.
- **lower θ** when the defense is flipping most coordinates
  (over-defense: honest progress is being reversed wholesale).
- hysteresis: moves are ±1 per decision, at most one decision per
  ``--rlr_adapt_every`` eval boundaries, clamped to [1, m-1].
"""

from __future__ import annotations

from typing import Dict, Optional

# policy constants (documented in recommend_threshold's docstring)
LOW_MARGIN_MASS_HI = 0.25   # histogram mass below m/2 that reads as
                            # "electorate splitting"
FLIP_FRAC_LO = 0.05         # defense barely biting
FLIP_FRAC_HI = 0.50         # defense reversing most coordinates
COS_SPLIT = 0.10            # |cosine| gap that reads as a corrupt
                            # anti-alignment signature


def low_margin_mass(margin_hist) -> float:
    """Fraction of coordinates in the lower half of the vote-margin
    buckets (margins below ~m/2)."""
    n = len(margin_hist)
    return float(sum(margin_hist[: max(1, n // 2)]))


def recommend_threshold(thr: int, m: int, flip_frac: float,
                        margin_hist, cos_honest: Optional[float] = None,
                        cos_corrupt: Optional[float] = None) -> int:
    """The pure adaptation policy: next θ given one boundary's telemetry.

    Returns a value in [1, m-1]; equal to ``thr`` when no move is
    warranted. See the module docstring for the rationale of each rule.
    """
    if flip_frac >= FLIP_FRAC_HI:
        return max(1, thr - 1)
    splitting = low_margin_mass(margin_hist) >= LOW_MARGIN_MASS_HI
    anti_aligned = (cos_honest is not None and cos_corrupt is not None
                    and cos_honest > COS_SPLIT
                    and cos_corrupt < -COS_SPLIT)
    if (splitting or anti_aligned) and flip_frac <= FLIP_FRAC_LO:
        return min(max(1, m - 1), thr + 1)
    return thr


class ThresholdController:
    """Stateful wrapper the service driver owns: validates the config,
    rate-limits decisions, and tracks the current θ across engine
    rebuilds (serve passes the controller through its adaptation
    restarts, so the cadence survives them)."""

    def __init__(self, cfg):
        if cfg.robustLR_threshold <= 0:
            raise ValueError("--rlr_adapt on needs the RLR defense "
                             "enabled (--robustLR_threshold > 0)")
        if cfg.telemetry != "full":
            raise ValueError(
                "--rlr_adapt on adapts from the vote-margin histogram "
                "and cosine split — run with --telemetry full")
        if not cfg.checkpoint_dir:
            raise ValueError(
                "--rlr_adapt on rebuilds the round programs from the "
                "boundary checkpoint — set --checkpoint_dir")
        self.thr = int(cfg.robustLR_threshold)
        self.m = int(cfg.agents_per_round)
        self.every = max(1, cfg.rlr_adapt_every)
        self.moves = []           # [(round, from, to)] decision log
        self._boundaries = 0

    def consider(self, defense: Optional[Dict], rnd: int) -> Optional[int]:
        """One eval boundary's decision: the new θ when a move is
        warranted (and due under the cadence), else None. ``defense`` is
        the host-fetched telemetry snapshot
        (obs/telemetry.host_summary — train.py stashes it per
        boundary)."""
        if not defense or "tel_flip_frac" not in defense:
            return None
        hist = defense.get("tel_margin_hist")
        if hist is None:
            return None
        self._boundaries += 1
        if self._boundaries % self.every:
            return None
        new = recommend_threshold(
            self.thr, self.m, defense["tel_flip_frac"], hist,
            defense.get("tel_cos_honest"), defense.get("tel_cos_corrupt"))
        if new == self.thr:
            return None
        # the decision as a typed ledger record (obs/events.py): the
        # controller is carried through serve's re-entries, so each move
        # is emitted exactly once, at the boundary that decided it
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            events as obs_events)
        obs_events.emit("adapt/move", round=rnd,
                        thr_from=self.thr, thr_to=new)
        self.moves.append((rnd, self.thr, new))
        self.thr = new
        return new
