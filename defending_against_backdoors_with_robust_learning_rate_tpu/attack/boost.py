"""Model-replacement boosting: scale corrupt updates to survive averaging.

The classic backdoor amplifier ("How To Backdoor Federated Learning",
arXiv:1807.00459): with m clients averaged, a single attacker's update is
diluted by ~1/m, so the attacker submits ``boost * u`` — at boost ≈ m the
poisoned model *replaces* the average. Weighted FedAvg dilutes by the
sample-size weights instead, so the effective replacement factor is
``boost * w_corrupt / sum(w)``.

What the defenses see:

- plain FedAvg: defeated — the boosted update dominates the weighted sum
  (tests/test_attack.py pins poison accuracy rising on a quick CPU
  config);
- RLR: the vote is on *signs*, which boosting cannot change — backdoor
  coordinates still lack the honest-agreement margin, their learning rate
  flips, and the boosted magnitude is applied in the WRONG direction
  (the paper's mechanism, held by the same test);
- ``--payload_norm_cap``: a boosted update's L2 norm grows by exactly
  ``boost``, so server-side validation masks it out — the attack is
  applied BEFORE payload validation in the round body precisely so this
  interaction is real.

The transform is a per-row multiplicative scale on the stacked updates —
elementwise, layout-blind (vmap and megabatch hand over the same
[m, ...] tree) and collective-free (the corrupt flags and the schedule
gate arrive replicated on every device of a mesh).
"""

from __future__ import annotations

import jax.numpy as jnp


def scale_rows(corrupt_flags, active, boost: float):
    """[m] f32 multiplicative row scale: ``boost`` on corrupt slots while
    the schedule is active, 1 elsewhere. ``active`` is a scalar bool (or
    None = always on)."""
    hit = corrupt_flags if active is None else corrupt_flags & active
    return jnp.where(hit, jnp.float32(boost), jnp.float32(1.0))
