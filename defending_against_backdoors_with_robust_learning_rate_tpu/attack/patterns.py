"""Trojan-pattern stamp library.

Reference semantics (src/utils.py:181-284, `add_pattern_bd`) re-expressed as
precomputed (mask, value, mode) stamps so the hot path is a single vectorized
`jnp.where`/add instead of Python pixel loops. Exact geometry parity:

fmnist (raw uint8 pixels, pre-normalization):
  - square    : x[21:26, 21:26] = 255                       (utils.py:227-230)
  - plus      : start=5, size=5; vertical col 5 rows 5..9;
                horizontal row 7 cols 3..7; value 255        (utils.py:244-253)
  - copyright / apple : additive inverted watermark, uint8 add *wraps mod 256*
                (utils.py:232-242; quirk SURVEY.md 2.3.10, reproduced)

fedemnist (float pixels, already normalized):
  - square    : x[21:26, 21:26] = 0                          (utils.py:256-259)
  - plus      : start=8, size=5; vertical col 8 rows 8..12;
                horizontal row 10 cols 6..10; value 0        (utils.py:275-282)
  - copyright / apple : x -= watermark/255                   (utils.py:261-273)

cifar10 (raw uint8, all 3 channels; only 'plus' exists — other pattern types
stamp nothing but poisoning still flips labels, as in the reference where
`add_pattern_bd` falls through and `poison_dataset` relabels anyway):
  - plus, agent_idx == -1 (full pattern, used for the poisoned val set):
      vertical col 5 rows 5..11; horizontal row 8 cols 2..8  (utils.py:192-201)
  - Distributed Backdoor Attack slices by agent_idx % 4      (utils.py:202-224):
      0: vertical rows 5..8      1: vertical rows 9..11
      2: horizontal cols 2..6    3: horizontal cols 5..8
    value 0.

Watermark assets: the reference loads `../watermark.png` / `../apple.png` with
cv2 (utils.py:233-241). We look for them in `data_dir`; if absent we fall back
to a deterministic procedural watermark so the pattern type stays functional
in asset-free environments (documented divergence).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import numpy as np

# stamp modes
SET = "set"            # x[mask] = value
ADD_WRAP_U8 = "addu8"  # x = uint8(x + value)  (wraps mod 256, quirk-parity)
SUB_FLOAT = "subf"     # x = x - value


@dataclasses.dataclass(frozen=True)
class Stamp:
    mode: str
    mask: np.ndarray          # [H, W] bool — where the pattern applies
    value: np.ndarray         # [H, W] float32 — pattern value / additive trojan

    @property
    def is_empty(self) -> bool:
        return not bool(self.mask.any()) and self.mode == SET


def _plus_mask(h: int, w: int, start: int, size: int,
               vert_rows: range, horiz_cols: range) -> np.ndarray:
    m = np.zeros((h, w), dtype=bool)
    for i in vert_rows:
        m[i, start] = True
    for j in horiz_cols:
        m[start + size // 2, j] = True
    return m


def _asset_search_path(data_dir: str):
    """Where the watermark/apple PNGs are looked for, in order: the
    `RLR_ASSET_DIR` env var, the data dir and its parent (the reference
    loads `../watermark.png` relative to src/, utils.py:233), and an
    `assets/` dir next to the package. The assets are MIT-licensed images
    from the reference repo; drop them in any of these (or point
    RLR_ASSET_DIR at a checkout) to get pixel-parity stamps."""
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = os.environ.get("RLR_ASSET_DIR")
    return tuple(p for p in (
        env, data_dir, ".", os.path.dirname(data_dir or "."),
        os.path.join(os.path.dirname(here), "assets")) if p)


def _load_watermark(name: str, data_dir: str) -> Optional[np.ndarray]:
    """cv2-load + invert + resize to 28x28, as utils.py:233-241."""
    for base in _asset_search_path(data_dir):
        path = os.path.join(base or ".", name)
        if os.path.exists(path):
            try:
                import cv2
                img = cv2.imread(path, cv2.IMREAD_GRAYSCALE)
                if img is None:
                    continue
                img = cv2.bitwise_not(img)
                return cv2.resize(img, dsize=(28, 28),
                                  interpolation=cv2.INTER_CUBIC).astype(np.float32)
            except Exception:
                continue
    return None


def _procedural_watermark(name: str) -> np.ndarray:
    """Deterministic stand-in when the reference PNG assets are absent."""
    rng = np.random.default_rng(abs(hash(name)) % (2 ** 31))
    base = (rng.random((7, 7)) > 0.5).astype(np.float32) * 255.0
    return np.kron(base, np.ones((4, 4), dtype=np.float32))  # 28x28 blocky mark


def build_stamp(data: str, pattern_type: str, agent_idx: int = -1,
                data_dir: str = "./data") -> Stamp:
    """Build the (mask, value, mode) stamp for a dataset/pattern/DBA-slice combo.

    `agent_idx=-1` is the full (unpartitioned) pattern, used for the poisoned
    *validation* set (src/federated.py:42-45); training poisoning passes the
    corrupt agent's id (src/agent.py:19-25), which only changes the geometry
    for cifar10 'plus' (the DBA split, utils.py:202-224).
    """
    if data == "fmnist":
        h = w = 28
        if pattern_type == "square":
            m = np.zeros((h, w), dtype=bool)
            m[21:26, 21:26] = True
            return Stamp(SET, m, np.full((h, w), 255.0, np.float32))
        if pattern_type == "plus":
            start, size = 5, 5
            m = _plus_mask(h, w, start, size,
                           range(start, start + size),
                           range(start - size // 2, start + size // 2 + 1))
            return Stamp(SET, m, np.full((h, w), 255.0, np.float32))
        if pattern_type in ("copyright", "apple"):
            name = "watermark.png" if pattern_type == "copyright" else "apple.png"
            troj = _load_watermark(name, data_dir)
            if troj is None:
                troj = _procedural_watermark(name)
            return Stamp(ADD_WRAP_U8, np.ones((h, w), dtype=bool), troj)

    elif data == "fedemnist":
        h = w = 28
        if pattern_type == "square":
            m = np.zeros((h, w), dtype=bool)
            m[21:26, 21:26] = True
            return Stamp(SET, m, np.zeros((h, w), np.float32))
        if pattern_type == "plus":
            start, size = 8, 5
            m = _plus_mask(h, w, start, size,
                           range(start, start + size),
                           range(start - size // 2, start + size // 2 + 1))
            return Stamp(SET, m, np.zeros((h, w), np.float32))
        if pattern_type in ("copyright", "apple"):
            name = "watermark.png" if pattern_type == "copyright" else "apple.png"
            troj = _load_watermark(name, data_dir)
            if troj is None:
                troj = _procedural_watermark(name)
            return Stamp(SUB_FLOAT, np.ones((h, w), dtype=bool), troj / 255.0)

    elif data in ("cifar10", "synthetic"):
        h = w = 32 if data == "cifar10" else 8
        m = np.zeros((h, w), dtype=bool)
        if pattern_type == "plus" and data == "cifar10":
            start, size = 5, 6
            if agent_idx == -1:
                for i in range(start, start + size + 1):
                    m[i, start] = True
                for j in range(start - size // 2, start + size // 2 + 1):
                    m[start + size // 2, j] = True
            elif agent_idx % 4 == 0:      # upper vertical (utils.py:205-208)
                for i in range(start, start + size // 2 + 1):
                    m[i, start] = True
            elif agent_idx % 4 == 1:      # lower vertical (utils.py:210-214)
                for i in range(start + size // 2 + 1, start + size + 1):
                    m[i, start] = True
            elif agent_idx % 4 == 2:      # left horizontal (utils.py:216-219)
                for j in range(start - size // 2, start + size // 4 + 1):
                    m[start + size // 2, j] = True
            else:                          # right horizontal (utils.py:221-224)
                for j in range(start - size // 4 + 1, start + size // 2 + 1):
                    m[start + size // 2, j] = True
        elif data == "synthetic":
            # small-image stand-in pattern: 3x3 corner block set to max
            m[:3, :3] = True
            return Stamp(SET, m, np.full((h, w), 255.0, np.float32))
        # cifar10 with a non-plus pattern: empty stamp (labels still flip,
        # matching the reference fall-through, utils.py:188-224)
        return Stamp(SET, m, np.zeros((h, w), np.float32))

    raise ValueError(f"no stamp for data={data!r} pattern={pattern_type!r}")


def apply_stamp(x, stamp: Stamp):
    """Apply a stamp to images shaped [..., H, W, C] (numpy or jax arrays).

    Works under jit: mask/value are compile-time constants. Input may be raw
    uint8 (fmnist/cifar10) or float (fedemnist); output dtype == input dtype
    for SET/ADD_WRAP_U8, float for SUB_FLOAT on float input.
    """
    import jax.numpy as jnp

    is_np = isinstance(x, np.ndarray)
    xp = np if is_np else jnp
    mask = stamp.mask[..., None]            # [H, W, 1] broadcast over channels
    if stamp.mode == SET:
        val = stamp.value[..., None].astype(np.float32)
        out = xp.where(mask, val.astype(x.dtype), x)
        return out
    if stamp.mode == ADD_WRAP_U8:
        troj = stamp.value[..., None].astype(np.uint8)
        out = (x.astype(xp.uint8) + troj)   # uint8 add wraps mod 256
        return xp.where(mask, out, x).astype(x.dtype)
    if stamp.mode == SUB_FLOAT:
        troj = stamp.value[..., None].astype(np.float32)
        out = x.astype(xp.float32) - troj
        return xp.where(mask, out, x.astype(xp.float32))
    raise ValueError(stamp.mode)
