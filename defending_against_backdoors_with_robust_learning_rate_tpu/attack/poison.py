"""Backdoor poisoning over agent-stacked arrays.

Reference behavior (src/utils.py:160-178 `poison_dataset`, src/agent.py:19-25):
the first `num_corrupt` agents poison their local slice at construction time —
`floor(poison_frac * |base-class idxs in slice|)` uniformly-sampled samples get
the trojan stamped onto the *raw stored pixels* (pre-normalization) and the
label flipped to `target_class`. The poisoned validation set is every
base-class val sample, fully trojaned (`poison_all=True`, full pattern
`agent_idx=-1`), relabeled (src/federated.py:42-45).

TPU-native differences:
- index selection is host-side, deterministic under a seeded numpy Generator
  (reference uses unseeded `random.sample`, utils.py:166; SURVEY.md 2.3.12);
- the stamp itself is a vectorized transform (attack/patterns.py) that can be
  applied either host-side at setup or on-device under jit.
"""

from __future__ import annotations

import math
from typing import Tuple

import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.attack.patterns import (
    build_stamp, apply_stamp)


def select_poison_idxs(labels: np.ndarray, base_class: int, frac: float,
                       rng: np.random.Generator,
                       valid: np.ndarray | None = None) -> np.ndarray:
    """Uniform sample of floor(frac * count) base-class indices (utils.py:161-166)."""
    cand = labels == base_class
    if valid is not None:
        cand = cand & valid
    cand_idxs = np.nonzero(cand)[0]
    k = math.floor(frac * len(cand_idxs))
    if k == 0:
        return np.zeros((0,), dtype=np.int64)
    return rng.choice(cand_idxs, size=k, replace=False)


def poison_client_row(images_row: np.ndarray, labels_row: np.ndarray,
                      size: int, agent_id: int, cfg, *, stamp=None,
                      seed_offset: int = 1234) -> np.ndarray:
    """Poison ONE client's padded row *in place* — the per-agent body of
    `poison_agent_shards`, factored out so the cohort-gather path
    (data/bank.py: rows materialized per sampled cohort member, not at
    build time) stamps bitwise-identical pixels: the index choice is a
    pure function of (cfg.seed, agent_id) and the row content, never of
    when or how often the row is gathered.

    images_row: [max_n, H, W, C] raw pixels; labels_row: [max_n];
    `size` the true shard length. Returns the [max_n] poison mask.

    The stamp geometry comes from the attack registry
    (attack/registry.stamp_for_agent): `--attack static` resolves to the
    legacy per-agent stamp bitwise (this function's historical behavior),
    `--attack dba` to the agent's round-robin shard of the full pattern
    (attack/dba.py). Index choice and label flip are strategy-blind."""
    max_n = labels_row.shape[0]
    mask = np.zeros((max_n,), dtype=bool)
    if stamp is None:
        from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
            registry as attack_registry)
        stamp = attack_registry.stamp_for_agent(cfg, agent_id)
    rng = np.random.default_rng(cfg.seed + seed_offset + agent_id)
    valid = np.arange(max_n) < size
    idxs = select_poison_idxs(labels_row, cfg.base_class, cfg.poison_frac,
                              rng, valid=valid)
    if len(idxs) == 0:
        return mask
    images_row[idxs] = np.asarray(
        apply_stamp(images_row[idxs], stamp)).astype(images_row.dtype)
    labels_row[idxs] = cfg.target_class
    mask[idxs] = True
    return mask


def poison_agent_shards(images: np.ndarray, labels: np.ndarray,
                        sizes: np.ndarray, cfg, *,
                        seed_offset: int = 1234) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Poison the local slices of the first cfg.num_corrupt agents, in place
    on copies of the stacked arrays.

    images: [K, max_n, H, W, C] raw pixels; labels: [K, max_n]; sizes: [K].
    Returns (images, labels, poison_mask[K, max_n]).
    """
    images = images.copy()
    labels = labels.copy()
    K, max_n = labels.shape
    poison_mask = np.zeros((K, max_n), dtype=bool)
    for aid in range(min(cfg.num_corrupt, K)):
        poison_mask[aid] = poison_client_row(images[aid], labels[aid],
                                             int(sizes[aid]), aid, cfg,
                                             seed_offset=seed_offset)
    return images, labels, poison_mask


def build_poisoned_val(val_images: np.ndarray, val_labels: np.ndarray,
                       cfg) -> Tuple[np.ndarray, np.ndarray]:
    """All base-class val samples, fully trojaned and relabeled
    (src/federated.py:42-45 with poison_all=True, agent_idx=-1)."""
    idxs = np.nonzero(val_labels == cfg.base_class)[0]
    stamp = build_stamp(cfg.data, cfg.pattern_type, agent_idx=-1,
                        data_dir=cfg.data_dir)
    imgs = np.asarray(apply_stamp(val_images[idxs], stamp)).astype(val_images.dtype)
    lbls = np.full((len(idxs),), cfg.target_class, dtype=val_labels.dtype)
    return imgs, lbls
