"""Distributed trigger splitting (DBA): each corrupt client stamps a
shard of the trojan pattern.

"DBA: Distributed Backdoor Attacks against Federated Learning"
(ICLR 2020): instead of every attacker stamping the full trigger, the
pattern's pixels are partitioned across the corrupt cohort — each local
trigger is smaller (harder to spot, smaller update perturbation per
client), while the poisoned *validation* trigger stays the full pattern
(attack/poison.build_poisoned_val, agent_idx=-1), which only fires when
the global model has composed all the shards.

The reference repo hard-codes a 4-way split of the cifar10 'plus'
geometry (attack/patterns.py, utils.py:202-224) — that remains the
``static`` strategy's behavior for exact parity. THIS module is the
generic registry strategy (``--attack dba``): the FULL pattern's stamped
coordinates are dealt round-robin (row-major order) across all
``num_corrupt`` agents, for every dataset and pattern type.

Host-side data poisoning only — the split changes which pixels each
corrupt client's shard stamps at construction/gather time
(attack/poison.poison_client_row routes its stamp through
registry.stamp_for_agent), never the traced program.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from defending_against_backdoors_with_robust_learning_rate_tpu.attack.patterns import (
    Stamp, build_stamp)


def split_stamp(stamp: Stamp, shard_idx: int, n_shards: int) -> Stamp:
    """Shard ``shard_idx`` of an ``n_shards``-way round-robin deal of the
    stamp's masked coordinates (row-major order): coordinate j of the
    flattened True-mask positions belongs to shard j % n_shards. The
    shards partition the full pattern exactly — stamping all of them
    reproduces the full stamp bitwise."""
    if n_shards <= 0:
        raise ValueError(f"n_shards must be positive, got {n_shards}")
    ys, xs = np.nonzero(stamp.mask)
    keep = np.arange(len(ys)) % n_shards == shard_idx % n_shards
    mask = np.zeros_like(stamp.mask)
    mask[ys[keep], xs[keep]] = True
    return dataclasses.replace(stamp, mask=mask)


def stamp_for_agent(cfg, agent_id: int) -> Stamp:
    """Corrupt agent ``agent_id``'s trigger shard: the FULL pattern
    (agent_idx=-1 geometry) split num_corrupt ways."""
    full = build_stamp(cfg.data, cfg.pattern_type, agent_idx=-1,
                      data_dir=cfg.data_dir)
    return split_stamp(full, agent_id, max(1, cfg.num_corrupt))
