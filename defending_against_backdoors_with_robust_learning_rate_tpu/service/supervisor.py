"""Unit supervision: deadline + exponential-backoff retry with failure
classification.

The r4/r5 TPU sessions survived (or didn't) on EXTERNAL babysitting:
`tpu_watch.sh` probing the backend, `run_bench` growing stall clocks off
stderr bytes, and a `kill` as the only remedy. The service driver replaces
that with in-process supervision: every dispatch / eval / checkpoint unit
runs under this supervisor, which

- **classifies** a failure before reacting:
  * ``transient`` — the error message carries an RPC/XLA retry-worthy
    signature (UNAVAILABLE, RESOURCE_EXHAUSTED, connection reset, ...):
    retry with exponential backoff;
  * ``wedged``    — the unit ran into a deadline/timeout (a stalled drain
    flush, a unit past ``--service_deadline_s``): retry, and let the
    driver degrade (sync-metrics fallback, skipped eval) when retries
    drain;
  * ``poisoned``  — a deterministic error (shape mismatch, NaN abort,
    assertion): retrying would reproduce it, so fail fast and let the
    driver's degradation policy decide what to drop.
- **consumes the heartbeat's stall vocabulary** instead of stderr
  heuristics: the wedge budget defaults to obs/heartbeat.py's
  ``DEFAULT_STALE_S`` (the same constant the external watchers key on),
  and every retry/backoff transition is written INTO the heartbeat
  (phase="retry"/"backoff" + cumulative counters), so `status.json` shows
  the self-healing in progress rather than a silent gap the watchdogs
  would misread as a wedge.

Determinism: backoff is a pure function of the attempt index (no jitter —
the chaos tests replay schedules exactly); `sleep`/`clock` are injectable.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    events as obs_events, heartbeat as hb_mod)

# substrings that mark an error retry-worthy: the gRPC/absl status names
# XLA:TPU runtime errors carry, plus the socket-level strings a wedged
# tunnel produces. Case-sensitive on the status names (they are ALL-CAPS
# constants), case-insensitive on the prose.
TRANSIENT_SIGNATURES = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED", "ABORTED",
    "UNKNOWN: ", "INTERNAL: ",
    "connection reset", "connection refused", "broken pipe",
    "socket closed", "transport closed", "temporarily unavailable",
    "transient", "retry",
)

TRANSIENT, WEDGED, POISONED = "transient", "wedged", "poisoned"
RETRYABLE = (TRANSIENT, WEDGED)


def classify(exc: BaseException) -> str:
    """Failure class of one exception (see module docstring)."""
    if isinstance(exc, TimeoutError):
        return WEDGED
    text = f"{type(exc).__name__}: {exc}"
    low = text.lower()
    for sig in TRANSIENT_SIGNATURES:
        if (sig in text) if sig.isupper() else (sig in low):
            return TRANSIENT
    return POISONED


class UnitFailure(RuntimeError):
    """A unit that failed past its retry budget (or failed fast as
    poisoned). The driver's degradation policy dispatches on
    ``classification``."""

    def __init__(self, kind: str, unit, classification: str,
                 attempts: int, cause: BaseException):
        super().__init__(
            f"{kind} unit {unit}: {classification} failure after "
            f"{attempts} attempt(s): {type(cause).__name__}: {cause}")
        self.kind = kind
        self.unit = unit
        self.classification = classification
        self.attempts = attempts
        self.cause = cause


class Supervisor:
    """Retry/backoff/deadline wrapper around the engine's step methods."""

    def __init__(self, retries: int = 3, backoff_s: float = 0.25,
                 deadline_s: float = 0.0, hb=None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.deadline_s = float(deadline_s)
        self.hb = hb if hb is not None else hb_mod.NullHeartbeat()
        self._sleep = sleep
        self._clock = clock
        self.counters: Dict[str, int] = {
            "retries": 0, "transient": 0, "wedged": 0, "poisoned": 0,
            "gave_up": 0, "slow_units": 0}
        self.phases_seen: List[str] = []
        # optional incident hook, on_incident(kind, unit_round): the
        # driver wires the flight recorder's snapshot + the profile
        # trigger here so retries/give-ups/slow units leave evidence
        # even when the event ledger is off
        self.on_incident: Optional[Callable[[str, Optional[int]],
                                            None]] = None

    def _incident(self, kind: str, unit) -> None:
        if self.on_incident is None:
            return
        try:
            self.on_incident(kind,
                             unit if isinstance(unit, int) else None)
        except Exception:
            pass  # observability must never take down the run

    # ------------------------------------------------------------- helpers

    def stall_budget(self) -> float:
        """Wedge budget for host-side waits (drain flushes, payload
        fetches): the configured per-unit deadline, else the heartbeat
        module's stale budget — the SAME constant the external stall
        detectors use, so in-process self-healing triggers no later than
        an external killer would have."""
        return self.deadline_s if self.deadline_s > 0 \
            else hb_mod.DEFAULT_STALE_S

    def phase(self, phase: str, **fields) -> None:
        if not self.phases_seen or self.phases_seen[-1] != phase:
            self.phases_seen.append(phase)
        self.hb.update(phase=phase, force=True,
                       service_phases=self.phases_seen, **fields,
                       **self.counters)

    def backoff(self, attempt: int) -> float:
        """Deterministic exponential backoff for attempt N (0-based)."""
        return self.backoff_s * (2 ** attempt)

    # ----------------------------------------------------------------- run

    def run(self, kind: str, fn: Callable[[], Any], unit=None) -> Any:
        """Run one unit supervised. Returns fn()'s value; raises
        UnitFailure when the unit is poisoned or the retry budget is
        spent. KeyboardInterrupt/SystemExit always propagate — the
        supervisor heals the run, it does not trap the operator."""
        attempt = 0
        while True:
            t0 = self._clock()
            try:
                out = fn()
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — classified below
                cls = classify(e)
                self.counters[cls] += 1
                if cls not in RETRYABLE or attempt >= self.retries:
                    self.counters["gave_up"] += 1
                    self.phase("degraded", failed_kind=kind)
                    obs_events.emit("supervisor/give_up", severity="error",
                                    round=unit if isinstance(unit, int)
                                    else None,
                                    kind=kind, classification=cls,
                                    attempts=attempt + 1)
                    self._incident(f"supervisor/give_up:{kind}", unit)
                    raise UnitFailure(kind, unit, cls, attempt + 1, e) \
                        from e
                delay = self.backoff(attempt)
                attempt += 1
                self.counters["retries"] += 1
                print(f"[service] {kind} unit {unit}: {cls} failure "
                      f"({type(e).__name__}: {e}); retry "
                      f"{attempt}/{self.retries} after {delay:.2f}s")
                # one typed ledger record per retry: backoff_s is the
                # deterministic schedule value, not measured time, so the
                # record joins the twin-drill byte comparison
                obs_events.emit("supervisor/retry", severity="warn",
                                round=unit if isinstance(unit, int)
                                else None,
                                kind=kind, classification=cls,
                                attempt=attempt, backoff_s=delay)
                self._incident(f"supervisor/retry:{kind}", unit)
                self.phase("retry", retry_kind=kind)
                self.phase("backoff", retry_kind=kind)
                self._sleep(delay)
                continue
            elapsed = self._clock() - t0
            if self.deadline_s > 0 and elapsed > self.deadline_s:
                # the unit COMPLETED but blew its deadline — the wedge
                # signal for degradation policy (e.g. stop overlapping
                # eval), recorded rather than retried: the work is done
                self.counters["slow_units"] += 1
                print(f"[service] {kind} unit {unit}: completed but took "
                      f"{elapsed:.2f}s (deadline {self.deadline_s:.2f}s) "
                      f"— flagged wedged-slow")
                obs_events.emit("supervisor/slow", severity="warn",
                                round=unit if isinstance(unit, int)
                                else None, kind=kind)
                self._incident(f"supervisor/slow:{kind}", unit)
                self.phase("slow", slow_kind=kind)
            return out

    def heartbeat_fields(self) -> Dict[str, Any]:
        """Cumulative counters for status.json (the CI chaos drill asserts
        these survive to the final heartbeat)."""
        return {**self.counters, "service_phases": list(self.phases_seen)}
