"""Resident fleet scheduler: bin-packed, ledger-driven experiment
packing that never idles the chip (ISSUE 16).

The FIFO queue (service/queue.py --tenants E) packs shape-compatible
cells E at a time, but a pack only retires when its SLOWEST member
finishes and a failed or quarantined tenant leaves its slot computing
masked garbage for the rest of the run. This module closes that gap
with three layers on top of service/tenancy.PackEngine:

- `CapacityModel` — how many tenants fit the device: an ANALYTIC
  bytes-per-tenant estimate (params x dtype x workspace multiplier,
  buffered carry ~2x params — the r13 measurement) against the
  device-resident budget (utils/compile_cache.DEVICE_RESIDENT_BYTES),
  with a conservative cap on the CPU backend where host RAM backs the
  "HBM" and the model is uncalibrated. The r14 HBM-watermark bench
  (BENCH_NOTES.md) is the calibration source; until those numbers land
  the estimate deliberately over-counts (workspace x3) so the packer
  under-packs rather than OOMs.
- `plan_fleet` — deterministic bin-packing: cells group by their
  `tenant_pack_key` (the compile-cache fingerprint's own field algebra,
  exactly like the FIFO planner) into per-shape-class BINS of
  capacity-modelled width; ineligible cells fall to the serial path and
  cohort-sampled bins run as fixed FIFO packs (the shared bank gather
  serves ONE draw — no mid-run backfill, by construction).
- `Scheduler` — the pure slot state machine: width W slots + a pending
  deque, consuming LEDGER-SHAPED events (`scheduler/slot_done`,
  `health/incident`, `service/recover`, `scheduler/evict`) and emitting
  deterministic decisions (backfill slot e with the next queued cell /
  idle slot e). No jax, no clocks — a synthetic event stream drives it
  in tests exactly like the live loop does.
- `run_bin` — the resident loop: one PackEngine per bin, pack clock
  advancing in snap-blocks PAST `cfg.rounds`; a slot whose effective
  round (pack_round + rnd_offset) reaches `rounds` retires and its slot
  is backfilled at offset = -pack_round so the incoming cell's key
  streams and schedule gates replay its solo program exactly
  (fl/tenancy.TenantKnobs.rnd_offset); a per-tenant health enforcement
  failure evicts JUST that slot (record-and-skip — the queue rows the
  failure) and backfills it the same way. Every admit/evict/backfill/
  idle decision is also emitted on the queue's event ledger, so the
  live run and the synthetic-stream tests see the same records.

Throughput accounting: slot OCCUPANCY = busy-slot-dispatches over
total-slot-dispatches (idle slots compute masked garbage — the metric,
not a mask, accounts for the waste), and the fleet-level `cells/hour`
gauge rides the Prometheus textfile exporter plus a `fleet`
comparability group in trajectory.json (obs/trajectory.py), gated in CI
like every other perf number.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
    compile_cache)

# bytes-per-tenant multipliers (analytic; r14 calibration pending):
# params + server update + donation/eval scratch
WORKSPACE_FACTOR = 3.0
# buffered packs carry (params, state): sum + sign-vote accumulators
# measured ~2x params bytes at K <= m (BENCH_NOTES r13)
BUFFERED_STATE_FACTOR = 2.0
# share of the device budget reserved for the SHARED side (train stacks,
# eval sets, executables) before tenants bill against it
TENANT_BUDGET_FRACTION = 0.5
# CPU backend: host RAM backs the "HBM" budget and the analytic model is
# uncalibrated there — cap the pack width instead of trusting it
CPU_MAX_WIDTH = 8

_DTYPE_BYTES = {"f32": 4, "bf16": 2, "f16": 2}


class CapacityModel:
    """HBM-vs-E: how many resident tenants one device carries.

    Analytic until the r14 HBM-watermark bench lands (BENCH_NOTES.md —
    the calibration TODO is recorded there): per-tenant bytes =
    param_count x dtype_bytes x (1 + workspace) [+ buffered carry], and
    the tenant side of the device budget is TENANT_BUDGET_FRACTION of
    utils/compile_cache.DEVICE_RESIDENT_BYTES. Deliberately
    conservative — under-packing costs throughput, over-packing OOMs a
    resident fleet mid-run."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 backend: Optional[str] = None):
        self.budget = (compile_cache.DEVICE_RESIDENT_BYTES
                       if budget_bytes is None else int(budget_bytes))
        if backend is None:
            import jax
            backend = jax.default_backend()
        self.backend = backend

    def tenant_bytes(self, cfg) -> int:
        """Analytic per-tenant resident footprint (no device work: the
        param tree is shape-evaluated, never materialized)."""
        import jax
        from defending_against_backdoors_with_robust_learning_rate_tpu.fl import (
            buffered)
        from defending_against_backdoors_with_robust_learning_rate_tpu.models.registry import (
            get_model, init_params)
        model = get_model(cfg.data, cfg.model_arch, cfg.dtype,
                          remat=cfg.remat, remat_policy=cfg.remat_policy)
        shapes = jax.eval_shape(
            lambda: init_params(model, cfg.image_shape,
                                jax.random.PRNGKey(0)))
        n_params = sum(int(l.size) for l in jax.tree_util.tree_leaves(
            shapes))
        per = n_params * _DTYPE_BYTES.get(cfg.dtype, 4)
        mult = 1.0 + WORKSPACE_FACTOR
        if buffered.is_buffered(cfg):
            mult += BUFFERED_STATE_FACTOR
        return max(1, int(per * mult))

    def max_width(self, cfg, requested: int) -> int:
        """The pack width for this shape class: the user's E, clamped by
        what the budget fits (and by CPU_MAX_WIDTH on the CPU backend)."""
        tenant_budget = int(self.budget * TENANT_BUDGET_FRACTION)
        fit = max(1, tenant_budget // self.tenant_bytes(cfg))
        width = max(1, min(int(requested), fit))
        if self.backend == "cpu":
            width = min(width, CPU_MAX_WIDTH)
        return width


def plan_fleet(base_cfg, cells: List[Dict[str, Any]], tenants: int,
               apply_overrides: Callable,
               capacity: Optional[CapacityModel] = None
               ) -> List[Tuple[str, List[Dict[str, Any]], int]]:
    """Deterministic bin-packing: [(kind, cells, width)] with kind one of
    ``bin`` (scheduler-resident, backfilled), ``fifo`` (cohort packs —
    fixed membership, the shared gather admits no clock skew) or
    ``serial``. Grouping is by `tenant_pack_key` exactly like the FIFO
    planner (service/tenancy.plan_packs); width is capacity-modelled per
    shape class. Same cells + same capacity model => same plan (the
    determinism pin in tests/test_scheduler.py)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.service.tenancy import (
        serial_reason)
    if capacity is None:
        capacity = CapacityModel()
    groups: Dict[str, List[Dict[str, Any]]] = {}
    order: List[str] = []
    items: List[Tuple[str, List[Dict[str, Any]], int]] = []
    cfg0: Dict[str, Any] = {}
    for cell in cells:
        try:
            cfg = apply_overrides(base_cfg, cell["overrides"])
            reason = serial_reason(cfg)
            key = None if reason else compile_cache.tenant_pack_key(cfg)
        except Exception as e:
            reason, key = f"{type(e).__name__}: {e}", None
        if key is None:
            print(f"[scheduler] cell {cell['name']!r} -> serial "
                  f"({reason})")
            items.append(("serial", [cell], 1))
            continue
        if key not in groups:
            groups[key] = []
            order.append(key)
            cfg0[key] = cfg
        groups[key].append(cell)
    for key in order:
        group = groups[key]
        if len(group) < 2:
            print(f"[scheduler] cell {group[0]['name']!r} -> serial "
                  f"(no shape-compatible partner in this queue)")
            items.append(("serial", group, 1))
            continue
        width = capacity.max_width(cfg0[key], tenants)
        if compile_cache.is_cohort_mode(cfg0[key]):
            # cohort packs: fixed membership (no backfill — the shared
            # bank gather serves ONE cohort_seed-driven draw), chunked
            # to the capacity-modelled width like the FIFO planner
            for i in range(0, len(group), width):
                chunk = group[i:i + width]
                items.append(("fifo" if len(chunk) >= 2 else "serial",
                              chunk, min(width, len(chunk))))
        else:
            items.append(("bin", group, width))
    pos = {id(c): i for i, c in enumerate(cells)}
    items.sort(key=lambda it: pos[id(it[1][0])])
    return items


class Scheduler:
    """The pure slot state machine (no jax, no clocks): W slots, a
    pending deque, ledger-shaped events in, deterministic decisions out.

    Events consumed (the live loop emits the same names on the queue
    ledger, so a synthetic `read_events` stream replays a run exactly):

    - ``scheduler/slot_done``   — slot's cell completed; vacate+fill
    - ``scheduler/evict``       — slot evicted (health enforcement)
    - ``health/incident``       — a quarantine-triggering incident on
      the slot's tenant; treated as an eviction trigger
    - ``service/recover``       — the slot's tenant entered recovery;
      its slot backfills from the queue instead of idling

    Decisions: ``{"op": "backfill", "slot": e, "item": cell}`` or
    ``{"op": "idle", "slot": e}``. Backfill order IS queue order — the
    deque pops left, nothing reorders."""

    VACATE_EVENTS = ("scheduler/slot_done", "scheduler/evict",
                     "health/incident", "service/recover")

    def __init__(self, width: int, resident: List[Any],
                 pending: List[Any]):
        if len(resident) > width:
            raise ValueError(f"{len(resident)} resident items in "
                             f"{width} slots")
        self.width = width
        self.slots: List[Any] = list(resident) + [None] * (
            width - len(resident))
        self.pending = collections.deque(pending)
        self.decisions: List[Dict[str, Any]] = []

    def occupancy(self) -> float:
        return (sum(1 for s in self.slots if s is not None)
                / max(self.width, 1))

    def on_event(self, event: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Consume one ledger record; return the decisions it forces.
        Unknown events and events without a slot are no-ops (a live
        ledger interleaves queue/cell records the scheduler ignores)."""
        name = event.get("event")
        slot = event.get("slot")
        if name not in self.VACATE_EVENTS or slot is None:
            return []
        if not (0 <= int(slot) < self.width):
            return []
        return self._vacate(int(slot))

    def _vacate(self, slot: int) -> List[Dict[str, Any]]:
        if self.pending:
            item = self.pending.popleft()
            self.slots[slot] = item
            decision = {"op": "backfill", "slot": slot, "item": item}
        else:
            self.slots[slot] = None
            decision = {"op": "idle", "slot": slot}
        self.decisions.append(decision)
        return [decision]


def run_bin(base_cfg, bin_cells: List[Dict[str, Any]], width: int,
            qledger=None) -> Tuple[List[Dict[str, Any]], Dict[str, Any]]:
    """One shape-class bin through the resident loop: up to `width`
    cells live as PackEngine slots; the pack clock advances in
    snap-blocks until every cell has retired, with completed/evicted
    slots backfilled from the bin's queue at offset = -pack_round.

    Returns (one queue row per cell in COMPLETION order, bin stats for
    the fleet summary). Row schema matches the FIFO queue's pack rows
    (summary under SUMMARY_KEYS + a "tenancy" clause) plus a
    "scheduler" clause with the slot's admission/retirement rounds."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.service.queue import (
        SUMMARY_KEYS, _cell_cfg, _new_row)
    from defending_against_backdoors_with_robust_learning_rate_tpu.service.tenancy import (
        PackEngine)
    from defending_against_backdoors_with_robust_learning_rate_tpu.train import (
        dispatch_schedule)

    def emit(name, severity="info", **fields):
        if qledger is not None:
            qledger.emit(name, severity=severity, **fields)

    W = min(width, len(bin_cells))
    resident, pending = bin_cells[:W], bin_cells[W:]
    sched = Scheduler(W, resident, pending)
    t0 = time.perf_counter()
    emit("scheduler/bin_start", width=W, cells=len(bin_cells))
    rows: List[Dict[str, Any]] = []
    engine = PackEngine(
        [_cell_cfg(base_cfg, c) for c in resident],
        names=[c["name"] for c in resident],
        offsets=[0] * W, evict_on_anomaly=True)
    # per-slot bookkeeping the engine doesn't carry: the queue row under
    # construction and the slot's admission round/wall
    meta = [{"cell": c, "row": _new_row(base_cfg, c),
             "admitted_round": 0, "t_admit": t0} for c in resident]
    for e, c in enumerate(resident):
        emit("scheduler/admit", slot=e, cell=c["name"], round=0)
    busy = total = 0
    rounds, snap = engine.rounds, engine.snap

    def finish_row(e: int, ok: bool, pack_rnd: int,
                   summary: Optional[Dict[str, Any]] = None,
                   error: Optional[str] = None) -> None:
        m = meta[e]
        row = m["row"]
        now = time.perf_counter()
        # amortized share, matching the FIFO pack's wall/E billing
        row["wall_s"] = round((now - m["t_admit"]) / max(W, 1), 3)
        row["ok"] = ok
        if summary is not None:
            row["summary"] = {k: summary[k] for k in SUMMARY_KEYS
                              if k in summary}
        if error is not None:
            row["error"] = error
        row["tenancy"] = {"slot": e, "tenants": W, "rounds": rounds,
                          "compile_s": round(engine.compile_s, 3)}
        row["scheduler"] = {"admitted_round": m["admitted_round"],
                            "retired_round": pack_rnd,
                            "offset": engine.slots[e].offset}
        rows.append(row)

    def backfill(e: int, event_name: str, pack_rnd: int,
                 severity: str = "info") -> None:
        """Vacate slot e through the scheduler and load whatever it
        decides; a cell whose load fails is recorded-and-skipped and the
        slot asks again."""
        emit(event_name, severity=severity, slot=e, round=pack_rnd)
        decisions = sched.on_event({"event": event_name, "slot": e})
        while decisions:
            d = decisions[0]
            if d["op"] == "idle":
                engine.idle_slot(e)
                emit("scheduler/idle", slot=e, round=pack_rnd)
                return
            cell = d["item"]
            try:
                engine.load_slot(e, _cell_cfg(base_cfg, cell),
                                 cell["name"], offset=-pack_rnd)
            except Exception as err:  # record-and-skip, slot re-asks
                meta[e] = {"cell": cell, "row": _new_row(base_cfg, cell),
                           "admitted_round": pack_rnd,
                           "t_admit": time.perf_counter()}
                finish_row(e, ok=False, pack_rnd=pack_rnd,
                           error=f"{type(err).__name__}: {err}")
                emit("scheduler/load_failed", severity="warn", slot=e,
                     cell=cell["name"],
                     error=f"{type(err).__name__}: {err}")
                decisions = sched.on_event(
                    {"event": "scheduler/evict", "slot": e})
                continue
            meta[e] = {"cell": cell, "row": _new_row(base_cfg, cell),
                       "admitted_round": pack_rnd,
                       "t_admit": time.perf_counter()}
            emit("scheduler/backfill", slot=e, cell=cell["name"],
                 round=pack_rnd, offset=-pack_rnd)
            return

    pack_rnd = 0
    loop_ok = False
    # hard ceiling: every cell runs `rounds` rounds; with backfill only
    # at snap boundaries the worst case is one snap-block of slack per
    # cell per slot — anything past that is a livelock, not progress
    max_blocks = (len(bin_cells) + W) * ((rounds + snap - 1) // snap + 1)
    try:
        for _ in range(max_blocks):
            if not engine.active_slots():
                break
            units = dispatch_schedule(pack_rnd, pack_rnd + snap, snap,
                                      engine.chain_n, False,
                                      engine.chained_fn is not None)
            info = None
            for unit in units:
                rnd, info = engine.dispatch_unit(unit)
                busy += len(engine.active_slots()) * len(unit)
                total += W * len(unit)
            pack_rnd += snap
            errors = engine.eval_boundary(
                pack_rnd, info, pack_rnd,
                max(time.perf_counter() - t0, 1e-9))
            for e, err in sorted(errors.items()):
                finish_row(e, ok=False, pack_rnd=pack_rnd,
                           error=f"{type(err).__name__}: {err}")
                engine.fail_slot(e, err)
                emit("health/incident", severity="warn", slot=e,
                     cell=meta[e]["cell"]["name"], round=pack_rnd,
                     error=f"{type(err).__name__}: {err}")
                backfill(e, "scheduler/evict", pack_rnd,
                         severity="warn")
            for e in list(engine.active_slots()):
                if pack_rnd + engine.slots[e].offset >= rounds:
                    summary = engine.finalize_slot(e)
                    summary["rounds_per_sec"] = rounds / max(
                        time.perf_counter() - meta[e]["t_admit"], 1e-9)
                    finish_row(e, ok=True, pack_rnd=pack_rnd,
                               summary=summary)
                    backfill(e, "scheduler/slot_done", pack_rnd)
        else:
            raise RuntimeError(
                f"scheduler bin made no progress in {max_blocks} "
                f"snap-blocks ({len(rows)}/{len(bin_cells)} cells "
                f"retired)")
        loop_ok = True
    finally:
        engine.close(loop_ok)
        if not loop_ok:
            # cells still resident when the bin dies get failure rows —
            # the record-and-skip contract, bin-shaped
            for e in engine.active_slots():
                finish_row(e, ok=False, pack_rnd=pack_rnd,
                           error="bin aborted (see queue log)")

    wall = time.perf_counter() - t0
    stats = {"wall_s": round(wall, 3), "width": W,
             "busy_slot_rounds": busy, "total_slot_rounds": total,
             "slot_occupancy": round(busy / max(total, 1), 4),
             "compile_s": round(engine.compile_s, 3),
             "pack_rounds": pack_rnd}
    emit("scheduler/bin_done", cells=len(rows),
         ok=sum(1 for r in rows if r.get("ok")), **stats)
    print(f"[scheduler] bin done: {len(rows)} cells over {W} slots, "
          f"{pack_rnd} pack rounds, occupancy "
          f"{stats['slot_occupancy']:.0%}, {wall:.1f}s")
    return rows, stats
