"""The continuous-service driver: rounds stream indefinitely, supervised.

``train.run`` is one-shot — it assumes every dispatch lands, every eval
returns, and the process lives to ``cfg.rounds``. ``serve`` turns the same
RoundEngine into a long-running service (FL_PyTorch, arXiv:2202.03099,
frames exactly this simulator-as-service gap):

- **rounds stream** until ``--service_rounds`` is reached, or — with 0 —
  until ``<log_dir>/service.stop`` appears; the client population churns
  underneath via service/churn.py on every path (a host-sampled run
  under churn routes through the cohort program, sampling cohorts from
  the churn-present set — data/cohort.py).
- **every unit is supervised** (service/supervisor.py): dispatch, eval and
  checkpoint each run under deadline + exponential-backoff retry with
  failure classification. Degradation policy on exhausted retries:
  * eval failed        -> skip THIS boundary's eval (training continues;
                          ``Service/Evals_Skipped`` counts the damage);
  * checkpoint wedged  -> the async drain is stalled: close it (bounded)
                          and fall back to synchronous metrics for the
                          rest of the run, then checkpoint again;
  * dispatch poisoned  -> nothing sane to drop — exit loudly with the
                          journal intact (the next start resumes
                          crash-exactly).
- **crash-exact recovery**: before the metrics writer opens, the driver
  finds the newest digest-valid checkpoint (utils/checkpoint.py),
  truncates ``metrics.jsonl`` back to that round's journaled byte offset,
  and resumes — replayed rounds rewrite the identical rows, so an
  interrupted-and-resumed service produces a byte-identical metrics file
  (modulo wall-clock rows) to one that never crashed. A ``kill -9`` at
  ANY point (mid-round, mid-save, mid-journal) lands in one of the cases
  utils/checkpoint.py enumerates; tests/test_service.py drives them
  through service/chaos.py.

Entry point::

    python -m defending_against_backdoors_with_robust_learning_rate_tpu.service.driver \
        --data synthetic --service_rounds 64 --snap 4 \
        --churn_available 0.7 --checkpoint_dir ck --chaos kill@6
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import jax

from defending_against_backdoors_with_robust_learning_rate_tpu.config import (
    Config, args_parser)
from defending_against_backdoors_with_robust_learning_rate_tpu.health import (
    monitor as health_monitor)
from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    attribution as obs_attribution, events as obs_events,
    export as obs_export, trigger as obs_trigger)
from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
    chaos as chaos_mod, churn as churn_mod)
from defending_against_backdoors_with_robust_learning_rate_tpu.service.supervisor import (
    POISONED, Supervisor, UnitFailure, WEDGED)
from defending_against_backdoors_with_robust_learning_rate_tpu.train import (
    RoundEngine)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
    checkpoint as ckpt)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.metrics import (
    MetricsWriter, NullWriter, run_name)

STOP_FILE = "service.stop"

# the churn population census (churn.active_count) is an O(population)
# host-side draw — observability, never worth O(1M) work per boundary on
# the cohort-sampled population axis
CENSUS_MAX_POPULATION = 100_000


def _metrics_path(cfg: Config) -> str:
    return os.path.join(cfg.log_dir, run_name(cfg), "metrics.jsonl")


def _events_path(cfg: Config) -> str:
    return os.path.join(cfg.log_dir, run_name(cfg), "events.jsonl")


def prepare_crash_exact_resume(cfg: Config, truncate: bool = True) -> Dict:
    """Truncate the metrics stream to the journaled offset of the newest
    digest-valid checkpoint, BEFORE any writer opens the file; a fresh
    stream instead journals the file's current end as the round-0 splice
    base. Returns what the recovery report needs.
    ``boundary`` in the result says whether the writer should emit a
    ``_run/start`` record: yes on a fresh stream or a pre-journal append
    (readers must be able to split the runs), no on a crash-exact splice
    (the recovered file must byte-match an uninterrupted run's).
    ``truncate=False`` (non-lead processes) computes everything but leaves
    the file alone — only the lead writer may cut the shared stream."""
    info = {"resumed_from": 0, "metrics_offset": 0, "truncated_bytes": 0,
            "resume_upto": None, "boundary": True}
    if not cfg.checkpoint_dir:
        return info
    # the journal-AGREED round, not the newest digest-valid one: a kill
    # between ckpt.save and journal_record leaves a newer unjournaled
    # checkpoint whose metrics offset is unknown — resuming there would
    # truncate the whole stream. resume_upto pins the engine's restore to
    # the same round.
    rnd = ckpt.newest_resumable_round(cfg.checkpoint_dir)
    info["resumed_from"] = rnd or 0
    info["resume_upto"] = rnd or 0
    path = _metrics_path(cfg)
    size = os.path.getsize(path) if os.path.exists(path) else 0
    journal = ckpt.journal_read(cfg.checkpoint_dir)
    if rnd is not None and not journal:
        # pre-journal checkpoint dir: resumable, but no splice point
        # exists — append rather than drop rows that cannot be replayed
        print(f"[service] checkpoint dir has no round journal — resuming "
              f"from round {rnd} without the crash-exact metrics splice")
        info["metrics_offset"] = size
        return info
    if not journal:
        # fresh service stream: journal the current end of the (append-
        # across-runs) metrics file as the round-0 splice base, so a kill
        # before the first checkpoint resumes by truncating back to HERE —
        # never to 0, which would wipe rows earlier runs wrote
        if truncate:
            ckpt.journal_record(cfg.checkpoint_dir, 0, size)
        info["metrics_offset"] = size
        return info
    offset = ckpt.journal_offset_for(cfg.checkpoint_dir, rnd or 0)
    info["metrics_offset"] = offset
    # a splice past a real checkpoint continues that run mid-stream with no
    # extra record (byte-identity with an uninterrupted run); a round-0
    # base resume restarts the run, which an uninterrupted serve would
    # open with a boundary record
    info["boundary"] = not rnd
    if truncate and size > offset:
        with open(path, "r+b") as f:
            f.truncate(offset)
        info["truncated_bytes"] = size - offset
        print(f"[service] crash-exact resume: metrics.jsonl truncated "
              f"to the round-{rnd or 0} journal offset "
              f"({size - offset} bytes of un-checkpointed rows "
              f"dropped for exact replay)")
    return info


def serve(cfg: Config, writer: Optional[MetricsWriter] = None,
          max_rounds: Optional[int] = None, _adapt=None,
          _adapt_reentry: bool = False, _health=None,
          _phases: Optional[List[str]] = None, _ledger=None,
          _export=None) -> Dict:
    """Run the continuous service; returns the engine summary extended
    with a ``service`` section (retry/degradation counters, recovery
    info).

    With ``--rlr_adapt on`` the service additionally hosts the online
    defense-adaptation loop (attack/adapt.py): at eval boundaries the
    controller reads the drained Defense/* telemetry; when it recommends
    a threshold move, the current engine is torn down at the boundary
    checkpoint and serve re-enters with
    ``robustLR_threshold=<new>`` — same writer (one continuous metrics
    stream), same checkpoint dir, the controller carried through
    (``_adapt``) so its cadence and decision log survive the restart.
    Revisited thresholds are AOT/XLA cache hits, not recompiles.

    Observability plane (ISSUE 15): with ``--events on`` (the default)
    a lead-process event ledger (obs/events.py) records every lifecycle
    transition into ``<run_dir>/events.jsonl``; ``--metrics_port`` /
    ``--metrics_textfile`` arm the Prometheus exporter (obs/export.py).
    Both are carried through every re-entry (``_ledger`` / ``_export``)
    — one ledger stream and one scrape endpoint per logical run, whoever
    created them closes them."""
    lead = jax.process_index() == 0
    ledger, created_ledger = _ledger, False
    if ledger is None and lead and cfg.events == "on":
        run = run_name(cfg)
        ledger = obs_events.EventLedger(_events_path(cfg), run=run,
                                        corr=obs_events.corr_id(run))
        created_ledger = True
    exporter, created_export = _export, False
    if exporter is None and lead and (cfg.metrics_port > 0
                                      or cfg.metrics_textfile):
        run = run_name(cfg)
        exporter = obs_export.MetricsExporter(
            port=cfg.metrics_port if cfg.metrics_port > 0 else None,
            textfile=cfg.metrics_textfile,
            info={"run": run, "backend": jax.default_backend(),
                  "jax_version": jax.__version__},
            base_labels={"run": run})
        created_export = True
        # bank-build progress counters (ISSUE 17) ride the same scrape
        # endpoint; the bank module keeps its numpy-only import surface
        # by taking the exporter by reference rather than importing it
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            bank as bank_mod)
        bank_mod.install_build_exporter(exporter)
        if exporter.port:
            print(f"[export] Prometheus /metrics on port {exporter.port}"
                  + (f" + textfile {cfg.metrics_textfile}"
                     if cfg.metrics_textfile else ""))
        elif cfg.metrics_textfile:
            print(f"[export] Prometheus textfile {cfg.metrics_textfile}")
    prev_ledger = obs_events.install(ledger)
    try:
        return _serve(cfg, writer, max_rounds, _adapt, _adapt_reentry,
                      _health, _phases, ledger, exporter)
    finally:
        obs_events.install(prev_ledger)
        if created_export and exporter is not None:
            exporter.close()
        if created_ledger and ledger is not None:
            ledger.close()


def _serve(cfg: Config, writer, max_rounds, _adapt, _adapt_reentry,
           _health, _phases, ledger, exporter) -> Dict:
    """The supervised round stream (see ``serve``); runs with the
    ledger installed as the process-wide emission target."""
    t_start = time.perf_counter()
    total = max_rounds if max_rounds is not None else cfg.service_rounds
    # supervision granularity is one round per dispatch unit; `rounds`
    # is runtime-only (EXCLUDED_FIELDS), so neither replace recompiles
    cfg = cfg.replace(chain=1, resume=bool(cfg.checkpoint_dir),
                      rounds=(total or cfg.rounds),
                      # -1 = auto: the service checkpoints forever and must
                      # bound the directory (one-shot runs keep everything)
                      service_keep_ckpts=(3 if cfg.service_keep_ckpts < 0
                                          else cfg.service_keep_ckpts))
    lead = jax.process_index() == 0
    if _adapt_reentry:
        # adaptation re-entry is NOT a crash: the stream and its writer
        # are alive and must continue untouched. The crash-exact prepare
        # would compute a phantom metrics path (run_name embeds the
        # adapted threshold) and report recovery against a file nobody
        # writes — resume directly from the boundary checkpoint instead.
        rnd0 = ckpt.newest_resumable_round(cfg.checkpoint_dir) or 0
        recovery = {"resumed_from": rnd0, "metrics_offset": None,
                    "truncated_bytes": 0, "resume_upto": rnd0,
                    "boundary": False}
    else:
        recovery = prepare_crash_exact_resume(cfg, truncate=lead)
    if writer is None:
        if lead:
            writer = MetricsWriter(cfg.log_dir, run_name(cfg),
                                   cfg.tensorboard,
                                   boundary=recovery["boundary"])
        else:
            writer = NullWriter()
    if recovery["boundary"]:
        # the ledger's stream-segment boundary, mirroring the metrics
        # _run/start semantics: a fresh stream (or a pre-journal append)
        # starts a segment; a crash-exact splice and the in-process
        # re-entries do NOT — their streams must byte-match an
        # uninterrupted run's. Deliberately field-free: the round budget
        # lives in the heartbeat, and an interrupted run relaunched with
        # a different --service_rounds must still splice byte-identically
        obs_events.emit("service/start")

    chaos = chaos_mod.Chaos(
        cfg.chaos, state_path=(os.path.join(cfg.log_dir, "chaos_state.json")
                               if cfg.chaos else None))
    if chaos.active:
        if chaos.requires_buffered() and cfg.agg_mode != "buffered":
            raise ValueError(
                "--chaos kill_midbuf is the buffered-aggregation drill "
                "(the kill must land on a non-empty carried buffer); run "
                "with --agg_mode buffered, or use the plain kill@N")
        print(f"[service] chaos injections armed: {cfg.chaos}")

    if chaos.active:
        # data-plane drill (ISSUE 14): a bank_corrupt term fires BEFORE
        # the engine opens the bank, so verify-on-open meets the damage
        # — searching the SAME root the engine will resolve
        from defending_against_backdoors_with_robust_learning_rate_tpu.data.registry import (
            resolve_bank_root)
        chaos.corrupt_bank(resolve_bank_root(cfg), dataset=cfg.data)

    ladder = _health
    if health_monitor.resolve_policy(cfg) == "recover" \
            and cfg.rlr_adapt == "on":
        # an adapted segment's live metrics stream sits at the ORIGINAL
        # threshold's run_name (the _adapt_reentry comment above); a
        # ladder re-entry inside that segment would crash-exact-splice a
        # phantom path computed from the ADAPTED cfg, stranding the real
        # stream. Refuse the combination until the re-entry threads the
        # stream's run dir explicitly.
        raise ValueError(
            "--health_policy recover is not supported together with "
            "--rlr_adapt on (the ladder's rollback re-entry would "
            "splice the wrong metrics stream inside an adapted "
            "segment); run with --health_policy record, or without "
            "adaptation")
    if health_monitor.resolve_policy(cfg) == "recover" and ladder is None:
        ladder = health_monitor.HealthLadder(
            cfg, state_path=os.path.join(cfg.log_dir,
                                         health_monitor.STATE_NAME))
        print("[health] auto-recovery ladder armed (--health_policy "
              "recover): discard -> rollback -> quarantine -> halt; "
              f"state in {ladder.state_path}")
        # a kill AFTER a QUARANTINE rung was recorded but BEFORE its
        # re-entry completed leaves the suspect set only in the state
        # file — re-arm it, or the resumed process would serve with the
        # suspects still voting (the ladder resumes, not the failure)
        spec = ",".join(str(i) for i in ladder.state["quarantined"])
        if spec and spec != cfg.quarantine:
            print(f"[health] re-arming journaled quarantine set "
                  f"[{spec}] from {ladder.state_path}")
            cfg = cfg.replace(quarantine=spec)

    adapt = _adapt
    if cfg.rlr_adapt == "on" and adapt is None:
        from defending_against_backdoors_with_robust_learning_rate_tpu.attack import (
            adapt as adapt_mod)
        adapt = adapt_mod.ThresholdController(cfg)   # validates loudly
        print(f"[adapt] online RLR-threshold adaptation armed: start "
              f"thr={adapt.thr}, decide every {adapt.every} eval "
              f"boundary(ies) from Defense/* telemetry")

    eng = RoundEngine(cfg, writer=writer,
                      resume_upto=recovery["resume_upto"])
    sup = Supervisor(retries=cfg.service_retries,
                     backoff_s=cfg.service_backoff_s,
                     deadline_s=cfg.service_deadline_s, hb=eng.hb)
    # forensics plane (ISSUE 18): the engine's flight recorder snapshots
    # its ring on every incident, and (opt-in) the anomaly trigger arms
    # a bounded profiler capture. Wired through hooks so the evidence
    # lands even when the event ledger is off.
    flight = getattr(eng, "flight", None)
    trigger = None
    if lead and cfg.trigger_profile == "on" and cfg.profile_rounds <= 0:
        trigger = obs_trigger.ProfileTrigger(
            eng, getattr(writer, "dir", None) or cfg.log_dir,
            exporter=exporter)
        print(f"[service] anomaly-triggered profiling armed: span "
              f"z>={obs_trigger.Z_THRESHOLD} or any incident opens a "
              f"{obs_trigger.DEFAULT_CAPTURE_ROUNDS}-round capture "
              f"(max {obs_trigger.MAX_CAPTURES}/run)")
    elif lead and cfg.trigger_profile == "on":
        print("[service] --trigger_profile ignored: an explicit "
              "--profile_rounds capture owns the profiler seat")

    def _on_incident(kind, rnd):
        if flight is not None:
            flight.snapshot(kind, rnd)
        if trigger is not None:
            trigger.note_incident(kind, rnd)

    sup.on_incident = _on_incident
    if ladder is not None:
        ladder.on_rung = lambda rung, r: _on_incident(f"health/{rung}", r)
    if ledger is not None:
        # heartbeat upgrade (ISSUE 15 satellite): every emitted record
        # mirrors its seq + identity into status.json, so watchers can
        # detect a wedged ledger without tailing events.jsonl. Rides the
        # heartbeat's normal rate limit — event churn must not become
        # fsync churn. Warn/error records double as the flight
        # recorder's incident feed (chaos actions, degradations — every
        # incident the hooks above don't already cover).
        def _hb_event(rec, hb=eng.hb):
            hb.update(ledger_seq=rec["seq"],
                      last_event={"event": rec["event"],
                                  "severity": rec["severity"],
                                  "round": rec["round"]})
            if rec["severity"] != "info" and \
                    not rec["event"].startswith("obs/trigger_"):
                # the trigger's own armed event is warn-severity; feeding
                # it back would re-arm the trigger on itself
                _on_incident(rec["event"], rec["round"])
        ledger.on_emit = _hb_event
    if _phases:
        # in-process re-entry (health ladder / adaptation): the phase
        # history is one continuous record — status.json must still show
        # the health_rollback that CAUSED this re-entry
        sup.phases_seen.extend(_phases)
    if recovery["resumed_from"] and eng.start_round:
        sup.phase("recover", recovered_round=eng.start_round)
        # a per-life record (obs/events.PER_LIFE_PREFIXES): the resumed
        # process's real action. Deliberately WITHOUT truncated_bytes:
        # that value counts whatever rows were flushed before death —
        # buffer state, not logical history — and would break the
        # kill-vs-no-kill twin byte-identity (it stays in the run
        # summary's service section, where it belongs)
        obs_events.emit("service/recover", round=eng.start_round,
                        resumed_from=recovery["resumed_from"])
        print(f"[service] recovered at round {eng.start_round} "
              f"in {time.perf_counter() - t_start:.2f}s")
    stop_path = os.path.join(cfg.log_dir, STOP_FILE)
    census = cfg.churn_enabled and cfg.num_agents <= CENSUS_MAX_POPULATION
    if census:
        print(f"[service] population census at start: "
              f"{churn_mod.active_count(cfg, eng.start_round)}/"
              f"{cfg.num_agents} clients active")
    elif cfg.churn_enabled:
        print(f"[service] population census skipped "
              f"({cfg.num_agents:,} clients > {CENSUS_MAX_POPULATION:,}; "
              f"O(population) draw)")

    def unit_stream():
        rnd = eng.start_round
        while True:
            if total and rnd >= total:
                return
            if not total and os.path.exists(stop_path):
                print(f"[service] stop file {stop_path} — draining out")
                return
            rnd += 1
            yield (rnd,)

    # two independent iterations of the SAME stream: one for the loop, one
    # pinned as the host-mode prefetcher's production order
    eng.set_schedule(unit_stream())
    evals_skipped = 0
    adapt_to = None   # (new_threshold, boundary_round) when a move fires
    recover_to = None  # a ladder rung that rebuilds the engine fired
    try:
        for unit in unit_stream():
            rnd = unit[0]
            # retained for the ladder's DISCARD rung (a reference, not a
            # copy — per-round families deliberately do not donate) and
            # the spike chaos injector's delta
            prev_params = eng.params

            def do_dispatch(unit=unit, rnd=rnd):
                chaos.on_dispatch(rnd)
                eng.dispatch(unit)

            sup.run("dispatch", do_dispatch, unit=rnd)
            _numerics_chaos(chaos, eng, rnd, prev_params)
            # kill-mid-round drill: after dispatch, before the boundary's
            # eval/checkpoint — the rows for this round must be replayed
            # bit-identically by the resumed process
            chaos.maybe_kill(rnd)

            if rnd % cfg.snap == 0:
                if ladder is not None:
                    # the recovery ladder judges the round's sentinel
                    # lanes BEFORE the boundary's eval/checkpoint: a bad
                    # commit must never reach the checkpoint, and a
                    # DISCARD heals in place before any row is emitted
                    _run_ladder(cfg, eng, sup, ladder, chaos, rnd, unit,
                                prev_params)
                def do_eval(rnd=rnd):
                    chaos.on_eval(rnd)
                    eng.eval_boundary(rnd)

                try:
                    sup.run("eval", do_eval, unit=rnd)
                except UnitFailure as e:
                    if not (eng.drain is not None and eng.drain.dead):
                        # degrade: skip THIS boundary's eval, keep
                        # training — a broken eval set must not take down
                        # the service
                        evals_skipped += 1
                        obs_events.emit("service/eval_skipped",
                                        severity="warn", round=rnd,
                                        classification=e.classification)
                        print(f"[service] degraded: eval at round {rnd} "
                              f"skipped ({e.classification}); training "
                              f"continues")
                if eng.drain is not None and eng.drain.dead:
                    # the drain thread died (its error surfaced through the
                    # supervisor above, delivered-once): every later submit
                    # would be a silent drop, so the skip-eval degradation
                    # must not absorb this one. Fall back to synchronous
                    # metrics and replay the boundary inline — if THAT
                    # fails too, exit loudly with the journal intact.
                    sup.phase("degraded", drain_dead_round=rnd)
                    obs_events.emit("service/drain_degraded",
                                    severity="warn", round=rnd,
                                    mode="dead")
                    print("[service] degraded: metrics drain died — "
                          "falling back to synchronous metrics and "
                          f"replaying round {rnd}'s eval inline")
                    eng.drain.close(raise_errors=False)
                    eng.drain = None
                    eng.eval_boundary(rnd)

                secs = chaos.drain_blocker_secs(rnd)
                if secs and eng.drain is not None:
                    eng.drain.submit(lambda _v, s=secs: time.sleep(s), ())

                def do_ckpt(rnd=rnd):
                    if cfg.checkpoint_dir:
                        eng.save_checkpoint(rnd,
                                            drain_timeout=sup.stall_budget())
                    else:
                        # no checkpoint flush will run: barrier the drain
                        # anyway, so the inline Service/* writes below never
                        # race the drain thread on the shared writer
                        eng.drain_flush(timeout=sup.stall_budget())

                try:
                    sup.run("checkpoint", do_ckpt, unit=rnd)
                except UnitFailure as e:
                    if e.classification == WEDGED and eng.drain is not None:
                        # the drain is stalled: degrade to sync metrics.
                        # close() gives the wedged callback a bounded
                        # grace to finish (its rows land in order), then
                        # the service continues inline.
                        obs_events.emit("service/drain_degraded",
                                        severity="warn", round=rnd,
                                        mode="wedged")
                        print("[service] degraded: metrics drain wedged — "
                              "falling back to synchronous metrics")
                        eng.drain.close(raise_errors=False,
                                        timeout=2 * sup.stall_budget())
                        eng.drain = None
                        eng.save_checkpoint(rnd)
                    else:
                        raise
                chaos.corrupt_checkpoint(cfg.checkpoint_dir, rnd)
                if lead and census:
                    eng.writer.scalar(
                        "Service/Active_Clients",
                        churn_mod.active_count(cfg, rnd), rnd)
                _emit_service_rows(eng, sup, evals_skipped, rnd)
                if eng.mstate.get("defense_round") == rnd:
                    # anomaly-gated defense telemetry (ISSUE 15
                    # satellite): the drained flip-fraction / margin
                    # summary judged for over-defense and electorate
                    # splitting — a LOW-severity ledger record in the
                    # same stream as the numerics incidents, never a
                    # ladder trigger. Replay-deduped, so a rollback's
                    # re-evaluated boundary re-emits nothing.
                    why = health_monitor.defense_anomaly(
                        eng.mstate.get("defense"),
                        flip_hi=cfg.defense_flip_frac_hi,
                        low_margin_hi=cfg.defense_low_margin_hi)
                    if why:
                        obs_events.emit(
                            "health/defense_anomaly", severity="info",
                            round=rnd, why=why,
                            flip_frac=float(eng.mstate["defense"]
                                            ["tel_flip_frac"]))
                if exporter is not None:
                    _update_exporter(exporter, eng, sup, ladder,
                                     evals_skipped, rnd, ledger)
                if (adapt is not None
                        and eng.mstate.get("defense_round") == rnd):
                    # the boundary's checkpoint step flushed the drain,
                    # so the telemetry stash is host-complete here; the
                    # freshness stamp gates out boundaries whose eval
                    # was skipped/degraded — the controller must never
                    # decide (or advance its cadence) on the PREVIOUS
                    # boundary's snapshot
                    new_thr = adapt.consider(eng.mstate.get("defense"),
                                             rnd)
                    if new_thr is not None:
                        adapt_to = (new_thr, rnd)
                        break
            eng.post_unit()
            if trigger is not None:
                # after post_unit, so the flight window the z-scan reads
                # already includes this unit's record
                trigger.step(rnd)
        if eng.drain is not None:
            eng.hb.update(phase="drain", force=True)
            eng.drain.flush()
    except health_monitor.HealthRecovery as hr:
        # ROLLBACK / QUARANTINE: tear this engine down and re-enter
        # through the crash-exact resume machinery below (the finally
        # still closes the engine first)
        recover_to = hr
    except UnitFailure:
        # poisoned/give-up on a non-degradable unit: exit loudly, journal
        # intact — the next `serve` resumes crash-exactly
        eng.hb.update(phase="failed", force=True,
                      **sup.heartbeat_fields())
        raise
    finally:
        eng.close()
    if recover_to is not None:
        eng.hb.update(phase=f"health_{recover_to.rung}", force=True,
                      health_round=recover_to.rnd)
        # flushed BEFORE the kill-mid-recovery window below, so a killed
        # and an unkilled recovery leave byte-identical ledgers: the
        # resumed process walks the journaled ladder and re-emits nothing
        obs_events.emit("health/reenter", severity="warn",
                        round=recover_to.rnd, rung=recover_to.rung,
                        quarantine=recover_to.quarantine)
        # kill-mid-rollback drill window: the rung is recorded (ladder
        # state saved) and the engine is closed, but recovery has not
        # completed — a kill HERE must resume the ladder, not the failure
        chaos.maybe_kill_recover(recover_to.rnd)
        print(f"[health] {recover_to.rung.upper()} at round "
              f"{recover_to.rnd}: re-entering through the crash-exact "
              f"resume (newest digest-valid checkpoint + metrics splice)"
              + (f"; quarantining clients [{recover_to.quarantine}]"
                 if recover_to.quarantine else ""))
        writer.close()
        # each recovery re-enters serve() recursively: bound the depth
        # per PROCESS so a long-lived service surviving many healed
        # episodes cannot creep toward the interpreter's recursion
        # limit — the crash-exact machinery makes a process restart
        # free, so the bound trades nothing away (the ladder state file
        # carries everything across it)
        ladder.reentries += 1
        if ladder.reentries > health_monitor.MAX_REENTRIES_PER_PROCESS:
            raise UnitFailure(
                "health", recover_to.rnd, POISONED, ladder.reentries,
                health_monitor.HealthIncident(
                    f"{ladder.reentries} recovery re-entries in one "
                    f"process (> "
                    f"{health_monitor.MAX_REENTRIES_PER_PROCESS}); "
                    f"restart the service — it resumes crash-exactly "
                    f"with the ladder state intact"))
        new_cfg = (cfg.replace(quarantine=recover_to.quarantine)
                   if recover_to.quarantine else cfg)
        outer_wall = time.perf_counter() - t_start
        # writer=None: the re-entry must reopen the stream AFTER the
        # crash-exact truncate (run_name deliberately ignores
        # --quarantine, so the stream path is unchanged)
        sub = serve(new_cfg, writer=None, max_rounds=total, _adapt=adapt,
                    _health=ladder, _phases=sup.phases_seen,
                    _ledger=ledger, _export=exporter)
        svc = sub.setdefault("service", {})
        # rounds_served counts DISTINCT rounds: the inner serve resumed
        # from a checkpoint BEHIND this segment's last round and
        # re-serves the overlap, so this segment only contributes the
        # prefix the inner did not replay (unlike the adapt re-entry
        # below, which resumes exactly at the boundary — no overlap)
        distinct = max(0, int(svc.get("resumed_from", 0))
                       - eng.start_round)
        for key, extra in ({**sup.counters,
                            "evals_skipped": evals_skipped,
                            "rounds_served": distinct,
                            "wall_s": outer_wall}).items():
            svc[key] = round(svc.get(key, 0) + extra, 3)
        svc["phases_seen"] = sorted(set(svc.get("phases_seen", []))
                                    | set(sup.phases_seen))
        svc["health"] = ladder.summary()
        return sub
    if adapt_to is not None:
        new_thr, at_rnd = adapt_to
        old_thr = cfg.robustLR_threshold
        eng.hb.update(phase="adapt", force=True, adapt_round=at_rnd,
                      adapt_threshold=new_thr)
        print(f"[adapt] RLR threshold {old_thr} -> {new_thr} at round "
              f"{at_rnd} (Defense/* telemetry; rebuilding round programs "
              f"from the boundary checkpoint)")
        # re-enter with the adapted program constant: same writer (one
        # continuous metrics stream), same checkpoint dir (the boundary's
        # checkpoint is the resume point), controller carried through so
        # the decision cadence/log survive
        outer_wall = time.perf_counter() - t_start
        sub = serve(cfg.replace(robustLR_threshold=new_thr),
                    writer=writer, max_rounds=total, _adapt=adapt,
                    _adapt_reentry=True, _health=ladder,
                    _phases=sup.phases_seen, _ledger=ledger,
                    _export=exporter)
        # the reliability record must cover the WHOLE run, not just the
        # last segment: fold this segment's supervisor counters into the
        # inner serve's service section
        svc = sub.setdefault("service", {})
        for key, extra in ({**sup.counters,
                            "evals_skipped": evals_skipped,
                            "rounds_served": eng.rounds_done,
                            "wall_s": outer_wall}).items():
            svc[key] = round(svc.get(key, 0) + extra, 3)
        svc["phases_seen"] = sorted(set(svc.get("phases_seen", []))
                                    | set(sup.phases_seen))
        if not _adapt_reentry:
            # the outermost segment's recovery info is the run's real
            # origin (inner re-entries report the adaptation boundary)
            svc["resumed_from"] = recovery["resumed_from"]
            svc["truncated_bytes"] = recovery["truncated_bytes"]
        svc["adaptations"] = [
            {"round": r, "from": f, "to": t} for r, f, t in adapt.moves]
        return sub
    if trigger is not None:
        # a capture window still open at exit: harvest what it caught
        # (eng.close() already stopped the trace on the engine's seat)
        trigger.finalize(eng.rnd)
    eng.hb.update(force=True, evals_skipped=evals_skipped,
                  **sup.heartbeat_fields())
    if exporter is not None:
        # final scrape state before the writer closes — a fleet console
        # polling the textfile sees the finished run's last values
        _update_exporter(exporter, eng, sup, ladder, evals_skipped,
                         eng.rnd, ledger)
    summary = eng.finalize()
    summary["service"] = {
        **sup.counters,
        "evals_skipped": evals_skipped,
        "phases_seen": list(sup.phases_seen),
        "resumed_from": recovery["resumed_from"],
        "truncated_bytes": recovery["truncated_bytes"],
        "rounds_served": eng.rounds_done,
        "wall_s": round(time.perf_counter() - t_start, 3),
    }
    if ledger is not None:
        summary["service"]["ledger_events"] = ledger.seq
    if ladder is not None:
        summary["service"]["health"] = ladder.summary()
    print(f"[service] served {eng.rounds_done} round(s); "
          f"retries={sup.counters['retries']} "
          f"evals_skipped={evals_skipped} "
          f"resumed_from={recovery['resumed_from']}")
    return summary


def _numerics_chaos(chaos, eng, rnd: int, prev_params) -> None:
    """Apply the numerics chaos injections (nan@N / spike@N:x) to the
    round's committed params. In buffered mode only the MODEL half of
    the (params, buffer) carry is touched — the buffer holds integer
    counters whose dtype a float transform would silently change."""
    if not chaos.active:
        return
    if chaos.nan_due(rnd):
        if eng.async_mode:
            eng.params = (health_monitor.poison_params(eng.params[0]),
                          eng.params[1])
        else:
            eng.params = health_monitor.poison_params(eng.params)
    factor = chaos.spike_due(rnd)
    if factor:
        if eng.async_mode:
            eng.params = (health_monitor.spike_params(
                prev_params[0], eng.params[0], factor), eng.params[1])
        else:
            eng.params = health_monitor.spike_params(prev_params,
                                                     eng.params, factor)


def _run_ladder(cfg, eng, sup, ladder, chaos, rnd: int, unit,
                prev_params) -> None:
    """One boundary's walk of the auto-recovery ladder
    (health/monitor.py). Healthy: fold the boundary into the EMA
    baseline and return. Incident: DISCARD in place (withdraw the
    commit, re-dispatch with a recovery nonce — a persistent fault, like
    a chaos nan@NxK with fire budget left, re-poisons the replay and
    escalates), then ROLLBACK / QUARANTINE via HealthRecovery (serve
    re-enters through the crash-exact machinery), then HALT loudly."""
    model_prev = prev_params[0] if eng.async_mode else prev_params
    report = ladder.check(cfg, eng, rnd, prev_params=model_prev)
    incident_emitted = False
    while not report["healthy"]:
        if not incident_emitted:
            # one typed record per incident episode (the rung records
            # below count the escalation walk)
            obs_events.emit("health/incident", severity="warn",
                            round=rnd, why=report["why"])
            incident_emitted = True
        # the QUARANTINE rung feeds --quarantine, which the host-sampled
        # program refuses (it never sees the sampled client ids) — that
        # path escalates past it. DISCARD is safe everywhere: the
        # prefetcher retains the last-served payload precisely for
        # same-unit re-dispatch (data/prefetch.RoundPrefetcher.get).
        rung = ladder.next_rung(cfg, quarantine_ok=not eng.host_mode)
        ladder.record(rung, rnd, sup)
        print(f"[health] incident at round {rnd} ({report['why']}) "
              f"-> {rung.upper()}")
        if rung == "discard":
            eng.params = prev_params
            eng.rounds_done -= 1
            eng.dispatch(unit, nonce=ladder.state["episode"]["discards"])
            _numerics_chaos(chaos, eng, rnd, prev_params)
            report = ladder.check(cfg, eng, rnd,
                                  prev_params=model_prev)
            continue
        if rung == "rollback":
            raise health_monitor.HealthRecovery("rollback", rnd)
        if rung == "quarantine":
            spec = ladder.quarantine_spec(eng, rnd)
            if spec:
                raise health_monitor.HealthRecovery("quarantine", rnd,
                                                    quarantine=spec)
            # no suspect evidence at all: nothing to quarantine — the
            # episode budget is spent either way, so fall through
            report = ladder.check(cfg, eng, rnd,
                                  prev_params=model_prev)
            continue
        raise UnitFailure(
            "health", rnd, POISONED, ladder.state["incidents"],
            health_monitor.HealthIncident(
                f"health ladder exhausted at round {rnd}: "
                f"{report['why']}"))
    ladder.note_healthy(report)


def _update_exporter(exporter, eng, sup: Supervisor, ladder,
                     evals_skipped: int, rnd: int, ledger) -> None:
    """Publish the boundary's service state through the Prometheus
    exporter (obs/export.py): heartbeat-plane gauges, supervisor/ladder
    counters, the drained eval scalars and the HBM watermarks — then
    rewrite the textfile. Values come from host state the boundary's
    drain flush already materialized; nothing here touches the device
    beyond the (cheap, possibly absent) allocator stats query."""
    exporter.observe_rounds(rnd)
    exporter.set("round", rnd, help_text="current round")
    exporter.set("rounds_target", eng.cfg.rounds,
                 help_text="configured total rounds (0 = indefinite)")
    summ = eng.mstate.get("summary") or {}
    for key, name in (("val_acc", "val_acc"),
                      ("poison_acc", "poison_acc"),
                      ("rounds_per_sec", "rounds_per_sec")):
        if key in summ:
            exporter.set(name, summ[key],
                         help_text=f"last boundary's {key}")
    for key, value in sup.counters.items():
        exporter.set(f"supervisor_{key}_total", value, mtype="counter",
                     help_text="supervisor census "
                               "(service/supervisor.py)")
    exporter.set("evals_skipped_total", evals_skipped, mtype="counter",
                 help_text="eval boundaries skipped by degradation")
    if ladder is not None:
        health = ladder.summary()
        exporter.set("health_incidents_total", health["incidents"],
                     mtype="counter",
                     help_text="health incidents (health/monitor.py)")
        for rung in health_monitor.RUNGS:
            exporter.set("health_rung_total", health[f"health_{rung}s"],
                         labels={"rung": rung}, mtype="counter",
                         help_text="recovery-ladder rung census")
        exporter.set("health_quarantined", len(health["quarantined"]),
                     help_text="quarantined client count")
    if ledger is not None:
        exporter.set("ledger_seq", ledger.seq,
                     help_text="event-ledger sequence number "
                               "(obs/events.py)")
    susp = summ.get("suspicion")
    if susp:
        # defense-provenance gauges (obs/reputation.py): the fleet's
        # scrape sees WHO the defense is flagging, not just whether it
        # is flipping — absent entirely when --reputation off
        exporter.set("rep_suspects", susp["suspect_count"],
                     help_text="clients past the suspicion streak "
                               "threshold (obs/reputation.py)")
        exporter.set("rep_clients_tracked", susp["clients"],
                     help_text="clients with longitudinal "
                               "reputation state")
        if susp.get("scores"):
            exporter.set("rep_top_suspect_score", susp["scores"][0],
                         help_text="highest suspicion score "
                                   "(suspicion EMA, obs/reputation.py)")
        if "auc" in susp:
            exporter.set("rep_suspicion_auc", susp["auc"],
                         help_text="suspicion ranking AUC vs known "
                                   "corrupt ids (evaluation only)")
    cfg = eng.cfg
    if cfg.traffic_enabled and cfg.num_agents <= CENSUS_MAX_POPULATION:
        # diurnal-traffic census (data/traffic.py, ISSUE 17 follow-up):
        # computed per boundary for the console print but never exported
        # until now. Host-side O(population) draw, same bound as the
        # churn census.
        from defending_against_backdoors_with_robust_learning_rate_tpu.data import (
            traffic as traffic_mod)
        exporter.set("traffic_present_clients",
                     traffic_mod.census(cfg, rnd),
                     help_text="clients traffic-present this round "
                               "(data/traffic.py census)")
    for key, value in obs_attribution.memory_watermarks().items():
        exporter.set(key, value,
                     help_text="device allocator watermark (bytes)")
    exporter.flush()


def _emit_service_rows(eng, sup: Supervisor, evals_skipped: int,
                       rnd: int) -> None:
    """Service/* counters at each boundary. Written inline (not through
    the drain): they are service-life observability, excluded — like
    Throughput/* — from the crash-exact row comparison."""
    w = eng.writer
    w.scalar("Service/Retries", sup.counters["retries"], rnd)
    w.scalar("Service/Transient_Failures", sup.counters["transient"], rnd)
    w.scalar("Service/Wedged_Failures", sup.counters["wedged"], rnd)
    w.scalar("Service/Poisoned_Failures", sup.counters["poisoned"], rnd)
    w.scalar("Service/Slow_Units", sup.counters["slow_units"], rnd)
    w.scalar("Service/Evals_Skipped", evals_skipped, rnd)


def main(argv=None) -> int:
    cfg = args_parser(argv)
    if cfg.platform:
        jax.config.update("jax_platforms", cfg.platform)
    if cfg.num_processes > 1 or cfg.coordinator:
        from defending_against_backdoors_with_robust_learning_rate_tpu.parallel import (
            multihost)
        multihost.maybe_initialize(cfg.coordinator, cfg.num_processes,
                                   cfg.process_id)
    serve(cfg)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
