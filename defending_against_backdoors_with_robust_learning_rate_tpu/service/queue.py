"""Experiment queue: scenario cells back-to-back in ONE process.

The ROADMAP's scenario matrix (attack x defense x faults x churn) needs a
host that runs many cells without paying process startup + XLA per cell.
This queue is that host: every cell is a set of Config overrides applied
to one base config, executed sequentially in the SAME interpreter — so the
persistent XLA cache and the AOT executable bank (utils/compile_cache.py)
are shared across cells. Cells that differ only in runtime knobs (seed,
rounds, faults rates at equal shapes) re-dispatch banked executables and
never touch XLA; cells that change the program (aggr, telemetry, churn)
compile once and bank for the NEXT queue run.

Queue file (JSON): either a bare list of override dicts, or
``{"cells": [{"name": ..., "overrides": {...}}, ...]}``::

    [{"aggr": "avg", "churn_available": 0.8},
     {"aggr": "sign", "server_lr": 1.0}]

Each finished cell appends one flushed row to
``<log_dir>/queue_results.jsonl`` (summary + the service counters when the
cell ran in service mode), so a mid-queue kill keeps completed rows — the
same crash discipline as the rest of the service subsystem. A cell whose
run *fails* is recorded with its error and the queue moves on: one
poisoned cell must not abort the matrix.

Entry point::

    python -m defending_against_backdoors_with_robust_learning_rate_tpu.service.queue \
        --queue cells.json --data synthetic --rounds 8 --snap 4
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional

from defending_against_backdoors_with_robust_learning_rate_tpu.config import (
    Config, args_parser)

SUMMARY_KEYS = ("round", "val_acc", "val_loss", "poison_acc", "poison_loss",
                "rounds_per_sec", "steady_rounds_per_sec", "params",
                # the last boundary's Defense/* telemetry snapshot
                # (obs/telemetry.host_summary via train.py): the
                # scenario matrix (scripts/sweep_scenarios.py) records
                # defense state per cell, not just outcomes
                "defense")


def load_cells(path: str) -> List[Dict[str, Any]]:
    """Parse the queue file into [{"name", "overrides"}] rows."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    raw = data.get("cells", data) if isinstance(data, dict) else data
    if not isinstance(raw, list):
        raise ValueError(f"queue file {path}: expected a list of cells")
    cells = []
    for i, cell in enumerate(raw):
        if not isinstance(cell, dict):
            raise ValueError(f"queue file {path}: cell {i} is not an object")
        overrides = dict(cell.get("overrides", cell))
        overrides.pop("name", None)
        cells.append({"name": str(cell.get("name", f"cell{i:03d}")),
                      "overrides": overrides})
    return cells


def _apply_overrides(base: Config, overrides: Dict[str, Any]) -> Config:
    fields = {f.name for f in dataclasses.fields(Config)}
    unknown = sorted(set(overrides) - fields)
    if unknown:
        raise ValueError(f"unknown Config fields in cell overrides: "
                         f"{unknown}")
    return base.replace(**overrides)


def run_queue(base_cfg: Config, cells: List[Dict[str, Any]],
              results_path: Optional[str] = None,
              service_mode: bool = False) -> List[Dict[str, Any]]:
    """Run every cell against one AOT bank; returns (and streams) one
    result row per cell. ``service_mode`` routes cells through
    service.driver.serve (supervised, journaled) instead of train.run."""
    results_path = results_path or os.path.join(base_cfg.log_dir,
                                                "queue_results.jsonl")
    os.makedirs(os.path.dirname(results_path) or ".", exist_ok=True)
    rows: List[Dict[str, Any]] = []
    with open(results_path, "a", encoding="utf-8") as out:
        for i, cell in enumerate(cells):
            cfg = _apply_overrides(base_cfg, cell["overrides"])
            if cfg.checkpoint_dir and "checkpoint_dir" not in cell["overrides"]:
                # a shared checkpoint dir would make cell N resume cell
                # N-1's journaled state (serve always resumes; same-shape
                # one-shot cells cross-restore too) — isolate per cell
                cfg = cfg.replace(checkpoint_dir=os.path.join(
                    cfg.checkpoint_dir, cell["name"]))
            print(f"[queue] cell {i + 1}/{len(cells)} {cell['name']!r}: "
                  f"{cell['overrides']}")
            row: Dict[str, Any] = {"cell": cell["name"],
                                   "overrides": cell["overrides"],
                                   "started": time.time()}
            if "meta" in cell:
                # caller-computed cell annotations (e.g. the scenario
                # sweep's simulated-clock cost) ride the row verbatim
                row["meta"] = cell["meta"]
            t0 = time.perf_counter()
            try:
                if service_mode:
                    from defending_against_backdoors_with_robust_learning_rate_tpu.service.driver import (
                        serve)
                    summary = serve(cfg)
                    row["service"] = summary.get("service")
                else:
                    from defending_against_backdoors_with_robust_learning_rate_tpu.train import (
                        run)
                    summary = run(cfg)
                row["summary"] = {k: summary[k] for k in SUMMARY_KEYS
                                  if k in summary}
                row["ok"] = True
            except Exception as e:  # one poisoned cell != a dead matrix
                row["ok"] = False
                row["error"] = f"{type(e).__name__}: {e}"
                print(f"[queue] cell {cell['name']!r} FAILED: "
                      f"{row['error']} — continuing with the next cell")
            row["wall_s"] = round(time.perf_counter() - t0, 3)
            out.write(json.dumps(row) + "\n")
            out.flush()   # a mid-queue kill keeps completed rows
            rows.append(row)
    done = sum(r["ok"] for r in rows)
    print(f"[queue] {done}/{len(rows)} cells completed -> {results_path}")
    return rows


def main(argv=None) -> int:
    # --queue (+ --service/--results) are queue-level; everything else is
    # the shared base-config flag surface (config.args_parser)
    qp = argparse.ArgumentParser(add_help=False)
    qp.add_argument("--queue", required=True,
                    help="JSON file of scenario cells (see module doc)")
    qp.add_argument("--service", action="store_true",
                    help="run cells through the supervised service driver "
                         "instead of the one-shot trainer")
    qp.add_argument("--results", default="",
                    help="queue_results.jsonl path (default: <log_dir>/)")
    qargs, rest = qp.parse_known_args(argv)
    base_cfg = args_parser(rest)
    if base_cfg.platform:
        import jax
        jax.config.update("jax_platforms", base_cfg.platform)
    cells = load_cells(qargs.queue)
    rows = run_queue(base_cfg, cells, results_path=qargs.results or None,
                     service_mode=qargs.service)
    return 0 if all(r["ok"] for r in rows) else 1


if __name__ == "__main__":
    raise SystemExit(main())
