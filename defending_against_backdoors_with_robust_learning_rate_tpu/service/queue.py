"""Experiment queue: scenario cells back-to-back in ONE process.

The ROADMAP's scenario matrix (attack x defense x faults x churn) needs a
host that runs many cells without paying process startup + XLA per cell.
This queue is that host: every cell is a set of Config overrides applied
to one base config, executed sequentially in the SAME interpreter — so the
persistent XLA cache and the AOT executable bank (utils/compile_cache.py)
are shared across cells. Cells that differ only in runtime knobs (seed,
rounds, faults rates at equal shapes) re-dispatch banked executables and
never touch XLA; cells that change the program (aggr, telemetry, churn)
compile once and bank for the NEXT queue run.

Queue file (JSON): either a bare list of override dicts, or
``{"cells": [{"name": ..., "overrides": {...}}, ...]}``::

    [{"aggr": "avg", "churn_available": 0.8},
     {"aggr": "sign", "server_lr": 1.0}]

Each finished cell appends one flushed row to
``<log_dir>/queue_results.jsonl`` (summary + the resolved ``run_name`` so
rows join to run dirs + the service counters when the cell ran in service
mode), so a mid-queue kill keeps completed rows — the same crash
discipline as the rest of the service subsystem. A cell whose run *fails*
is recorded with its error and the queue moves on: one poisoned cell must
not abort the matrix. The FINAL row is a queue-level throughput summary
(``queue_summary``: cells/hour, aggregate wall, compile-vs-steady split).

``--tenants E`` (ISSUE 13, service/tenancy.py) folds the EXPERIMENT axis:
shape-compatible cells (grouped by the compile-cache fingerprint's own
field algebra, utils/compile_cache.tenant_pack_key) run up to E at a time
as ONE resident ``*_mt`` program with per-tenant seeds/thresholds/LRs as
traced [E]-vectors; incompatible cells fall back to this serial path with
a printed note.

Entry point::

    python -m defending_against_backdoors_with_robust_learning_rate_tpu.service.queue \
        --queue cells.json --data synthetic --rounds 8 --snap 4 [--tenants 8]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional

from defending_against_backdoors_with_robust_learning_rate_tpu.config import (
    Config, args_parser)
from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    events as obs_events)

SUMMARY_KEYS = ("round", "val_acc", "val_loss", "poison_acc", "poison_loss",
                "rounds_per_sec", "steady_rounds_per_sec", "params",
                # the last boundary's Defense/* telemetry snapshot
                # (obs/telemetry.host_summary via train.py): the
                # scenario matrix (scripts/sweep_scenarios.py) records
                # defense state per cell, not just outcomes
                "defense",
                # the last boundary's Health/* snapshot (health/monitor
                # via train.py / service/tenancy.py): a sweep cell that
                # went nonfinite under --health_policy record is a
                # RECORDED verdict in the queue results, never a dead
                # queue or a silent hole
                "health",
                # the last boundary's per-client suspicion verdict
                # (obs/reputation.ReputationTracker.summary via
                # train.py / service/tenancy.py): sweep cells carry
                # which clients the defense provenance plane ranked
                # suspect — and the ranking AUC when ground truth is
                # known — without any extra file to join
                "suspicion")


def load_cells(path: str) -> List[Dict[str, Any]]:
    """Parse the queue file into [{"name", "overrides"}] rows."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    raw = data.get("cells", data) if isinstance(data, dict) else data
    if not isinstance(raw, list):
        raise ValueError(f"queue file {path}: expected a list of cells")
    cells = []
    for i, cell in enumerate(raw):
        if not isinstance(cell, dict):
            raise ValueError(f"queue file {path}: cell {i} is not an object")
        overrides = dict(cell.get("overrides", cell))
        overrides.pop("name", None)
        cells.append({"name": str(cell.get("name", f"cell{i:03d}")),
                      "overrides": overrides})
    return cells


def _apply_overrides(base: Config, overrides: Dict[str, Any]) -> Config:
    fields = {f.name for f in dataclasses.fields(Config)}
    unknown = sorted(set(overrides) - fields)
    if unknown:
        raise ValueError(f"unknown Config fields in cell overrides: "
                         f"{unknown}")
    return base.replace(**overrides)


def _cell_cfg(base_cfg: Config, cell: Dict[str, Any]) -> Config:
    cfg = _apply_overrides(base_cfg, cell["overrides"])
    if cfg.checkpoint_dir and "checkpoint_dir" not in cell["overrides"]:
        # a shared checkpoint dir would make cell N resume cell
        # N-1's journaled state (serve always resumes; same-shape
        # one-shot cells cross-restore too) — isolate per cell
        cfg = cfg.replace(checkpoint_dir=os.path.join(
            cfg.checkpoint_dir, cell["name"]))
    return cfg


def _new_row(base_cfg: Config, cell: Dict[str, Any]) -> Dict[str, Any]:
    row: Dict[str, Any] = {"cell": cell["name"],
                           "overrides": cell["overrides"],
                           "started": time.time()}
    try:
        from defending_against_backdoors_with_robust_learning_rate_tpu.utils.metrics import (
            run_name)
        # the resolved run-dir name rides every row so rows join to run
        # dirs (metrics.jsonl / trace.json) without re-deriving the name
        row["run_name"] = run_name(_cell_cfg(base_cfg, cell))
    except Exception:
        pass   # a broken cell still gets its (failed) row below
    if "meta" in cell:
        # caller-computed cell annotations (e.g. the scenario sweep's
        # simulated-clock cost) ride the row verbatim
        row["meta"] = cell["meta"]
    return row


def _run_serial_cell(base_cfg: Config, cell: Dict[str, Any],
                     service_mode: bool) -> Dict[str, Any]:
    """One cell through the historical serial path (train.run or the
    supervised service driver); returns its finished row."""
    row = _new_row(base_cfg, cell)
    # unknown Config fields are a queue-file AUTHORING error and raise
    # out of the queue (the historical contract, test-pinned) — only a
    # cell's RUN failure is recorded-and-skipped
    cfg = _cell_cfg(base_cfg, cell)
    t0 = time.perf_counter()
    try:
        if service_mode:
            from defending_against_backdoors_with_robust_learning_rate_tpu.service.driver import (
                serve)
            summary = serve(cfg)
            row["service"] = summary.get("service")
        else:
            from defending_against_backdoors_with_robust_learning_rate_tpu.train import (
                run)
            summary = run(cfg)
        row["summary"] = {k: summary[k] for k in SUMMARY_KEYS
                          if k in summary}
        row["ok"] = True
    except Exception as e:  # one poisoned cell != a dead matrix
        row["ok"] = False
        row["error"] = f"{type(e).__name__}: {e}"
        print(f"[queue] cell {cell['name']!r} FAILED: "
              f"{row['error']} — continuing with the next cell")
    row["wall_s"] = round(time.perf_counter() - t0, 3)
    return row


def _run_pack_cells(base_cfg: Config, pack: List[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """One tenant pack (service/tenancy.py): E cells as one resident
    *_mt program, one finished row per cell. A pack failure is recorded
    on every member cell and the queue moves on (the record-and-skip
    contract, pack-shaped)."""
    from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
        tenancy)
    rows = [_new_row(base_cfg, cell) for cell in pack]
    t0 = time.perf_counter()
    try:
        cfgs = [_cell_cfg(base_cfg, cell) for cell in pack]
        summaries, pack_info = tenancy.run_pack(
            cfgs, names=[c["name"] for c in pack])
    except tenancy.PackIneligible as e:
        # a refusal only run_pack could see (e.g. host-sampled 'auto'
        # resolving ON against the loaded dataset's bytes) — before any
        # program build; the members get their solo runs, not a failure
        print(f"[tenancy] pack {[c['name'] for c in pack]} -> serial "
              f"({e})")
        return [_run_serial_cell(base_cfg, cell, False) for cell in pack]
    except Exception as e:
        wall = round(time.perf_counter() - t0, 3)
        for row in rows:
            row["ok"] = False
            row["error"] = f"{type(e).__name__}: {e}"
            row["wall_s"] = round(wall / len(pack), 3)
        print(f"[queue] tenant pack "
              f"{[c['name'] for c in pack]} FAILED: "
              f"{rows[0]['error']} — continuing with the next cells")
    else:
        wall = round(time.perf_counter() - t0, 3)
        for slot, (row, summary) in enumerate(zip(rows, summaries,
                                                  strict=True)):
            row["summary"] = {k: summary[k] for k in SUMMARY_KEYS
                              if k in summary}
            row["ok"] = True
            # the pack's wall clock is SHARED: per-cell cost is wall/E,
            # which is exactly what cells/hour should bill
            row["wall_s"] = round(wall / len(pack), 3)
            row["tenancy"] = {"slot": slot, **pack_info}
    return rows


def _queue_summary_row(rows: List[Dict[str, Any]], wall_s: float,
                       scheduler_stats: Optional[List[Dict[str, Any]]]
                       = None) -> Dict[str, Any]:
    """The queue-level throughput summary appended as the FINAL
    queue_results.jsonl row: cells/hour, the aggregate wall, and the
    compile-vs-steady split (per-cell steady seconds estimated from each
    summary's rounds/steady-rate pair; the remainder is compile+warmup).
    A scheduler run (service/scheduler.py) additionally reports the
    fleet's slot-occupancy fraction: busy slot-dispatches over total
    slot-dispatches across every bin — the number that says how close
    the resident fleet came to never idling the chip."""
    ok = [r for r in rows if r.get("ok")]
    steady_s = warmup_s = 0.0
    for r in ok:
        summ = r.get("summary", {})
        srps, rnds = summ.get("steady_rounds_per_sec"), summ.get("round")
        cell_wall = r.get("wall_s", 0.0)
        ten = r.get("tenancy")
        if ten:
            # packed cells: run_pack measured the pack's true
            # compile/AOT seconds — bill each tenant its 1/E share
            # (wall_s is already wall/E; the summary's steady rate is
            # pack-level and would overcount E-fold)
            w = min(cell_wall,
                    ten.get("compile_s", 0.0) / max(ten["tenants"], 1))
            warmup_s += w
            steady_s += max(0.0, cell_wall - w)
        elif srps and rnds:
            s = min(cell_wall, rnds / srps)
            steady_s += s
            warmup_s += max(0.0, cell_wall - s)
        else:
            warmup_s += cell_wall
    packed = sum(1 for r in ok if "tenancy" in r)
    summary = {
        "queue_summary": True,
        "cells": len(rows), "ok": len(ok),
        "packed_cells": packed, "serial_cells": len(ok) - packed,
        "wall_s": round(wall_s, 3),
        "cells_per_hour": round(3600.0 * len(ok) / max(wall_s, 1e-9), 2),
        # clamped: steady_s is assembled from per-cell wall_s values that
        # were ROUNDED at emit time, so on a fully-warm queue their sum
        # can exceed the true wall by sub-ms rounding — the invariant
        # wall_s >= steady_s must survive the double rounding
        "steady_s": round(min(steady_s, wall_s), 3),
        "compile_warmup_s": round(warmup_s, 3),
    }
    if scheduler_stats:
        busy = sum(s.get("busy_slot_rounds", 0) for s in scheduler_stats)
        tot = sum(s.get("total_slot_rounds", 0) for s in scheduler_stats)
        summary["scheduler"] = True
        summary["slot_occupancy"] = round(busy / max(tot, 1), 4)
        summary["scheduler_bins"] = len(scheduler_stats)
    return summary


def run_queue(base_cfg: Config, cells: List[Dict[str, Any]],
              results_path: Optional[str] = None,
              service_mode: bool = False,
              tenants: int = 0,
              scheduler: bool = False) -> List[Dict[str, Any]]:
    """Run every cell against one AOT bank; returns (and streams) one
    result row per cell, plus a final queue-level throughput summary
    row. ``service_mode`` routes cells through service.driver.serve
    (supervised, journaled) instead of train.run. ``tenants`` E >= 2
    groups shape-compatible cells into tenant packs of up to E run as
    ONE resident *_mt program (service/tenancy.py); incompatible cells
    fall back to the serial path with a printed note. ``scheduler``
    (needs tenants >= 2) replaces the fixed FIFO packs with the
    resident fleet scheduler (service/scheduler.py): capacity-modelled
    bins whose completed/evicted slots backfill from the queue instead
    of idling — the serial and FIFO paths stay available for A/B."""
    results_path = results_path or os.path.join(base_cfg.log_dir,
                                                "queue_results.jsonl")
    os.makedirs(os.path.dirname(results_path) or ".", exist_ok=True)
    if tenants >= 2 and service_mode:
        print("[queue] --tenants ignored in --service mode (supervised "
              "cells are per-run journaled; packing is one-shot)")
        tenants = 0
    if scheduler and tenants < 2:
        print("[queue] --scheduler needs --tenants >= 2 (slots to pack); "
              "running the serial path")
        scheduler = False
    if scheduler:
        from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
            scheduler as fleet)
        items = fleet.plan_fleet(base_cfg, cells, tenants,
                                 _apply_overrides)
        n_bin = sum(1 for kind, _, _ in items if kind == "bin")
        n_fifo = sum(1 for kind, _, _ in items if kind == "fifo")
        print(f"[queue] scheduler E={tenants}: {n_bin} bins + {n_fifo} "
              f"fifo packs + {len(items) - n_bin - n_fifo} serial cells "
              f"over {len(cells)} cells")
    elif tenants >= 2:
        from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
            tenancy)
        items = [(kind, group, len(group)) for kind, group in
                 tenancy.plan_packs(base_cfg, cells, tenants,
                                    _apply_overrides)]
        n_pack = sum(1 for kind, _, _ in items if kind == "pack")
        print(f"[queue] tenancy E={tenants}: {n_pack} packs + "
              f"{len(items) - n_pack} serial cells over {len(cells)} "
              f"cells")
    else:
        items = [("serial", [cell], 1) for cell in cells]
    # queue-level event ledger (obs/events.py): cell/pack lifecycle as
    # typed records at the log root — NOT installed as the ambient
    # ledger (a service-mode cell's serve installs its own per-run one)
    qledger = None
    if base_cfg.events == "on":
        qledger = obs_events.EventLedger(
            os.path.join(base_cfg.log_dir, "events.jsonl"), run="queue",
            corr=obs_events.corr_id(f"queue:{results_path}"))
    rows: List[Dict[str, Any]] = []
    scheduler_stats: List[Dict[str, Any]] = []
    t_queue = time.perf_counter()
    with open(results_path, "a", encoding="utf-8") as out:
        for kind, group, width in items:
            if kind == "bin":
                from defending_against_backdoors_with_robust_learning_rate_tpu.service import (
                    scheduler as fleet)
                print(f"[queue] scheduler bin x{len(group)} "
                      f"(width {width}): {[c['name'] for c in group]}")
                try:
                    new_rows, stats = fleet.run_bin(base_cfg, group,
                                                    width,
                                                    qledger=qledger)
                    scheduler_stats.append(stats)
                except Exception as e:
                    # a bin that dies before its engine exists (e.g.
                    # dataset load) degrades to the serial path — the
                    # FIFO queue's pack-fallback contract, bin-shaped
                    print(f"[queue] scheduler bin FAILED "
                          f"({type(e).__name__}: {e}) — running "
                          f"members serially")
                    if qledger is not None:
                        qledger.emit("queue/pack_fallback",
                                     severity="warn",
                                     cells=[c["name"] for c in group],
                                     note=f"{type(e).__name__}: {e}")
                    new_rows = [_run_serial_cell(base_cfg, c,
                                                 service_mode)
                                for c in group]
            elif kind in ("pack", "fifo"):
                print(f"[queue] tenant pack x{len(group)}: "
                      f"{[c['name'] for c in group]}")
                if qledger is not None:
                    qledger.emit("queue/pack_start", tenants=len(group),
                                 cells=[c["name"] for c in group])
                new_rows = _run_pack_cells(base_cfg, group)
                if qledger is not None and not any(
                        "tenancy" in r for r in new_rows):
                    qledger.emit("queue/pack_fallback", severity="warn",
                                 cells=[c["name"] for c in group],
                                 note="pack degraded to serial (or "
                                      "failed) — see cell rows")
            else:
                cell = group[0]
                print(f"[queue] cell {len(rows) + 1}/{len(cells)} "
                      f"{cell['name']!r}: {cell['overrides']}")
                if qledger is not None:
                    qledger.emit("queue/cell_start", cell=cell["name"])
                new_rows = [_run_serial_cell(base_cfg, cell,
                                             service_mode)]
            for row in new_rows:
                out.write(json.dumps(row) + "\n")
                out.flush()   # a mid-queue kill keeps completed rows
                rows.append(row)
                if qledger is None:
                    continue
                slot = (row.get("tenancy") or {}).get("slot")
                if row.get("ok"):
                    qledger.emit("queue/cell_done", cell=row["cell"],
                                 slot=slot, wall_s=row.get("wall_s"))
                else:
                    qledger.emit("queue/cell_fail", severity="error",
                                 cell=row["cell"], slot=slot,
                                 error=row.get("error"))
        summary_row = _queue_summary_row(
            rows, time.perf_counter() - t_queue,
            scheduler_stats=scheduler_stats or None)
        out.write(json.dumps(summary_row) + "\n")
        out.flush()
    if qledger is not None:
        qledger.emit("queue/done", cells=summary_row["cells"],
                     ok=summary_row["ok"],
                     cells_per_hour=summary_row["cells_per_hour"])
        qledger.close()
    if base_cfg.metrics_textfile:
        # queue-level scrape state: cells/hour + completion census in
        # the same textfile-collector format the service exporter uses
        from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
            export as obs_export)
        qexp = obs_export.MetricsExporter(
            textfile=base_cfg.metrics_textfile,
            info={"queue": results_path})
        qexp.set("queue_cells_total", summary_row["cells"],
                 mtype="counter", help_text="queue cells attempted")
        qexp.set("queue_cells_ok_total", summary_row["ok"],
                 mtype="counter", help_text="queue cells completed ok")
        qexp.set("queue_cells_per_hour", summary_row["cells_per_hour"],
                 help_text="queue throughput")
        if "slot_occupancy" in summary_row:
            # fleet-level scheduler gauges (service/scheduler.py): the
            # same cells/hour number the `fleet` trajectory group gates
            qexp.set("fleet_cells_per_hour",
                     summary_row["cells_per_hour"],
                     help_text="resident fleet throughput (scheduler)")
            qexp.set("fleet_slot_occupancy",
                     summary_row["slot_occupancy"],
                     help_text="busy slot-dispatches / total "
                               "slot-dispatches across scheduler bins")
        qexp.close()
    if "slot_occupancy" in summary_row:
        # fleet bench artifact: a bare bench-result object the perf
        # trajectory gate folds into its `fleet` comparability group
        # (obs/trajectory.py; scripts/bench_trajectory.py --fold)
        import jax
        artifact = {
            "metric": "fleet_cells_per_hour",
            "value": summary_row["cells_per_hour"],
            "device": str(jax.devices()[0]),
            "bench_config": base_cfg.data,
            "dtype": base_cfg.dtype,
            "cells": summary_row["cells"], "ok": summary_row["ok"],
            "slot_occupancy": summary_row["slot_occupancy"],
            "scheduler_bins": summary_row["scheduler_bins"],
            "wall_s": summary_row["wall_s"],
        }
        apath = os.path.join(os.path.dirname(results_path) or ".",
                             "fleet_bench.json")
        with open(apath, "w", encoding="utf-8") as f:
            json.dump(artifact, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"[queue] fleet bench artifact -> {apath}")
    done = sum(r["ok"] for r in rows)
    print(f"[queue] {done}/{len(rows)} cells completed "
          f"({summary_row['cells_per_hour']} cells/hour) "
          f"-> {results_path}")
    return rows


def main(argv=None) -> int:
    # --queue (+ --service/--results) are queue-level; everything else is
    # the shared base-config flag surface (config.args_parser)
    qp = argparse.ArgumentParser(add_help=False)
    qp.add_argument("--queue", required=True,
                    help="JSON file of scenario cells (see module doc)")
    qp.add_argument("--service", action="store_true",
                    help="run cells through the supervised service driver "
                         "instead of the one-shot trainer")
    qp.add_argument("--results", default="",
                    help="queue_results.jsonl path (default: <log_dir>/)")
    qp.add_argument("--tenants", type=int, default=0,
                    help="tenant-pack width E (service/tenancy.py): >=2 "
                         "runs up to E shape-compatible cells as ONE "
                         "resident *_mt program; incompatible cells fall "
                         "back to the serial path")
    qp.add_argument("--scheduler", action="store_true",
                    help="resident fleet scheduler (service/scheduler.py"
                         "): capacity-modelled bins whose completed/"
                         "evicted slots backfill from the queue instead "
                         "of idling; needs --tenants >= 2")
    qargs, rest = qp.parse_known_args(argv)
    base_cfg = args_parser(rest)
    if base_cfg.platform:
        import jax
        jax.config.update("jax_platforms", base_cfg.platform)
    cells = load_cells(qargs.queue)
    rows = run_queue(base_cfg, cells, results_path=qargs.results or None,
                     service_mode=qargs.service, tenants=qargs.tenants,
                     scheduler=qargs.scheduler)
    return 0 if all(r["ok"] for r in rows) else 1


if __name__ == "__main__":
    raise SystemExit(main())
