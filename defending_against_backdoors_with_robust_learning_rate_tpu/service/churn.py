"""Seeded client-churn lifecycles: arrive / depart / rejoin as a pure
function of (client id, round).

`faults/model.py` models *within-round* failures: a per-round Bernoulli
dropout draw has no memory, so a "failed" client is back next round. A
production FL population churns differently — a departed client stays away
for a while and may rejoin later (FedJAX, arXiv:2108.02117, makes this
cohort process a first-class simulator primitive). This module generalizes
the fault machinery to that regime while keeping every property the faults
design bought:

- **pure function of (client, round)**: time is cut into per-client
  lifecycle phases of ``churn_period`` rounds (each client gets a seeded
  phase offset, so phase boundaries don't align across the population);
  the client is present for a whole phase iff a per-(client, phase)
  uniform draw clears ``churn_available``. Presence at any round is
  computable in O(1) with NO sequential state — which is exactly what
  makes crash recovery exact: a resumed run reconstructs the identical
  lifecycle history from the config alone.
- **replicated, collective-free**: the draw depends only on program
  constants (``churn_seed``) and traced per-slot values, so every device
  of a mesh computes the identical mask — like the fault draw, no
  collective is needed to agree on who is away (pinned by the
  ``*_churn`` specs in analysis/contracts.py).
- **participation-mask protocol**: the [m] availability bools AND into
  the same mask the aggregation rules already honor
  (faults/masking.py) — away clients are excluded arithmetically, shapes
  stay static, one compiled program serves every churn pattern.

The lifecycle key derives from ``cfg.churn_seed`` (its own `program`
config field), NOT from ``cfg.seed``: training keys are program
*arguments* (runtime provenance), while the churn stream is baked into
the traced program as a constant — and the cohort process can be re-drawn
without perturbing any training stream.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

# fold_in tag separating the churn lifecycle stream from every PRNGKey(seed)
# stream any other subsystem derives
CHURN_KEY_TAG = 0xC4A21


def churn_key(cfg):
    """Base key of the lifecycle streams (a traced constant)."""
    return jax.random.fold_in(jax.random.PRNGKey(cfg.churn_seed),
                              CHURN_KEY_TAG)


def active_slots(cfg, client_ids, rnd):
    """[m] bool — is each client present at round ``rnd``?

    ``client_ids`` is any int array of client ids (the round's sampled
    slots, or ``arange(K)`` for a population census); ``rnd`` may be a
    traced int32 scalar (the round program under churn takes the round
    index as an argument) or a Python int (host-side mirror — same jax
    ops, bit-identical answer)."""
    period = max(1, int(cfg.churn_period))
    p = jnp.float32(cfg.churn_available)
    base = churn_key(cfg)

    def one(cid):
        k_off, k_phase = jax.random.split(jax.random.fold_in(base, cid))
        # per-client phase offset de-aligns phase boundaries across the
        # population, so arrivals/departures are spread over rounds
        # instead of synchronizing at multiples of the period
        off = jax.random.randint(k_off, (), 0, period)
        phase = (rnd + off) // period
        return jax.random.uniform(jax.random.fold_in(k_phase, phase)) < p

    return jax.vmap(one)(jnp.asarray(client_ids, jnp.int32))


def active_count(cfg, rnd) -> int:
    """Host-side census: how many of the K clients are present at round
    ``rnd``. Service-driver observability only (snap cadence) — never on
    the hot path."""
    return int(np.asarray(
        jnp.sum(active_slots(cfg, jnp.arange(cfg.num_agents), int(rnd)))))


def churn_away(churn_active):
    """Scalar: sampled slots whose client is away this round (the
    Churn/Sampled_Away series)."""
    return jnp.sum((~churn_active).astype(jnp.float32))


def churn_only_scalars(churn_active, mask):
    """Faults/*-compatible scalar set for a churn-without-faults round
    (there is no fault draw to count): nothing dropped or straggled, the
    effective electorate is the churn mask."""
    return {"fault_dropped": jnp.float32(0.0),
            "fault_straggled": jnp.float32(0.0),
            "fault_voters": jnp.sum(mask.astype(jnp.float32)),
            "churn_away": churn_away(churn_active)}
