"""Deterministic chaos injection for the service driver's recovery drills.

The crash-exact recovery claim (ISSUE 6) is only worth anything if it is
*driven*: this module injects, at test-chosen rounds, exactly the failures
the r4/r5 sessions met in the wild — a process killed mid-round, a wedged
dispatch, a stalled metrics drain, a checkpoint truncated on disk, an eval
that crawls. The driver calls one hook per unit; the spec decides what
fires.

Spec grammar (``--chaos``): comma-separated ``action@round`` terms, each
optionally ``xN`` (fire on the first N attempts — wedges that survive one
retry) and/or ``:arg`` (seconds for the slow/wedge actions)::

    kill@7                 SIGKILL self right after round 7's dispatch
                           (mid-round w.r.t. the eval/checkpoint boundary)
    kill_midbuf@7          the buffered-aggregation drill (ISSUE 12):
                           same SIGKILL, declared as a MID-BUFFER kill —
                           the driver refuses the spec unless --agg_mode
                           buffered is on, and the recovery acceptance is
                           that the carried buffer/staleness state rides
                           the digest-verified checkpoint back byte-
                           exactly (pick a round where the commit cadence
                           leaves the buffer non-empty at the preceding
                           checkpoint, e.g. K=2m with an odd --snap)
    wedge@3                dispatch attempt 1 of round 3 raises a
    wedge@3x2              transient UNAVAILABLE ChaosError (x2: first two
                           attempts — exercises repeated backoff)
    poison@5               round 5's dispatch raises a deterministic
                           (non-retryable) error on every attempt
    poison_eval@4          round 4's eval raises deterministically
                           (drives the skip-eval degradation)
    slow_eval@2:0.4        round 2's eval sleeps 0.4s (deadline/slow-unit
                           classification)
    wedge_drain@6:0.8      a 0.8s blocker is queued on the metrics drain
                           at round 6 (the checkpoint flush then times
                           out -> wedged -> sync-metrics degradation)
    corrupt_ckpt@4         round 4's just-saved checkpoint gets its bytes
                           flipped on disk (digest-verified restore must
                           fall back to the previous one)
    nan@5                  one NaN written into the committed params right
    nan@5x2                after round 5's dispatch — the deterministic
                           stand-in for a bf16 NaN burst; x2 also poisons
                           the health ladder's DISCARD re-dispatch, so
                           recovery must escalate to ROLLBACK (ISSUE 14)
    spike@3:25             round 3's committed delta scaled x25 — a finite
                           magnitude burst tripping the norm-spike
                           sentinel (health/sentinel.py)
    bank_corrupt@0         flip bytes in the client bank's 0th
                           indices-*.bin shard BEFORE the engine opens it
                           (--bank_verify must fail loudly naming the
                           shard)
    kill_recover@4         SIGKILL in the window where the health ladder
                           has RECORDED a rollback/quarantine for round 4
                           but its crash-exact re-entry has not finished —
                           the resumed process must resume the LADDER
                           (health_state.json), not the failure

Injections persist their fire counts in a small state file (atomic
rewrite) so a ``kill`` does NOT re-fire after the resumed process replays
its round — the whole point is to crash once and then observe a clean
recovery. ``kill`` marks its state BEFORE raising SIGKILL for the same
reason.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import signal
import time
from typing import Dict, List, Optional

from defending_against_backdoors_with_robust_learning_rate_tpu.obs import (
    events as obs_events)
from defending_against_backdoors_with_robust_learning_rate_tpu.utils.checkpoint import (
    atomic_write_text)

ACTIONS = ("kill", "kill_midbuf", "wedge", "poison", "poison_eval",
           "slow_eval", "wedge_drain", "corrupt_ckpt",
           "nan", "spike", "bank_corrupt", "kill_recover")

_TERM_RE = re.compile(
    r"^(?P<action>[a-z_]+)@(?P<round>\d+)"
    r"(?:x(?P<count>\d+))?(?::(?P<arg>[0-9.]+))?$")


class ChaosError(RuntimeError):
    """Injected failure. The message carries the transient/poisoned
    signature the supervisor classifies on."""


@dataclasses.dataclass
class Injection:
    action: str
    rnd: int
    count: int = 1        # how many times it fires (attempts, for wedges)
    arg: float = 0.0      # seconds for slow/wedge actions

    @property
    def key(self) -> str:
        return f"{self.action}@{self.rnd}"


def parse_spec(spec: str) -> List[Injection]:
    out: List[Injection] = []
    for term in filter(None, (t.strip() for t in (spec or "").split(","))):
        m = _TERM_RE.match(term)
        if not m or m.group("action") not in ACTIONS:
            raise ValueError(
                f"bad chaos term {term!r}; expected action@round[xN][:arg] "
                f"with action in {ACTIONS}")
        out.append(Injection(m.group("action"), int(m.group("round")),
                             int(m.group("count") or 1),
                             float(m.group("arg") or 0.0)))
    return out


class Chaos:
    """The injector: holds the parsed spec + persisted fire counts."""

    def __init__(self, spec: str, state_path: Optional[str] = None):
        self.injections = parse_spec(spec)
        self.state_path = state_path
        self._fired: Dict[str, int] = {}
        if state_path and os.path.exists(state_path):
            try:
                with open(state_path, encoding="utf-8") as f:
                    self._fired = {k: int(v)
                                   for k, v in json.load(f).items()}
            except (OSError, ValueError):
                self._fired = {}

    @property
    def active(self) -> bool:
        return bool(self.injections)

    def _mark(self, inj: Injection) -> None:
        self._fired[inj.key] = self._fired.get(inj.key, 0) + 1
        if self.state_path:
            atomic_write_text(self.state_path, json.dumps(self._fired))
        # one typed ledger record per fired injection — except the
        # SIGKILL family: a dying process writes no last word, and the
        # kill-vs-no-kill twin drills demand byte-identical ledgers
        # (obs/events.py module doc). Fire counts persist, so a
        # crash-resumed replay never re-emits.
        if obs_events.chaos_ledgered(inj.action):
            obs_events.emit(f"chaos/{inj.action}", severity="warn",
                            round=inj.rnd, fired=self._fired[inj.key])

    def _due(self, action: str, rnd: int) -> Optional[Injection]:
        for inj in self.injections:
            if (inj.action == action and inj.rnd == rnd
                    and self._fired.get(inj.key, 0) < inj.count):
                return inj
        return None

    # ------------------------------------------------------------- hooks

    def on_dispatch(self, rnd: int) -> None:
        """Called before round ``rnd``'s dispatch (every attempt)."""
        inj = self._due("wedge", rnd)
        if inj is not None:
            self._mark(inj)
            if inj.arg > 0:
                time.sleep(inj.arg)
            raise ChaosError(
                f"UNAVAILABLE: injected wedged dispatch at round {rnd} "
                f"(chaos {inj.key})")
        inj = self._due("poison", rnd)
        if inj is not None:
            # NOT marked exhausted per attempt beyond count: a poisoned
            # unit is deterministic — every retry reproduces it
            raise ChaosError(
                f"injected deterministic failure at round {rnd} "
                f"(chaos {inj.key})")

    def maybe_kill(self, rnd: int) -> None:
        """Called after round ``rnd``'s dispatch: kill -9 mid-round. Marks
        state FIRST (the next life must not re-fire while replaying).
        ``kill_midbuf`` is the buffered-aggregation variant — same kill,
        but the driver has already validated the mode (serve refuses the
        spec on a sync run: a 'mid-buffer' drill without a buffer would
        silently test nothing)."""
        inj = self._due("kill", rnd) or self._due("kill_midbuf", rnd)
        if inj is None:
            return
        self._mark(inj)
        print(f"[chaos] kill -9 after round {rnd}'s dispatch "
              f"({inj.key})", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    def requires_buffered(self) -> bool:
        """Whether the spec contains a buffered-mode-only drill."""
        return any(inj.action == "kill_midbuf" for inj in self.injections)

    def nan_due(self, rnd: int) -> bool:
        """Numerics drill (ISSUE 14): whether a NaN should be written
        into round ``rnd``'s committed params (the driver performs the
        write — health/monitor.poison_params). Fire counts persist, so
        the health ladder's DISCARD re-dispatch only re-meets the fault
        when the spec says xN > 1, and a post-ROLLBACK replay of an
        exhausted injection runs clean — recovery is observable as a
        healthy replay, exactly like the kill drills."""
        inj = self._due("nan", rnd)
        if inj is None:
            return False
        self._mark(inj)
        print(f"[chaos] NaN written into round {rnd}'s params "
              f"({inj.key})", flush=True)
        return True

    def spike_due(self, rnd: int) -> float:
        """Numerics drill: the factor round ``rnd``'s committed delta
        should be scaled by (0.0 = no injection; default x20 trips the
        default --health_spike_factor of 10 with margin)."""
        inj = self._due("spike", rnd)
        if inj is None:
            return 0.0
        self._mark(inj)
        factor = inj.arg or 20.0
        print(f"[chaos] round {rnd}'s update scaled x{factor:g} "
              f"({inj.key})", flush=True)
        return factor

    def corrupt_bank(self, bank_root: str, dataset: str = "") -> bool:
        """Data-plane drill: flip bytes mid-file in the N-th
        ``indices-*.bin`` shard found under ``bank_root`` (N = the
        term's @round slot, reused as a shard index). ``dataset`` scopes
        the walk to bank subdirectories named ``<dataset>-<key>`` (the
        data/registry layout) — a shared persistent client_banks root
        can hold OTHER experiments' banks, and a drill must never
        damage data the drilled run will not even open. Runs BEFORE the
        engine opens the bank, so a --bank_verify open must detect it
        and name the shard. Returns True when anything fired."""
        fired = False
        for inj in self.injections:
            if (inj.action != "bank_corrupt"
                    or self._fired.get(inj.key, 0) >= inj.count):
                continue
            shards = sorted(
                os.path.join(base, name)
                for base, _dirs, files in os.walk(bank_root)
                for name in files
                if name.startswith("indices-") and name.endswith(".bin")
                and (not dataset or os.path.abspath(base) ==
                     os.path.abspath(bank_root)
                     or os.path.basename(base).startswith(f"{dataset}-")))
            if not shards:
                continue
            victim = shards[inj.rnd % len(shards)]
            size = os.path.getsize(victim)
            with open(victim, "r+b") as f:
                f.seek(max(0, size // 2))
                f.write(b"\xde\xad\xbe\xef")
            self._mark(inj)
            print(f"[chaos] corrupted bank shard {victim} ({inj.key})",
                  flush=True)
            fired = True
        return fired

    def maybe_kill_recover(self, rnd: int) -> None:
        """Kill-mid-rollback drill (ISSUE 14): SIGKILL in the window the
        health ladder just RECORDED a rollback/quarantine for round
        ``rnd`` (state saved, engine closed) but the crash-exact
        re-entry has not completed — the one crash window the recovery
        ladder adds. The resumed process must pick the ladder up from
        health_state.json, not re-meet the original failure. Marks state
        first, like every kill."""
        inj = self._due("kill_recover", rnd)
        if inj is None:
            return
        self._mark(inj)
        print(f"[chaos] kill -9 mid-recovery of round {rnd} "
              f"({inj.key})", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)

    def on_eval(self, rnd: int) -> None:
        inj = self._due("slow_eval", rnd)
        if inj is not None:
            self._mark(inj)
            time.sleep(inj.arg or 0.5)
        inj = self._due("poison_eval", rnd)
        if inj is not None:
            raise ChaosError(
                f"injected deterministic eval failure at round {rnd} "
                f"(chaos {inj.key})")

    def drain_blocker_secs(self, rnd: int) -> Optional[float]:
        """Seconds a drain blocker should sleep at round ``rnd`` (the
        driver submits the sleeper — this module never touches the drain
        directly), or None."""
        inj = self._due("wedge_drain", rnd)
        if inj is None:
            return None
        self._mark(inj)
        return inj.arg or 0.5

    def corrupt_checkpoint(self, ckpt_dir: str, rnd: int) -> bool:
        """After the round-``rnd`` checkpoint save: flip bytes in the
        newest checkpoint's largest file, leaving the digest sidecar in
        place — the restore path must *detect* the corruption (digest
        mismatch) and fall back. Returns True when it fired."""
        inj = self._due("corrupt_ckpt", rnd)
        if inj is None:
            return False
        self._mark(inj)
        from defending_against_backdoors_with_robust_learning_rate_tpu.utils import (
            checkpoint as ckpt)
        rounds = ckpt.saved_rounds(ckpt_dir)
        if not rounds:
            return False
        path = os.path.join(os.path.abspath(ckpt_dir),
                            f"round_{rounds[-1]:06d}")
        victim, vsize = None, -1
        for base, _dirs, files in os.walk(path):
            for name in files:
                fp = os.path.join(base, name)
                size = os.path.getsize(fp)
                if size > vsize:
                    victim, vsize = fp, size
        if victim is None:
            return False
        with open(victim, "r+b") as f:
            f.seek(max(0, vsize // 2))
            f.write(b"\xde\xad\xbe\xef")
        print(f"[chaos] corrupted checkpoint file {victim} "
              f"({inj.key})", flush=True)
        return True
